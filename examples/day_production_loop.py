"""Production day/pass loop: DayRunner over day- and hour-addressed data.

What the reference's online-learning deployment does all day: for each
pass (here one per hour) load that split's files, register its keys,
train, write a delta checkpoint + xbox serving export; at day end,
shrink (decay/evict cold features) and write the day base. Kill the
process anywhere and rerun — the done-file protocol resumes from the
last completed pass.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/day_production_loop.py
"""

import os
import sys

# Runnable from anywhere: put the repo root (parent of examples/) on the
# path so `python examples/<name>.py` works without installing.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

import jax

from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.day_runner import DayRunner

SLOTS = ("user", "item")


def write_day(root: str, day: str, hours) -> None:
    rng = np.random.default_rng(int(day))
    for h in hours:
        d = os.path.join(root, day, f"{h:02d}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "part-00000"), "w") as f:
            for _ in range(256):
                feats = {s: rng.integers(1, 500, rng.integers(1, 3))
                         for s in SLOTS}
                label = int(rng.random() < 0.2)
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{label} {toks}\n")


def main() -> None:
    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=64)
    trainer = CTRTrainer(
        DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(32,)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.05), mesh=mesh,
        config=TrainerConfig(auc_num_buckets=1 << 10),
        # The production tier: the persistent table lives in device HBM;
        # passes build/write back on-device (AIBox thesis).
        store_factory=lambda cfg: DeviceFeatureStore(cfg, mesh=mesh))
    trainer.init(seed=0)

    with tempfile.TemporaryDirectory() as tmp:
        data_root = os.path.join(tmp, "data")
        out_root = os.path.join(tmp, "output")
        days = ["20260730", "20260731"]
        for day in days:
            write_day(data_root, day, hours=[0, 1, 2])

        runner = DayRunner(
            trainer, feed, out_root, data_root=data_root,
            split_interval=60, split_per_pass=1, hours=[0, 1, 2],
            pipeline_passes=True,   # overlap pass k+1 load with pass k
            save_xbox=True,         # serving export every pass
            min_show_shrink=0.0)    # day-end decay (no eviction here)
        stats = runner.run_days(days, resume=True)
        for day in days:
            for i, s in enumerate(stats[day]):
                print(f"{day} pass {i}: loss={s['loss']:.4f} "
                      f"auc={s['auc']:.4f}")

        # The checkpoint protocol wrote per-pass deltas + a day base.
        recs = runner.ckpt.records()
        print("checkpoint records:",
              [(r.day, r.pass_id) for r in recs][:8])
        base = os.path.join(out_root, days[-1], "0", "emb.base.npz")
        assert os.path.exists(base), base
        print("day base:", base)
        print(f"store holds {trainer.engine.store.num_features} features")


if __name__ == "__main__":
    main()
