"""Migrate a reference job's proto-text configs and train with them.

A PaddleBox job ships three text configs: the reader (DataFeedDesc),
the sparse table/accessor (TableParameter), and the distributed
strategy. This example loads all three AS-IS with the proto-text
loaders and runs a training pass — the literal migration path
MIGRATION.md describes.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/migrate_reference_configs.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddlebox_tpu.data import (Dataset, data_feed_config_from_desc,
                                table_config_from_desc)
from paddlebox_tpu.fleet.strategy import DistributedStrategy
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

FEED_DESC = """
name: "MultiSlotDataFeed"
batch_size: 64
multi_slot_desc {
  slots { name: "user" type: "uint64" is_used: true }
  slots { name: "item" type: "uint64" is_used: true }
}
"""

TABLE_DESC = """
table_class: "MemorySparseTable"
accessor {
  accessor_class: "CtrCommonAccessor"
  embedx_dim: 8
  ctr_accessor_param { show_click_decay_rate: 0.98 }
  embedx_sgd_param {
    name: "SparseAdaGradSGDRule"
    adagrad { learning_rate: 0.1 initial_g2sum: 3.0 }
  }
}
"""

STRATEGY_DESC = """
amp: false
hybrid_configs { dp_degree: -1 }
"""


def main() -> None:
    feed, feed_extras = data_feed_config_from_desc(FEED_DESC)
    table, table_extras = table_config_from_desc(TABLE_DESC)
    strategy = DistributedStrategy.from_proto_text(STRATEGY_DESC)
    import jax
    topo = strategy.topology(world_size=len(jax.devices()))
    mesh = build_mesh(topo)
    print(f"feed: {len(feed.sparse_slots)} slots batch={feed.batch_size}; "
          f"table: dim={table.dim} opt={table.optimizer} "
          f"lr={table.learning_rate}; mesh dp={topo.dp}")

    model = DeepFM(slot_names=tuple(s.name for s in feed.sparse_slots),
                   emb_dim=table.dim, hidden=(16,))
    tr = CTRTrainer(model, feed, table, mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10))
    tr.init(seed=0)

    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as tmpdir:
        p = os.path.join(tmpdir, "part")
        with open(p, "w") as f:
            for _ in range(512):
                u, i = rng.integers(1, 200, 2)
                label = int(((int(u) % 2) == (int(i) % 2))
                            == (rng.random() < 0.85))
                f.write(f"{label} user:{u} item:{i}\n")
        losses = []
        for _ in range(4):
            ds = Dataset(feed, num_reader_threads=1)
            ds.set_filelist([p])
            ds.load_into_memory()
            stats = tr.train_pass(ds)
            losses.append(stats["loss"])
        print(f"losses {losses[0]:.4f} -> {losses[-1]:.4f} "
              f"auc={stats['auc']:.4f} overflow={stats['lookup_overflow']}")
        assert losses[-1] < losses[0]
        assert stats["lookup_overflow"] == 0
    print("migrated-config training OK")


if __name__ == "__main__":
    main()
