"""DeepFM CTR: the full production lifecycle in one script.

Generate svm-format click logs -> threaded columnar load -> pass-based
training (feed_pass key registration, one jitted pull/fwd-bwd/push step
per batch, device AUC) -> xbox serving export -> online predictor.

Runs anywhere; on a dev box force the virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/ctr_deepfm_end_to_end.py
"""

import os
import sys

# Runnable from anywhere: put the repo root (parent of examples/) on the
# path so `python examples/<name>.py` works without installing.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

import jax

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.serving import (CTRPredictor, load_delta_update,
                                   load_xbox_model)
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item", "context")


def write_logs(path: str, n_rows: int, seed: int) -> str:
    """Plain text, one sample per line: `label slot:feasign ...` —
    the svm-format the native C++ parser reads."""
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for _ in range(n_rows):
            feats = {s: rng.integers(1, 5000, rng.integers(1, 4))
                     for s in SLOTS}
            # Make some features genuinely predictive so AUC moves.
            signal = np.mean([(int(v) % 7 == 0)
                              for vs in feats.values() for v in vs])
            label = int(rng.random() < 0.08 + 0.7 * signal)
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")
    return path


def main() -> None:
    ndev = len(jax.devices())
    mesh = build_mesh(HybridTopology(dp=ndev))

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=2.0) for s in SLOTS),
        batch_size=256)
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(64, 32))
    trainer = CTRTrainer(
        model, feed, TableConfig(name="emb", dim=8, learning_rate=0.2),
        mesh=mesh,
        config=TrainerConfig(auc_num_buckets=1 << 12,
                             dense_learning_rate=3e-3))
    trainer.init(seed=0)

    with tempfile.TemporaryDirectory() as tmp:
        files = [write_logs(os.path.join(tmp, f"part-{i}"), 2048, i)
                 for i in range(2)]

        for epoch in range(6):
            ds = Dataset(feed, num_reader_threads=2)
            ds.set_filelist(files)
            ds.load_into_memory()
            stats = trainer.train_pass(ds)
            print(f"pass {epoch}: loss={stats['loss']:.4f} "
                  f"auc={stats['auc']:.4f}")

        # Per-pass online serving export: keys + emb + w only (xbox).
        n = trainer.engine.store.save_xbox(tmp)
        print(f"xbox export: {n} features")

        keys, emb, w = load_xbox_model(tmp, table="emb")
        pred = CTRPredictor(model, feed, keys, emb, w, trainer.params)
        ds = Dataset(feed, num_reader_threads=1)
        ds.set_filelist(files[:1])
        ds.load_into_memory()
        batch = next(ds.batches_sharded(1))
        probs = pred.predict(batch)
        print(f"served {probs.shape[0]} predictions; "
              f"mean CTR {probs.mean():.4f}")
        assert np.isfinite(probs).all()

        # Real-time model update: train one more pass, export only the
        # touched keys (delta), land it on the LIVE predictor — no cold
        # reload (the reference's online patch-model flow).
        trainer.engine.store.save_base(os.path.join(tmp, "b0"))
        ds = Dataset(feed, num_reader_threads=2)
        ds.set_filelist(files)
        ds.load_into_memory()
        trainer.train_pass(ds)
        delta_dir = os.path.join(tmp, "delta")
        trainer.engine.store.save_delta(delta_dir)
        dk, de, dw = load_delta_update(delta_dir, table="emb")
        n_new = pred.apply_update(dk, de, dw, dense_params=trainer.params)
        probs2 = pred.predict(batch)
        print(f"live update: {dk.shape[0]} keys ({n_new} new); mean CTR "
              f"{probs.mean():.4f} -> {probs2.mean():.4f}")


if __name__ == "__main__":
    main()
