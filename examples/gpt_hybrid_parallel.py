"""GPT training with hybrid parallelism: dp x mp x pp on one mesh.

The dense/LLM side of the framework (reference role: Fleet hybrid
parallel — tensor + pipeline + data parallel). Shardings are
annotations; XLA inserts the collectives. Pipeline runs the 1F1B
schedule (the reference's default) with bounded activation memory.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/gpt_hybrid_parallel.py
"""

import os
import sys

# Runnable from anywhere: put the repo root (parent of examples/) on the
# path so `python examples/<name>.py` works without installing.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp
import optax

from paddlebox_tpu.models.gpt import (GPTConfig, init_gpt,
                                      make_gpt_train_step)
from paddlebox_tpu.parallel import HybridTopology, build_mesh


def main() -> None:
    ndev = len(jax.devices())
    assert ndev >= 8, ("run with XLA_FLAGS="
                       "--xla_force_host_platform_device_count=8")
    topo = HybridTopology(dp=2, mp=2, pp=2)
    mesh = build_mesh(topo)
    print("mesh:", dict(mesh.shape))

    cfg = GPTConfig(vocab_size=512, d_model=64, n_heads=4, n_layers=4,
                    d_ff=128, max_seq_len=64)
    params, specs = init_gpt(jax.random.PRNGKey(0), cfg, pp_stages=2)
    opt = optax.adam(1e-3)
    step = make_gpt_train_step(cfg, mesh, specs, opt,
                               num_microbatches=4, schedule="1f1b")
    opt_state = opt.init(params)

    rng = np.random.default_rng(0)
    # A learnable toy task: next token = (token + 1) % vocab.
    tokens = jnp.asarray(rng.integers(0, 511, (8, 64)), jnp.int32)
    targets = (tokens + 1) % cfg.vocab_size

    losses = []
    for i in range(10):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
        if i % 3 == 0:
            print(f"step {i}: loss {losses[-1]:.4f}")
    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "1F1B hybrid step failed to learn"


if __name__ == "__main__":
    main()
