"""Graph learning end-to-end: metapath walks → skip-gram embeddings.

The graph engine (reference role: GPU graph engine + GraphDataGenerator,
heter_ps/graph_gpu_wrapper.h) on a bipartite user–item graph: typed
nodes, metapath walks (user→item→user), degree-aware negatives, and
node-feature pulls — trained into embeddings whose user/item clusters
separate.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/graph_deepwalk.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from paddlebox_tpu.graph import (GraphDataGenerator, GraphGenConfig,
                                 GraphTable)


def main() -> None:
    rng = np.random.default_rng(0)
    n_users, n_items = 32, 32
    users = np.arange(n_users)
    items = np.arange(n_users, n_users + n_items)
    n = n_users + n_items

    # Two co-click communities: users 0-15 <-> items 0-15, rest <-> rest.
    def edges(u_lo, u_hi, i_lo, i_hi, k=6):
        src = np.repeat(np.arange(u_lo, u_hi), k)
        dst = rng.integers(n_users + i_lo, n_users + i_hi, src.size)
        return src, dst

    u2i = tuple(np.concatenate(p) for p in zip(
        edges(0, 16, 0, 16), edges(16, 32, 16, 32)))
    i2u = (u2i[1], u2i[0])

    table = GraphTable()
    table.add_edges("u2i", *u2i, num_nodes=n)
    table.add_edges("i2u", *i2u, num_nodes=n)
    table.set_node_types(np.concatenate(
        [np.zeros(n_users, np.int32), np.ones(n_items, np.int32)]))
    table.set_node_feat("x", rng.normal(size=(n, 4)).astype(np.float32))

    gen = GraphDataGenerator(
        table, "u2i",
        GraphGenConfig(walk_len=6, window=2, num_neg=4, batch_walks=32,
                       metapath=("u2i", "i2u"), start_type=0,
                       degree_negatives=True, feat_name="x"))

    emb = jnp.asarray(rng.normal(0, 0.1, (n, 16)), jnp.float32)

    @jax.jit
    def step(emb, c, x, negs, mask):
        def loss_fn(emb):
            pos = jnp.sum(emb[c] * emb[x], -1)
            neg = jnp.einsum("pd,pnd->pn", emb[c], emb[negs])
            l = jax.nn.softplus(-pos) + jax.nn.softplus(neg).sum(-1)
            return jnp.sum(l * mask) / jnp.maximum(mask.sum(), 1)
        loss, g = jax.value_and_grad(loss_fn)(emb)
        return emb - 0.5 * g, loss

    loss = None
    for batch in gen.batches(epochs=60):
        assert batch["center_feats"].shape[-1] == 4  # feature pulls ride along
        emb, loss = step(emb, batch["centers"], batch["contexts"],
                         batch["negatives"], batch["mask"])
    print(f"final loss: {float(loss):.4f}")

    e = np.asarray(emb)
    e = e / np.linalg.norm(e, axis=1, keepdims=True)
    sims = e @ e.T
    intra = (sims[:16, :16].mean() + sims[16:32, 16:32].mean()) / 2
    inter = sims[:16, 16:32].mean()
    print(f"intra-community sim {intra:.3f} vs inter {inter:.3f}")
    assert intra > inter + 0.05, "communities failed to separate"
    print("OK")


if __name__ == "__main__":
    main()
