"""The full online CTR production loop: train → export → serve → patch.

The reference's headline flow (README.md:48 "real-time model update"):
a trainer publishes per-pass xbox exports; an online predict service
loads the base, answers requests over the wire, and absorbs delta
exports live — requests before and after a patch see different models,
and the patched service matches a cold rebuild from the full export.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/online_serving.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

from paddlebox_tpu.data import Dataset, DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.serving import (CTRPredictor, PredictClient,
                                   PredictServer, load_xbox_model)
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item")


def train_pass(tr, feed, tmpdir, rng, lo, hi, name):
    path = os.path.join(tmpdir, name)
    with open(path, "w") as f:
        for _ in range(256):
            toks = " ".join(f"{s}:{rng.integers(lo, hi)}" for s in SLOTS)
            f.write(f"{int(rng.random() < 0.3)} {toks}\n")
    ds = Dataset(feed, num_reader_threads=1)
    ds.set_filelist([path])
    ds.load_into_memory()
    return tr.train_pass(ds)


def main() -> None:
    rng = np.random.default_rng(0)
    mesh = build_mesh(HybridTopology(dp=8))
    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.0) for s in SLOTS),
        batch_size=64)
    model = DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(16,))
    tr = CTRTrainer(model, feed,
                    TableConfig(name="emb", dim=8, learning_rate=0.1),
                    mesh=mesh,
                    config=TrainerConfig(auc_num_buckets=1 << 10))
    tr.init(seed=0)

    with tempfile.TemporaryDirectory() as tmpdir:
        # Pass 1 trains the base model; export + stand up the service.
        stats = train_pass(tr, feed, tmpdir, rng, 1, 400, "pass1")
        base = os.path.join(tmpdir, "xbox_base")
        tr.engine.store.save_xbox(base)
        keys, emb, w = load_xbox_model(base, table="emb")
        pred = CTRPredictor(model, feed, keys, emb, w,
                            jax.device_get(tr.params),
                            compute_dtype="float32")
        server = PredictServer("127.0.0.1:0", pred)
        cli = PredictClient(server.endpoint)
        try:
            queries = ["0 " + " ".join(
                f"{s}:{rng.integers(200, 600)}" for s in SLOTS)
                for _ in range(32)]
            before = cli.predict(queries)
            print(f"pass1 loss={stats['loss']:.4f}  "
                  f"serving {cli.stats()['keys']} keys  "
                  f"p(before)={before[:3].round(4).tolist()}")

            # Pass 2 trains on NEW traffic; its delta patches the live
            # service without a restart.
            train_pass(tr, feed, tmpdir, rng, 300, 700, "pass2")
            delta = os.path.join(tmpdir, "delta")
            tr.engine.store.save_delta(delta)
            n_new = cli.apply_delta(delta, table="emb")
            after = cli.predict(queries)
            print(f"delta patched {n_new} new keys in place  "
                  f"p(after)={after[:3].round(4).tolist()}")
            assert not np.allclose(before, after), \
                "patch must change served answers on patched traffic"

            # Pass 3 flows to serving with NO RPC at all: the day
            # loop's donefile protocol publishes the delta and the
            # replica's publisher thread hot-swaps it (the
            # zero-downtime path a real fleet runs on).
            from paddlebox_tpu.checkpoint.protocol import \
                CheckpointProtocol
            from paddlebox_tpu.serving import DonefilePublisher
            root = os.path.join(tmpdir, "ckpt")
            proto = CheckpointProtocol(root)
            pub = DonefilePublisher(pred, root, table="emb",
                                    poll_s=0.05)
            pub.start()
            try:
                train_pass(tr, feed, tmpdir, rng, 500, 900, "pass3")
                mdir = proto.model_dir("day0", 1)
                tr.engine.store.save_delta(mdir)
                proto.publish("day0", 1)
                import time as _time
                deadline = _time.time() + 10
                while pub.applied < 1 and _time.time() < deadline:
                    _time.sleep(0.02)
                assert pub.applied == 1, "publisher must hot-swap"
                swapped = cli.predict(queries)
                print(f"donefile hot-swap applied "
                      f"(stats hotswap_applied="
                      f"{cli.stats()['hotswap_applied']})  "
                      f"p(swapped)={swapped[:3].round(4).tolist()}")
                assert not np.allclose(after, swapped), \
                    "hot-swap must change served answers"
            finally:
                pub.stop()

            # Phase 4 — the streaming online-learning loop (ONLINE.md):
            # a stream trainer, the donefile publisher, and a FLEET
            # replica in one process tree. A fresh event lands in the
            # log dir, becomes an incremental pass, publishes a delta,
            # the replica's publisher applies it — and the event's key
            # must be servable through the fleet router within the
            # freshness budget.
            import time as _time

            from paddlebox_tpu.core import flags as flagmod
            from paddlebox_tpu.serving import DonefilePublisher as _DP
            from paddlebox_tpu.serving.router import FleetRouter
            from paddlebox_tpu.stream import StreamRunner

            FRESH_BUDGET_S = 20.0
            pub2 = _DP(pred, root, table="emb", poll_s=0.05)
            pub2.start()
            router = FleetRouter(replicas=[server.endpoint])
            rcli = PredictClient(router.endpoint)
            prev_flags = {k: flagmod.flag(k) for k in
                          ("stream_pass_events", "stream_pass_window_s")}
            try:
                flagmod.set_flags({"stream_pass_events": 256,
                                   "stream_pass_window_s": 0.0})

                def ack_applied(day, pass_id):
                    # "Servable" = the live replica's publisher has
                    # APPLIED the delta, not merely seen it published.
                    want = pub2.applied + 1
                    deadline = _time.time() + 30.0
                    while pub2.applied < want and _time.time() < deadline:
                        _time.sleep(0.01)
                    assert pub2.applied >= want, \
                        "replica never applied the streamed delta"
                    return _time.time()

                runner = StreamRunner(tr, feed, root,
                                      log_dir=os.path.join(tmpdir,
                                                           "events"),
                                      shuffle=False,
                                      num_reader_threads=1,
                                      ack_fn=ack_applied)
                os.makedirs(os.path.join(tmpdir, "events"), exist_ok=True)
                # A burst of fresh traffic around a brand-new key range.
                fresh_q = ["0 " + " ".join(f"{s}:{5000 + i}"
                                           for s in SLOTS)
                           for i in range(4)]
                before_fresh = rcli.predict(fresh_q)
                lines = []
                for _ in range(256):
                    toks = " ".join(
                        f"{s}:{rng.integers(5000, 5050)}" for s in SLOTS)
                    lines.append(f"{int(rng.random() < 0.4)} {toks}")
                tmp_ev = os.path.join(tmpdir, "events", ".burst.log.tmp")
                with open(tmp_ev, "w") as f:
                    f.write("\n".join(lines) + "\n")
                os.replace(tmp_ev,
                           os.path.join(tmpdir, "events", "burst.log"))
                t_event = _time.time()
                trained = runner.poll_once(flush=True)
                servable_s = _time.time() - t_event
                assert trained == 1, "the burst must carve one pass"
                after_fresh = rcli.predict(fresh_q)
                assert not np.allclose(before_fresh, after_fresh), \
                    "fresh keys must change served answers post-swap"
                assert servable_s < FRESH_BUDGET_S, (
                    f"event->servable {servable_s:.1f}s blew the "
                    f"{FRESH_BUDGET_S:.0f}s budget")
                q = runner.freshness_quantiles()
                print(f"streamed pass servable through the fleet in "
                      f"{servable_s * 1e3:.0f} ms "
                      f"(digest p99={q['p99']:.0f} ms)  "
                      f"p(fresh)={after_fresh[:3].round(4).tolist()}")
            finally:
                flagmod.set_flags(prev_flags)
                rcli.close()
                router.stop()
                pub2.stop()

            # Phase 5 — the model-quality observatory (OBSERVABILITY.md
            # "Model quality & data health"): served traffic is sampled
            # by request id, labels arrive late (the stream tier's
            # event log catching up) and join against the bounded
            # pending window, and a calibration-shifted burst must trip
            # quality/alarms/copc on the replica — visible in ONE
            # `fleet_top --once --json` scrape beside the systems
            # columns.
            import contextlib
            import io
            import json as _json

            prev_q = {k: flagmod.flag(k) for k in
                      ("quality_sample_rate", "quality_min_events",
                       "quality_copc_band")}
            try:
                flagmod.set_flags({"quality_sample_rate": 1.0,
                                   "quality_min_events": 64,
                                   "quality_copc_band": 0.3})
                shifted = ["0 " + " ".join(
                    f"{s}:{rng.integers(1, 400)}" for s in SLOTS)
                    for _ in range(16)]
                for r in range(8):
                    rid = f"req-{r}"
                    cli.predict(shifted, rid=rid)
                    # The late label feed reports every served request
                    # clicked — a hard calibration shift vs the model's
                    # predicted CTR.
                    cli.send_labels(rid, [1.0] * len(shifted))
                st = cli.stats()
                assert st["quality_alarms"] >= 1, \
                    "calibration-shifted burst must trip a copc alarm"
                from tools import fleet_top
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    rc = fleet_top.main(["--targets",
                                         f"rep={server.endpoint}",
                                         "--once", "--json"])
                assert rc == 0, "fleet_top scrape must reach the replica"
                row = _json.loads(buf.getvalue())["summary"][0]
                assert row.get("quality_alarms", 0) >= 1, row
                print(f"calibration-shift alarm visible in one fleet_top "
                      f"scrape (copc={row.get('copc')}, "
                      f"alarms={row['quality_alarms']})")
            finally:
                flagmod.set_flags(prev_q)
        finally:
            cli.stop_server()
            cli.close()
            server.stop()
    print("online serving loop OK")


if __name__ == "__main__":
    main()
