"""CTR training against a remote parameter-server cluster with SSD tiers.

The multi-node deployment shape (reference role: CPU PS + SSD table
under BoxPS): sharded PS servers hold the persistent feature store —
each shard bounded in RAM with disk overflow — and the trainer's pass
engine does BuildPull / EndPass write-back over the typed wire.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/remote_ps_tiered.py
"""

import os
import sys

# Runnable from anywhere: put the repo root (parent of examples/) on the
# path so `python examples/<name>.py` works without installing.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile

import numpy as np

import jax

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.distributed.ps import PSBackedStore, start_local_cluster
from paddlebox_tpu.embedding import TableConfig
from paddlebox_tpu.embedding.ssd_tier import TieredFeatureStore
from paddlebox_tpu.models import DeepFM
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item", "ctx")


def main() -> None:
    with tempfile.TemporaryDirectory() as tmp:
        _run(tmp)


def _run(tmp: str) -> None:
    cfg = TableConfig(name="emb", dim=8, learning_rate=0.05)

    # 2 PS shards, each keeping at most 500 hot rows in RAM.
    def tiered(c, shard_idx):
        return TieredFeatureStore(c, os.path.join(tmp, f"ssd{shard_idx}"),
                                  max_ram_features=500, seed=shard_idx)

    servers, client = start_local_cluster(2, {"emb": cfg},
                                          store_factory=tiered)
    try:
        mesh = build_mesh(HybridTopology(dp=len(jax.devices())))
        feed = DataFeedConfig(
            slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
            batch_size=128)
        trainer = CTRTrainer(
            DeepFM(slot_names=SLOTS, emb_dim=8, hidden=(32,)), feed, cfg,
            mesh=mesh, config=TrainerConfig(auc_num_buckets=1 << 10),
            store_factory=lambda c: PSBackedStore(client, "emb"))
        trainer.init(seed=0)

        rng = np.random.default_rng(0)
        path = os.path.join(tmp, "part-0")
        with open(path, "w") as f:
            for _ in range(2048):
                feats = {s: rng.integers(1, 4000, rng.integers(1, 3))
                         for s in SLOTS}
                toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                                for v in vs)
                f.write(f"{int(rng.random() < 0.2)} {toks}\n")

        for ep in range(2):
            ds = Dataset(feed, num_reader_threads=2)
            ds.set_filelist([path])
            ds.load_into_memory()
            stats = trainer.train_pass(ds)
            print(f"pass {ep}: loss={stats['loss']:.4f} "
                  f"auc={stats['auc']:.4f}")

        for s in servers:
            st = s.tables["emb"]
            print(f"shard {s.index}: ram={st.ram.num_features} "
                  f"disk={st.disk.num_features}")
        total = sum(st["emb"] for st in client.stats())
        print(f"cluster holds {total} features across "
              f"{len(servers)} shards")
    finally:
        client.stop_servers()
        client.close()
        for s in servers:
            s.stop()


if __name__ == "__main__":
    main()
