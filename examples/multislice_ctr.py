"""Multi-slice (DCN) CTR training: 2 slices x 4 chips on one mesh.

The multi-node story (reference role: the inner/inter-node NCCL split —
gather_one_node_grad/gather_multi_node_grad, heter_comm.h:156-172; the
inter-node SyncParam, boxps_worker.cc:584-645): the pass table shards
over dp INSIDE each slice (all-to-all stays on ICI), slices hold
replicas kept bit-equal by one DCN psum of the push accumulator, and
dense grads sync hierarchically (reduce-scatter on ICI → psum over DCN
→ all-gather). On real multi-slice hardware `build_mesh` lays the slice
axis over DCN via `create_hybrid_device_mesh`; here the virtual CPU
mesh proves the semantics.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/multislice_ctr.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotConf
from paddlebox_tpu.embedding import DeviceFeatureStore, TableConfig
from paddlebox_tpu.models import WideDeep
from paddlebox_tpu.parallel import HybridTopology, build_mesh
from paddlebox_tpu.train import CTRTrainer, TrainerConfig

SLOTS = ("user", "item", "ctx")


def write_logs(path: str, n_lines: int = 2048) -> None:
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(n_lines):
            feats = {s: rng.integers(1, 4000, rng.integers(1, 3))
                     for s in SLOTS}
            # Planted signal: "hot" user ids click more.
            hot = int(feats["user"][0]) % 3 == 0
            label = int(rng.random() < (0.45 if hot else 0.1))
            toks = " ".join(f"{s}:{v}" for s, vs in feats.items()
                            for v in vs)
            f.write(f"{label} {toks}\n")


def main() -> None:
    assert len(jax.devices()) >= 8, (
        "run with XLA_FLAGS=--xla_force_host_platform_device_count=8")
    mesh = build_mesh(HybridTopology(slice=2, dp=4),
                      devices=jax.devices()[:8])
    print("mesh:", dict(mesh.shape))

    feed = DataFeedConfig(
        slots=tuple(SlotConf(s, avg_len=1.5) for s in SLOTS),
        batch_size=256)
    trainer = CTRTrainer(
        WideDeep(slot_names=SLOTS, emb_dim=8, hidden=(32, 16)), feed,
        TableConfig(name="emb", dim=8, learning_rate=0.1), mesh=mesh,
        config=TrainerConfig(dense_learning_rate=3e-3,
                             auc_num_buckets=1 << 12),
        store_factory=lambda cfg: DeviceFeatureStore(cfg, mesh=mesh))
    trainer.init(seed=0)
    assert trainer.dcn_axis == "slice" and trainer.ndev == 8

    with tempfile.TemporaryDirectory() as tmpdir:
        path = os.path.join(tmpdir, "part-00000")
        write_logs(path)
        ds = Dataset(feed, num_reader_threads=2)
        ds.set_filelist([path])
        ds.load_into_memory()

        for p in range(3):
            trainer.reset_metrics()
            ds.local_shuffle(seed=p)
            stats = trainer.train_pass(ds)
            print(f"pass {p}: loss={stats['loss']:.4f} "
                  f"auc={stats['auc']:.4f}")
    assert stats["auc"] > 0.6, "model failed to learn the planted signal"
    print("OK — hierarchical dense sync + intra-slice sparse all-to-all "
          "+ DCN grad stage, all in one jitted step")


if __name__ == "__main__":
    main()
