"""Auto-parallel: process mesh, dist-tensor annotations, shard planner.

Role of the reference's experimental auto-parallel stack
(``python/paddle/distributed/auto_parallel/``: ``ProcessMesh``, dist
tensor attrs, ``Engine``/planner/partitioner/reshard,
``framework/process_mesh_desc.h``): users annotate a few tensors with
mesh + dims-mapping, a planner completes the rest, a partitioner rewrites
the program per rank, and reshard inserts communication.

TPU-first: GSPMD **is** the partitioner — XLA propagates shardings and
inserts collectives; what remains valuable is (a) the annotation surface
(:class:`ProcessMesh`, :func:`shard_tensor` — dims-mapping semantics match
the reference: one mesh-dim name or None per tensor dim), (b) a planner
that completes un-annotated parameter pytrees with sensible specs
(batch→dp, vocab/feature dims→mp, large remaining dims→sharding), and
(c) :func:`reshard` (device_put to a new sharding = the reference's
reshard pass, compiled to collectives by XLA).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.parallel.zero import _spec_for_leaf


@dataclasses.dataclass(frozen=True)
class ProcessMesh:
    """Logical device mesh (role of auto_parallel.ProcessMesh): an
    nd-array of process/device ids with named dims, convertible to a
    ``jax.sharding.Mesh`` over the actual devices."""

    shape: Tuple[int, ...]
    dim_names: Tuple[str, ...]

    def __post_init__(self):
        if len(self.shape) != len(self.dim_names):
            raise ValueError("shape/dim_names length mismatch")

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    def to_jax(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        devs = list(devices) if devices is not None else jax.devices()
        if len(devs) < self.size:
            raise ValueError(f"mesh needs {self.size} devices, "
                             f"have {len(devs)}")
        arr = np.asarray(devs[:self.size]).reshape(self.shape)
        return Mesh(arr, axis_names=self.dim_names)


@dataclasses.dataclass(frozen=True)
class DistAttr:
    """Per-tensor distributed attributes (role of the reference's
    TensorDistAttr): the mesh and one mesh-dim (or None) per tensor dim."""

    mesh: ProcessMesh
    dims_mapping: Tuple[Optional[str], ...]

    def spec(self) -> P:
        return P(*self.dims_mapping)


def shard_tensor(x: jax.Array, mesh: Union[ProcessMesh, Mesh],
                 dims_mapping: Sequence[Optional[str]],
                 devices: Optional[Sequence[jax.Device]] = None
                 ) -> jax.Array:
    """Place ``x`` with the given dims mapping (role of
    auto_parallel.shard_tensor). Inside jit, use
    ``jax.lax.with_sharding_constraint`` with the same spec."""
    jmesh = mesh.to_jax(devices) if isinstance(mesh, ProcessMesh) else mesh
    return jax.device_put(x, NamedSharding(jmesh, P(*dims_mapping)))


def reshard(x: jax.Array, mesh: Union[ProcessMesh, Mesh],
            dims_mapping: Sequence[Optional[str]],
            devices: Optional[Sequence[jax.Device]] = None) -> jax.Array:
    """Re-layout to a new sharding (role of the reshard pass — XLA emits
    the all-to-all/all-gather/slice traffic)."""
    return shard_tensor(x, mesh, dims_mapping, devices)


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

# Parameter-name hints: dims of embedding/vocab-like tables shard over mp
# (matches the reference planner's operator-aware rules for embedding and
# matmul ops).
_VOCAB_HINT = re.compile(r"(embed|vocab|emb_table|wte|lm_head)",
                         re.IGNORECASE)


def plan_params(params: Any, mesh: Mesh, *,
                mp_axis: str = "mp", sharding_axis: str = "sharding",
                min_shard_size: int = 1 << 14,
                overrides: Optional[Dict[str, P]] = None) -> Any:
    """Complete a parameter pytree with PartitionSpecs (role of the
    auto-parallel completion/planner pass).

    Rules, in order:
    1. explicit ``overrides`` by flattened key path substring
    2. params whose path matches vocab/embedding hints: shard dim 0 over
       ``mp_axis`` when divisible
    3. 2D+ weights: shard the largest mp-divisible dim over ``mp_axis``
       (falls back to ``sharding_axis``)
    4. small leaves (< min_shard_size elements) replicate
    """
    mp = mesh.shape.get(mp_axis, 1)
    zshard = mesh.shape.get(sharding_axis, 1)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)

    def path_str(path) -> str:
        return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)

    specs = []
    for path, leaf in flat:
        name = path_str(path)
        shape = np.shape(leaf)
        if overrides:
            hit = next((s for pat, s in overrides.items() if pat in name),
                       None)
            if hit is not None:
                specs.append(hit)
                continue
        if np.prod(shape, dtype=np.int64) < min_shard_size or not shape:
            specs.append(P())
            continue
        if mp > 1 and _VOCAB_HINT.search(name) and shape[0] % mp == 0:
            specs.append(P(*([mp_axis] + [None] * (len(shape) - 1))))
            continue
        # Shared largest-divisible-dim rule (same helper as the ZeRO
        # planner — one place to improve dim selection).
        spec = P()
        if mp > 1 and len(shape) >= 2:
            spec = _spec_for_leaf(shape, mp, mp_axis, 0)
        if spec == P() and zshard > 1:
            spec = _spec_for_leaf(shape, zshard, sharding_axis, 0)
        specs.append(spec)
    return jax.tree_util.tree_unflatten(treedef, specs)


# ---------------------------------------------------------------------------
# Cost-based planner (role of the reference's planner + cost model,
# auto_parallel/planner_v2.py + cost_model: rank candidate distributions
# by estimated memory + communication instead of name heuristics alone).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanCost:
    """Estimated per-step cost of one plan on one device."""

    param_bytes_per_device: int      # resident param memory
    allreduce_bytes: int             # grad sync for replicated params
    allgather_bytes: int             # param gather for sharded params

    @property
    def comm_bytes(self) -> int:
        return self.allreduce_bytes + self.allgather_bytes


def estimate_plan(params: Any, specs: Any, mesh: Mesh, *,
                  dp_axis: str = "dp") -> PlanCost:
    """Cost model (deliberately simple, like the reference's per-op
    byte-count comms model): a replicated leaf holds full bytes and pays
    a ring all-reduce (~2x bytes) on its gradient over dp each step; a
    leaf sharded over axes A holds bytes/|A| and pays an all-gather of
    its full bytes (use) + reduce-scatter of its grad (~2x bytes total)
    over A, while its grad sync over dp shrinks to bytes/|A|."""
    leaves = jax.tree_util.tree_leaves(params)
    # None spec leaves mean replicated; keep them as leaves so the two
    # flattenings stay congruent.
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or isinstance(x, P))
    if len(leaves) != len(spec_leaves):
        raise ValueError(
            f"params/specs structure mismatch: {len(leaves)} param leaves "
            f"vs {len(spec_leaves)} spec leaves — a silent zip truncation "
            f"here would under-count the plan's cost")
    dp = int(mesh.shape.get(dp_axis, 1))
    mem = ar = ag = 0
    for leaf, spec in zip(leaves, spec_leaves):
        nbytes = int(np.prod(np.shape(leaf), dtype=np.int64)
                     * np.dtype(getattr(leaf, "dtype", np.float32)).itemsize)
        factor = 1
        for entry in (() if spec is None else spec):
            if entry is None:
                continue
            for ax in (entry if isinstance(entry, (tuple, list))
                       else (entry,)):
                factor *= int(mesh.shape[ax])
        mem += nbytes // factor
        if factor > 1:
            ag += 2 * nbytes                 # gather + grad scatter
            ar += 2 * (nbytes // factor) if dp > 1 else 0
        elif dp > 1:
            ar += 2 * nbytes
    return PlanCost(param_bytes_per_device=mem, allreduce_bytes=ar,
                    allgather_bytes=ag)


def plan_params_cost(params: Any, mesh: Mesh, *,
                     bytes_budget_per_device: int,
                     shard_axes: Sequence[str] = ("sharding", "mp"),
                     dp_axis: str = "dp") -> Tuple[Any, PlanCost]:
    """Choose per-leaf specs by COST under a device memory budget (role
    of the reference planner's cost-guided completion): start fully
    replicated (cheapest communication — one grad all-reduce), then
    while over budget, shard the largest remaining leaf over the first
    shard axis that divides one of its dims — biggest leaves first
    maximizes memory reclaimed per unit of added all-gather traffic,
    which is exactly the greedy the byte-count cost model prescribes.
    Returns (specs pytree, estimated PlanCost). Raises if the budget is
    unreachable even fully sharded."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    sizes = [int(np.prod(np.shape(l), dtype=np.int64)
                 * np.dtype(getattr(l, "dtype", np.float32)).itemsize)
             for l in flat]
    axes = [a for a in shard_axes
            if a in mesh.axis_names and int(mesh.shape[a]) > 1]
    specs: list = [P()] * len(flat)
    resident = list(sizes)

    def try_shard(i: int) -> bool:
        shape = np.shape(flat[i])
        best = None  # (reclaimed bytes, spec)
        for ax in axes:
            n = int(mesh.shape[ax])
            for d, s in enumerate(shape):
                if s % n == 0 and s >= n:
                    reclaimed = sizes[i] - sizes[i] // n
                    if best is None or reclaimed > best[0]:
                        best = (reclaimed,
                                P(*[ax if j == d else None
                                    for j in range(len(shape))]))
                    break  # first divisible dim per axis
        if best is None:
            return False
        specs[i] = best[1]
        resident[i] = sizes[i] - best[0]
        return True

    order = sorted(range(len(flat)), key=lambda i: -sizes[i])
    for i in order:
        if sum(resident) <= bytes_budget_per_device:
            break
        try_shard(i)
    if sum(resident) > bytes_budget_per_device:
        raise ValueError(
            f"plan cannot fit {sum(resident)} bytes into the "
            f"{bytes_budget_per_device}-byte budget even after sharding "
            f"every divisible leaf over {axes or 'no available axes'}")
    spec_tree = jax.tree_util.tree_unflatten(treedef, specs)
    return spec_tree, estimate_plan(params, spec_tree, mesh,
                                    dp_axis=dp_axis)


def plan_shardings(params: Any, mesh: Mesh, **kw) -> Any:
    """plan_params → NamedShardings (feed straight into jit in_shardings)."""
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                  plan_params(params, mesh, **kw),
                                  is_leaf=lambda x: isinstance(x, P))


def apply_plan(params: Any, mesh: Mesh, **kw) -> Any:
    """Place a parameter pytree per the plan (annotation + partition in
    one step — the Engine.prepare() ergonomics of the reference)."""
    shardings = plan_shardings(params, mesh, **kw)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)
