"""Named collective primitives for use inside ``shard_map``.

API-role parity with the reference's collective op set
(``paddle/fluid/operators/collective/``): ``c_allreduce_{sum,max,min}``,
``c_allgather``, ``c_reducescatter``, ``c_broadcast``, ``alltoall``,
``send_v2/recv_v2`` (as ``ppermute``), ``barrier``. On TPU these lower to XLA
collectives scheduled over ICI/DCN — there are no communicators or streams to
manage (reference needs ``NCCLCommContext``, ``collective_helper.h:70``).

All functions must be called under ``jax.shard_map`` (or inside ``pjit`` with
manual axes) with ``axis`` naming a mesh axis.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Sequence[str]]


def all_reduce_sum(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.psum(x, axis)


def all_reduce_max(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.pmax(x, axis)


def all_reduce_min(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.pmin(x, axis)


def all_reduce_mean(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.pmean(x, axis)


def all_gather(x: jax.Array, axis: Axis, *, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Concatenate shards along ``gather_dim`` (role of c_allgather)."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter_sum(x: jax.Array, axis: Axis, *, scatter_dim: int = 0) -> jax.Array:
    """Sum-reduce then scatter along ``scatter_dim`` (role of c_reducescatter)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x: jax.Array, axis: Axis, *, split_dim: int, concat_dim: int,
               tiled: bool = True) -> jax.Array:
    """All-to-all exchange (role of alltoall_op; EP dispatch, SP Ulysses)."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def broadcast(x: jax.Array, axis: Axis, *, root: int = 0) -> jax.Array:
    """Every rank receives root's shard (role of c_broadcast).

    Implemented as a masked psum — O(1) extra memory, unlike an n-way
    all_gather that would materialize every shard just to index one.
    """
    mask = (lax.axis_index(axis) == root).astype(x.dtype)
    return lax.psum(x * mask, axis)


def _quantized_allreduce_flat(flat: jax.Array, axis: Axis,
                              wire_dtype: str, block: int) -> jax.Array:
    """All-reduce-sum one flat f32 vector over ``axis`` with a narrowed
    wire (EQuARX, PAPERS.md): the scatter hop ships each rank's destined
    segment quantized (int8 per-block absmax scales, or a bf16 cast),
    accumulation ALWAYS happens in f32 after dequantization, and the
    gather hop ships the reduced segment through the same codec.
    ``wire_dtype='f32'`` is a plain ``lax.psum`` — bit-identical to the
    pre-quantization program, so the default path never changes HLO.

    The reduce-scatter is realized as a tiled ``all_to_all`` of the
    quantized rows (a real ``psum_scatter`` would accumulate IN the wire
    dtype — int8 sums overflow immediately); row i of the [n, seg]
    reshape is the segment destined for rank i.
    """
    if wire_dtype == "f32":
        return lax.psum(flat, axis)
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    n = 1
    for nm in names:
        n *= lax.axis_size(nm)
    if n == 1:
        return flat
    orig = flat.size
    pad = (-orig) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    seg_w = flat.size // n
    rows = flat.reshape(n, seg_w)
    if wire_dtype == "bf16":
        recv = lax.all_to_all(rows.astype(jnp.bfloat16), axis,
                              split_axis=0, concat_axis=0, tiled=True)
        seg = jnp.sum(recv.astype(jnp.float32), axis=0)
        out = lax.all_gather(seg.astype(jnp.bfloat16), axis, axis=0,
                             tiled=True).astype(jnp.float32)
    elif wire_dtype == "int8":
        from paddlebox_tpu.multihost.quant import (dequantize_blocked,
                                                   quantize_blocked)
        q, scales = quantize_blocked(rows, block)
        q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                           tiled=True)
        scales = lax.all_to_all(scales, axis, split_axis=0,
                                concat_axis=0, tiled=True)
        seg = jnp.sum(dequantize_blocked(q, scales, seg_w, block), axis=0)
        qg, sg = quantize_blocked(seg[None, :], block)
        qg = lax.all_gather(qg[0], axis, axis=0, tiled=True)
        sg = lax.all_gather(sg[0], axis, axis=0, tiled=True)
        out = dequantize_blocked(qg.reshape(n, seg_w),
                                 sg.reshape(n, -1), seg_w,
                                 block).reshape(-1)
    else:
        raise ValueError(
            f"quantized allreduce wire must be f32|bf16|int8, "
            f"got {wire_dtype!r}")
    return out[:orig] if pad else out


def quantized_psum(tree, axis: Axis, *, wire_dtype: str = "f32",
                   block: int = 128):
    """All-reduce-sum a pytree over ``axis`` with a reduced-precision
    wire (``FLAGS_dense_allreduce_dtype``): blocked int8 absmax
    quantize -> scatter -> f32 dequant-accumulate -> gather, reusing
    the ``multihost/quant.py`` jnp codec twins. ``'f32'`` returns
    ``lax.psum(tree, axis)`` verbatim — the default program is
    bit-identical to the unquantized sync.

    Like :func:`hierarchical_psum_tree` the tree is fused into ONE flat
    vector (raveled leaves, padded to a multiple of the axis size) so
    arbitrary leaf shapes never break the segment split, and per-block
    scales amortize over the whole fused grad block. Call under
    shard_map / pjit manual axes with ``axis`` in scope.
    """
    if wire_dtype == "f32":
        return lax.psum(tree, axis)
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    sizes = [int(l.size) for l in leaves]
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    acc_dt = jnp.result_type(*dtypes)
    flat = jnp.concatenate([l.astype(acc_dt).ravel() for l in leaves])
    flat = _quantized_allreduce_flat(flat.astype(jnp.float32), axis,
                                     wire_dtype, block).astype(acc_dt)
    out = []
    off = 0
    for size, shape, dt in zip(sizes, shapes, dtypes):
        out.append(flat[off:off + size].reshape(shape).astype(dt))
        off += size
    return jax.tree.unflatten(treedef, out)


def hierarchical_psum_tree(tree, *, inner_axis: Axis,
                           outer_axis: Axis,
                           outer_wire_dtype: str = "f32",
                           quant_block: int = 128):
    """All-reduce-sum a pytree across inner (ICI) × outer (DCN) axes by
    the bandwidth-optimal two-level schedule: reduce-scatter over the
    fast inner axis, all-reduce only the 1/inner_n shard over the slow
    outer axis, all-gather back over the inner axis.

    Role of the reference's two-level dense sync — SyncParam's fused
    ReduceScatter + inter-node SyncDense + AllGather
    (``boxps_worker.cc:584-645``) and HeterComm's
    gather_one_node_grad/gather_multi_node_grad split
    (``heter_comm.h:156-172``): each DCN link carries total_bytes /
    inner_n instead of total_bytes.

    The tree is flattened into ONE fused f32-width-preserving vector
    (leaves raveled + concatenated, padded to a multiple of the inner
    axis size) so arbitrary leaf shapes never break the reduce-scatter
    split — same fusion the reference applies to the dense param block.
    Numerically == ``lax.psum(tree, (inner, outer))`` up to summation
    order. Call under shard_map with both axes in scope.

    ``outer_wire_dtype`` narrows ONLY the slow outer (DCN) hop through
    the :func:`quantized_psum` codec (``'bf16'``/``'int8'``); the fast
    ICI reduce-scatter/all-gather stays f32 — the DCN link is where
    bytes cost, and keeping ICI exact bounds the quantization error to
    one outer round trip. ``'f32'`` (default) leaves the program
    bit-identical to the pre-quantization wire.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    n_in = lax.axis_size(inner_axis)
    sizes = [int(l.size) for l in leaves]
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    # One accumulation dtype for the fused buffer: promote everything to
    # the widest leaf dtype (in practice f32 for grads); cast back after.
    acc_dt = jnp.result_type(*dtypes)
    flat = jnp.concatenate([l.astype(acc_dt).ravel() for l in leaves])
    pad = (-flat.size) % n_in
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), acc_dt)])
    if n_in > 1:
        part = lax.psum_scatter(flat, inner_axis, scatter_dimension=0,
                                tiled=True)
        part = _quantized_allreduce_flat(part, outer_axis,
                                         outer_wire_dtype, quant_block)
        flat = lax.all_gather(part, inner_axis, axis=0, tiled=True)
    else:
        flat = _quantized_allreduce_flat(flat, outer_axis,
                                         outer_wire_dtype, quant_block)
    out = []
    off = 0
    for size, shape, dt in zip(sizes, shapes, dtypes):
        out.append(flat[off:off + size].reshape(shape).astype(dt))
        off += size
    return jax.tree.unflatten(treedef, out)


def ppermute_shift(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Ring shift: rank i sends to rank (i+shift) % n. Role of send_v2/recv_v2
    p2p pairs in pipeline parallelism (reference p2p_communication.py)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def barrier(axis: Axis, token: Optional[jax.Array] = None) -> jax.Array:
    """Collective rendezvous (role of barrier op / MPICluster::barrier).

    Returns a scalar token that the caller MUST thread into downstream
    computation (e.g. add to a value, or pass as an operand) — an unused
    collective would be dead-code-eliminated by XLA and the barrier would
    be a no-op.
    """
    t = jnp.zeros((), jnp.int32) if token is None else token.astype(jnp.int32).sum()
    return lax.psum(t, axis)
