"""Named collective primitives for use inside ``shard_map``.

API-role parity with the reference's collective op set
(``paddle/fluid/operators/collective/``): ``c_allreduce_{sum,max,min}``,
``c_allgather``, ``c_reducescatter``, ``c_broadcast``, ``alltoall``,
``send_v2/recv_v2`` (as ``ppermute``), ``barrier``. On TPU these lower to XLA
collectives scheduled over ICI/DCN — there are no communicators or streams to
manage (reference needs ``NCCLCommContext``, ``collective_helper.h:70``).

All functions must be called under ``jax.shard_map`` (or inside ``pjit`` with
manual axes) with ``axis`` naming a mesh axis.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Sequence[str]]


def all_reduce_sum(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.psum(x, axis)


def all_reduce_max(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.pmax(x, axis)


def all_reduce_min(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.pmin(x, axis)


def all_reduce_mean(x: jax.Array, axis: Axis) -> jax.Array:
    return lax.pmean(x, axis)


def all_gather(x: jax.Array, axis: Axis, *, gather_dim: int = 0,
               tiled: bool = True) -> jax.Array:
    """Concatenate shards along ``gather_dim`` (role of c_allgather)."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter_sum(x: jax.Array, axis: Axis, *, scatter_dim: int = 0) -> jax.Array:
    """Sum-reduce then scatter along ``scatter_dim`` (role of c_reducescatter)."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True)


def all_to_all(x: jax.Array, axis: Axis, *, split_dim: int, concat_dim: int,
               tiled: bool = True) -> jax.Array:
    """All-to-all exchange (role of alltoall_op; EP dispatch, SP Ulysses)."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)


def broadcast(x: jax.Array, axis: Axis, *, root: int = 0) -> jax.Array:
    """Every rank receives root's shard (role of c_broadcast).

    Implemented as a masked psum — O(1) extra memory, unlike an n-way
    all_gather that would materialize every shard just to index one.
    """
    mask = (lax.axis_index(axis) == root).astype(x.dtype)
    return lax.psum(x * mask, axis)


def ppermute_shift(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Ring shift: rank i sends to rank (i+shift) % n. Role of send_v2/recv_v2
    p2p pairs in pipeline parallelism (reference p2p_communication.py)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def barrier(axis: Axis, token: Optional[jax.Array] = None) -> jax.Array:
    """Collective rendezvous (role of barrier op / MPICluster::barrier).

    Returns a scalar token that the caller MUST thread into downstream
    computation (e.g. add to a value, or pass as an operand) — an unused
    collective would be dead-code-eliminated by XLA and the barrier would
    be a no-op.
    """
    t = jnp.zeros((), jnp.int32) if token is None else token.astype(jnp.int32).sum()
    return lax.psum(t, axis)
