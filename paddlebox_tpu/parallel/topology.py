"""Hybrid-parallel device mesh topology.

Role of ``HybridCommunicateGroup`` (reference
``python/paddle/distributed/fleet/base/topology.py:52,134``): carve the device
set into communication groups for data-parallel (dp), pipeline (pp),
ZeRO-sharding (sharding), tensor/model-parallel (mp), expert (ep), and — new
for the TPU build — sequence/context parallel (sp) axes.

TPU-first difference: instead of materializing NCCL communicators per group,
we build ONE ``jax.sharding.Mesh`` whose named axes ARE the groups. pjit /
shard_map + XLA then insert collectives over the right axis; physical ICI
adjacency is handled by ``jax.experimental.mesh_utils.create_device_mesh``.

Axis order convention (outermost → innermost): ``slice, dp, sharding, pp,
sp, ep, mp``. The innermost axis maps to physically-adjacent devices, so mp
(the highest-frequency, latency-sensitive collectives) rides the fastest ICI
links; dp (lowest frequency — one gradient sync per step) may cross DCN.
This extends the reference's [dp, pp, sharding, mp] nesting
(``topology.py:52``) with sp (long-context sequence parallel) and ep
(expert parallel, role of the MoE group in ``moe_layer.py``).

``slice`` is the multi-slice / multi-pod DCN axis (role of the reference's
inner-vs-inter-node comm split — ``heter_comm.h:156-172``
gather_one_node_grad / gather_multi_node_grad and the two-level NCCL
communicators): devices within a slice are ICI-connected; crossing slices
rides the data-center network. Collectives that name only intra-slice axes
stay on ICI; the hierarchical helpers in ``parallel.collective``
(``hierarchical_psum_tree``) and the ``dcn_axis`` hooks in the sparse
push / CTR trainer route the slow DCN hop over the minimum data.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order, outermost first. "slice" (DCN) is outermost: its
# links are the slowest, so only the lowest-frequency collectives may
# name it.
AXIS_ORDER: Tuple[str, ...] = ("slice", "dp", "sharding", "pp", "sp",
                               "ep", "mp")


@dataclasses.dataclass(frozen=True)
class HybridTopology:
    """Degrees of each parallelism axis. 1 = axis unused.

    slice    multi-slice / multi-pod data parallel over DCN (outermost:
             slowest links, lowest-frequency collectives)
    dp       data parallel (replica groups; gradient allreduce)
    sharding ZeRO optimizer/gradient/param sharding subgroups inside dp
    pp       pipeline stages
    sp       sequence/context parallel (ring attention / Ulysses)
    ep       expert parallel (MoE all-to-all dispatch group)
    mp       tensor/model parallel (innermost: fastest ICI)
    """

    slice: int = 1
    dp: int = 1
    sharding: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    mp: int = 1

    @property
    def world_size(self) -> int:
        n = 1
        for a in AXIS_ORDER:
            n *= getattr(self, a)
        return n

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def nontrivial_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if getattr(self, a) > 1]


def build_mesh(topo: Optional[HybridTopology] = None,
               devices: Optional[Sequence[jax.Device]] = None,
               axis_order: Sequence[str] = AXIS_ORDER) -> Mesh:
    """Build a ``jax.sharding.Mesh`` realizing the hybrid topology.

    On TPU, uses ``mesh_utils.create_device_mesh`` so the logical mesh
    respects physical ICI adjacency (innermost axes on nearest neighbors).
    On CPU (virtual-device tests) falls back to a plain reshape.
    """
    if devices is None:
        devices = jax.devices()
    ndev = len(devices)
    if topo is None:
        topo = HybridTopology(dp=ndev)
    if topo.world_size != ndev:
        raise ValueError(
            f"topology {topo.axis_sizes()} needs {topo.world_size} devices, "
            f"have {ndev}")
    shape = tuple(getattr(topo, a) for a in axis_order)
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils
        n_slices = getattr(topo, "slice", 1)
        if n_slices > 1 and "slice" in axis_order:
            # Multi-slice: the slice axis spans DCN, every other axis is
            # intra-slice ICI. create_hybrid_device_mesh lays devices out
            # so exactly the slice dim crosses slice boundaries.
            si = list(axis_order).index("slice")
            dcn_shape = tuple(n_slices if i == si else 1
                              for i in range(len(shape)))
            ici_shape = tuple(1 if i == si else s
                              for i, s in enumerate(shape))
            mesh_devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=list(devices))
        else:
            mesh_devices = mesh_utils.create_device_mesh(
                shape, devices=list(devices))
    else:
        mesh_devices = np.asarray(devices).reshape(shape)
    return Mesh(mesh_devices, axis_names=tuple(axis_order))


# Process-global default topology/mesh (role of fleet.init wiring the global
# HybridCommunicateGroup, fleet_base.py:211).
_DEFAULT: Dict[str, object] = {"topo": None, "mesh": None}


def set_default_topology(topo: HybridTopology,
                         devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    mesh = build_mesh(topo, devices)
    _DEFAULT["topo"] = topo
    _DEFAULT["mesh"] = mesh
    return mesh


def get_default_topology() -> Tuple[Optional[HybridTopology], Optional[Mesh]]:
    return _DEFAULT["topo"], _DEFAULT["mesh"]  # type: ignore[return-value]


def data_sharding(mesh: Mesh, *,
                  batch_axes: Sequence[str] = ("slice", "dp", "sharding")
                  ) -> NamedSharding:
    """Sharding for a [batch, ...] input: batch split over the replica axes
    (dp and its inner ZeRO-sharding subgroups). Sequence-parallel splits the
    sequence dimension, not batch — annotate that separately."""
    axes = [a for a in batch_axes if a in mesh.axis_names and mesh.shape[a] > 1]
    spec = P(tuple(axes) if axes else None)
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
