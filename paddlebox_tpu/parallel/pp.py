"""Pipeline parallelism over the ``pp`` mesh axis.

Role of the reference's pipeline stacks: dygraph 1F1B
(``meta_parallel/pipeline_parallel.py:82`` forward_backward_pipeline),
``PipelineLayer`` partitioning (``parallel_layers/pp_layers.py``), p2p
send/recv (``pp_utils/p2p_communication.py``), and static-graph
``SectionWorker`` microbatch scopes (``section_worker.cc:40-116``).

TPU-first: stages live on the pp mesh axis (every device holds ITS stage's
params — stacked pytrees sharded on the leading dim); microbatches stream
through a ``lax.scan`` whose body computes one stage step and rotates
activations to the next stage with ``ppermute`` (neighbor ICI transfer).
Autodiff through the scan yields the pipeline backward with activation
stashing (GPipe schedule) — no hand-written adjoint, no interceptor
runtime; XLA overlaps the ppermute with the next microbatch's compute.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Host-side: stack per-stage param pytrees on a new leading dim
    (shard it over "pp": each device then holds its own stage's params).
    Role of PipelineLayer's partitioning of the layer list."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_specs(stacked_params: Any, axis: str = "pp") -> Any:
    """PartitionSpecs sharding the stacked leading dim over the pp axis."""
    return jax.tree.map(lambda _: P(axis), stacked_params)


def gpipe_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                params_local: Any, x_microbatches: jax.Array, *,
                axis: str = "pp") -> jax.Array:
    """Run the pipeline on microbatches (call INSIDE shard_map).

    stage_fn(params, act) -> act: one stage's computation (same signature
    on every stage; heterogeneous stages dispatch on a params field).
    params_local: this device's stage params (leading stage dim already
    consumed by sharding).
    x_microbatches [M, mb, F]: the full microbatched input (replicated or
    dp-sharded on mb; only stage 0 reads it).

    Returns [M, mb, F_out]: outputs, valid on the LAST stage and
    broadcast to all pp ranks via masked psum (so out_specs can be P()).

    Total steps = M + n_stages - 1; the bubble executes masked compute,
    same cost shape as the reference's 1F1B bubble.
    """
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    # Probe output shape once (shapes static).
    out_shape = jax.eval_shape(lambda p, a: stage_fn(p, a), params_local,
                               jax.ShapeDtypeStruct(mb_shape, x_microbatches.dtype))

    state0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    outputs0 = jnp.zeros((m,) + out_shape.shape, out_shape.dtype)

    def step(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (while t < m).
        x_t = x_microbatches[jnp.clip(t, 0, m - 1)]
        ingest = (rank == 0) & (t < m)
        state = jnp.where(ingest, x_t, state)
        y = stage_fn(params_local, state)
        # Last stage emits microbatch t - (n-1) when in range.
        mb_idx = t - (n - 1)
        emit = (rank == n - 1) & (mb_idx >= 0) & (mb_idx < m)
        idx = jnp.clip(mb_idx, 0, m - 1)
        outputs = outputs.at[idx].set(
            jnp.where(emit, y, outputs[idx]))
        # Rotate activations to the next stage.
        state = lax.ppermute(y, axis, [(i, (i + 1) % n) for i in range(n)])
        return (state, outputs), None

    (_, outputs), _ = lax.scan(step, (state0, outputs0),
                               jnp.arange(m + n - 1))
    # Broadcast final outputs from the last stage to every pp rank so the
    # loss is computable anywhere (role of _broadcast_final_loss,
    # pipeline_parallel.py:325).
    is_last = (rank == n - 1).astype(outputs.dtype)
    return lax.psum(outputs * is_last, axis)


def make_pipeline_fn(mesh: Mesh, stage_fn, stacked_params_template, *,
                     axis: str = "pp", extra_in_specs: Tuple = ()):
    """Jitted wrapper: (stacked_params, x_microbatches) -> outputs."""
    import functools

    pspecs = stage_specs(stacked_params_template, axis)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(pspecs, P()) + extra_in_specs,
        out_specs=P(), check_vma=False)
    def run(stacked_params, x_mb):
        params_local = jax.tree.map(lambda a: a[0], stacked_params)
        return gpipe_apply(stage_fn, params_local, x_mb, axis=axis)

    return run
