"""Pipeline parallelism over the ``pp`` mesh axis.

Role of the reference's pipeline stacks: dygraph 1F1B
(``meta_parallel/pipeline_parallel.py:82`` forward_backward_pipeline),
``PipelineLayer`` partitioning (``parallel_layers/pp_layers.py``), p2p
send/recv (``pp_utils/p2p_communication.py``), and static-graph
``SectionWorker`` microbatch scopes (``section_worker.cc:40-116``).

TPU-first: stages live on the pp mesh axis (every device holds ITS stage's
params — stacked pytrees sharded on the leading dim); microbatches stream
through a ``lax.scan`` whose body computes one stage step and rotates
activations to the next stage with ``ppermute`` (neighbor ICI transfer).
Autodiff through the scan yields the pipeline backward with activation
stashing (GPipe schedule) — no hand-written adjoint, no interceptor
runtime; XLA overlaps the ppermute with the next microbatch's compute.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Host-side: stack per-stage param pytrees on a new leading dim
    (shard it over "pp": each device then holds its own stage's params).
    Role of PipelineLayer's partitioning of the layer list."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_specs(stacked_params: Any, axis: str = "pp") -> Any:
    """PartitionSpecs sharding the stacked leading dim over the pp axis."""
    return jax.tree.map(lambda _: P(axis), stacked_params)


def gpipe_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                params_local: Any, x_microbatches: jax.Array, *,
                axis: str = "pp") -> jax.Array:
    """Run the pipeline on microbatches (call INSIDE shard_map).

    stage_fn(params, act) -> act: one stage's computation (same signature
    on every stage; heterogeneous stages dispatch on a params field).
    params_local: this device's stage params (leading stage dim already
    consumed by sharding).
    x_microbatches [M, mb, F]: the full microbatched input (replicated or
    dp-sharded on mb; only stage 0 reads it).

    Returns [M, mb, F_out]: outputs, valid on the LAST stage and
    broadcast to all pp ranks via masked psum (so out_specs can be P()).

    Total steps = M + n_stages - 1; the bubble executes masked compute,
    same cost shape as the reference's 1F1B bubble.
    """
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]

    # Probe output shape once (shapes static).
    out_shape = jax.eval_shape(lambda p, a: stage_fn(p, a), params_local,
                               jax.ShapeDtypeStruct(mb_shape, x_microbatches.dtype))

    state0 = jnp.zeros(mb_shape, x_microbatches.dtype)
    outputs0 = jnp.zeros((m,) + out_shape.shape, out_shape.dtype)

    def step(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (while t < m).
        x_t = x_microbatches[jnp.clip(t, 0, m - 1)]
        ingest = (rank == 0) & (t < m)
        state = jnp.where(ingest, x_t, state)
        y = stage_fn(params_local, state)
        # Last stage emits microbatch t - (n-1) when in range.
        mb_idx = t - (n - 1)
        emit = (rank == n - 1) & (mb_idx >= 0) & (mb_idx < m)
        idx = jnp.clip(mb_idx, 0, m - 1)
        outputs = outputs.at[idx].set(
            jnp.where(emit, y, outputs[idx]))
        # Rotate activations to the next stage.
        state = lax.ppermute(y, axis, [(i, (i + 1) % n) for i in range(n)])
        return (state, outputs), None

    (_, outputs), _ = lax.scan(step, (state0, outputs0),
                               jnp.arange(m + n - 1))
    # Broadcast final outputs from the last stage to every pp rank so the
    # loss is computable anywhere (role of _broadcast_final_loss,
    # pipeline_parallel.py:325).
    is_last = (rank == n - 1).astype(outputs.dtype)
    return lax.psum(outputs * is_last, axis)


def _loss_and_seed(loss_fn, loss_params, y, tgt, lgrads, lmask):
    """Shared last-stage loss evaluation for both 1F1B schedules: the
    loss value, the backward seed (d loss / d y), and — when the head
    rides the loss_params channel — its masked grad accumulation. One
    implementation so the two schedules cannot drift."""
    if loss_params is None:
        loss_j, seed = jax.value_and_grad(lambda yy: loss_fn(yy, tgt))(y)
        return loss_j, seed, lgrads
    (loss_j, (dlp, seed)) = jax.value_and_grad(
        lambda lp, yy: loss_fn(lp, yy, tgt), argnums=(0, 1))(loss_params, y)
    lgrads = jax.tree.map(
        lambda g, d: g + lmask * d.astype(g.dtype), lgrads, dlp)
    return loss_j, seed, lgrads


def _pipeline_out(loss, grads, lgrads, dx0_buf, m, loss_params,
                  return_input_grads):
    """Shared output assembly (mean-loss scaling + optional channels)."""
    grads = jax.tree.map(lambda g: g / m, grads)
    out = (loss, grads)
    if loss_params is not None:
        out = out + (jax.tree.map(lambda g: g / m, lgrads),)
    if return_input_grads:
        out = out + (dx0_buf / m,)
    return out


def one_f_one_b_value_and_grad(
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        loss_fn: Callable[..., jax.Array],
        params_local: Any, x_microbatches: jax.Array,
        targets_microbatches: jax.Array, *,
        axis: str = "pp", loss_params: Any = None,
        return_input_grads: bool = False):
    """1F1B pipeline forward+backward with bounded activation memory
    (call INSIDE shard_map).

    Role of the reference 1F1B schedules
    (``meta_parallel/pipeline_parallel.py:82`` forward_backward_pipeline;
    static-graph ``section_worker.cc:40-63``): each microbatch's backward
    starts as soon as its gradient returns, so a stage holds at most
    ``2*(n_stages - rank) - 1`` in-flight stage INPUTS — independent of
    the microbatch count M — where the GPipe-through-autodiff path
    (:func:`gpipe_apply` + ``jax.grad``) stashes every scan step's
    internal residuals, O(M).

    TPU-first differences from the reference:
    - Eager lock-step schedule: every tick runs one (masked) forward AND
      one (masked) backward on every stage; in steady state both halves
      are real work on every stage simultaneously, so there is no
      masked-idle waste — strict Megatron-style 1F1B alternation would
      leave half of each SPMD tick masked out. Fill/drain bubbles are the
      usual ``n-1`` ticks at each end.
    - Rematerialized backward: the ring buffer stores stage INPUTS only;
      the backward recomputes the stage forward under ``jax.vjp`` (the
      standard TPU trade of FLOPs for HBM).
    - Activations move by neighbor ``ppermute`` (fwd ring s->s+1, bwd
      ring s->s-1) on ICI; param grads accumulate locally per stage.

    ``stage_fn(params, act) -> act`` must preserve the activation shape
    across stages (same contract as :func:`gpipe_apply`).
    ``loss_fn(last_stage_out, target_mb)`` — or, when ``loss_params`` is
    given, ``loss_fn(loss_params, last_stage_out, target_mb)`` — returns
    a scalar, evaluated on the last stage; the returned loss is the mean
    over microbatches, broadcast to every pp rank.

    Returns ``(loss, stage_grads)`` by default, both scaled so grads
    correspond to the mean loss. With ``loss_params``, returns
    ``(loss, stage_grads, loss_param_grads)`` — the grads of the
    last-stage head/readout (zero on other ranks; psum them outside if
    the head is replicated). With ``return_input_grads``, appends
    ``dx0 [M, *mb_shape]``: cotangents of the stage-0 microbatch inputs
    (nonzero on rank 0 only), for backpropagating into an embedding that
    runs OUTSIDE the pipeline loop.
    """
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    m = x_microbatches.shape[0]
    mb_shape = x_microbatches.shape[1:]
    dtype = x_microbatches.dtype

    # Static ring capacity: max in-flight inputs over all stages.
    ring_cap = 2 * n - 1

    fwd0 = jnp.zeros(mb_shape, dtype)
    bwd0 = jnp.zeros(mb_shape, dtype)
    ring0 = jnp.zeros((ring_cap,) + mb_shape, dtype)
    grads0 = jax.tree.map(jnp.zeros_like, params_local)
    loss0 = jnp.zeros((), jnp.float32)
    lgrads0 = (jax.tree.map(jnp.zeros_like, loss_params)
               if loss_params is not None else None)
    dx0_buf0 = (jnp.zeros((m,) + mb_shape, dtype)
                if return_input_grads else None)

    # Schedule (ticks): F(s, j) at tick j + s;
    # B(s, j) at tick 2*(n-1) - s + j  (same tick as F on the last stage).
    total_ticks = m + 2 * (n - 1)

    def tick(carry, t):
        fwd_in, bwd_in, ring, grads, loss_acc, lgrads, dx0_buf = carry

        # ---- forward half -------------------------------------------
        j_f = t - rank
        f_active = (j_f >= 0) & (j_f < m)
        x_t = x_microbatches[jnp.clip(j_f, 0, m - 1)]
        x_in = jnp.where(rank == 0, x_t, fwd_in)
        ring = ring.at[jnp.clip(j_f, 0, m - 1) % ring_cap].set(
            jnp.where(f_active, x_in, ring[jnp.clip(j_f, 0, m - 1)
                                           % ring_cap]))
        y = stage_fn(params_local, x_in)
        y = jnp.where(f_active, y, 0)

        # Last stage: seed the backward for THIS tick's microbatch.
        # targets may be any pytree microbatched on the leading dim (a
        # trainer batch dict) — each leaf is indexed the same way.
        j_b = t - (2 * (n - 1) - rank)
        b_active = (j_b >= 0) & (j_b < m)
        tgt = jax.tree.map(
            lambda a: a[jnp.clip(j_b, 0, m - 1)], targets_microbatches)

        is_last = rank == n - 1
        loss_j, seed, lgrads = _loss_and_seed(
            loss_fn, loss_params, y, tgt, lgrads,
            (b_active & is_last).astype(jnp.float32))
        loss_acc = loss_acc + jnp.where(b_active & is_last,
                                        loss_j.astype(jnp.float32), 0.0)
        din = jnp.where(is_last, seed.astype(dtype), bwd_in)

        # ---- backward half (rematerialized) -------------------------
        x_saved = ring[jnp.clip(j_b, 0, m - 1) % ring_cap]
        _, vjp = jax.vjp(stage_fn, params_local, x_saved)
        dparams, dx = vjp(din)
        bmask = b_active.astype(dtype)
        grads = jax.tree.map(
            lambda g, d: g + bmask * d.astype(g.dtype), grads, dparams)
        dx = dx * bmask
        if dx0_buf is not None:
            # Stage 0's input cotangent for microbatch j_b (zero off
            # rank 0 — there j_b indexes a different stage's schedule).
            keep = (b_active & (rank == 0)).astype(dtype)
            idx = jnp.clip(j_b, 0, m - 1)
            dx0_buf = dx0_buf.at[idx].add(keep * dx)

        # ---- rotate rings -------------------------------------------
        fwd_next = lax.ppermute(y, axis,
                                [(i, (i + 1) % n) for i in range(n)])
        bwd_next = lax.ppermute(dx, axis,
                                [(i, (i - 1) % n) for i in range(n)])
        return (fwd_next, bwd_next, ring, grads, loss_acc, lgrads,
                dx0_buf), None

    (_, _, _, grads, loss_acc, lgrads, dx0_buf), _ = lax.scan(
        tick, (fwd0, bwd0, ring0, grads0, loss0, lgrads0, dx0_buf0),
        jnp.arange(total_ticks))

    # Mean loss over microbatches, broadcast from the last stage (role of
    # _broadcast_final_loss, pipeline_parallel.py:325).
    loss = lax.psum(loss_acc * (rank == n - 1), axis) / m
    return _pipeline_out(loss, grads, lgrads, dx0_buf, m, loss_params,
                         return_input_grads)


def interleaved_one_f_one_b_value_and_grad(
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        loss_fn: Callable[..., jax.Array],
        chunk_params: Any, x_microbatches: jax.Array,
        targets_microbatches: jax.Array, *,
        num_chunks: int, axis: str = "pp", loss_params: Any = None,
        return_input_grads: bool = False):
    """Interleaved (virtual-stage) 1F1B: each rank holds ``num_chunks``
    pipeline chunks assigned CYCLICALLY over ranks (virtual stage
    ``d`` lives on rank ``d % p``, chunk ``d // p``) — the reference's
    interleaved scheduler (``meta_parallel/pipeline_parallel.py``
    ``_forward_backward_pipeline(... virtual_pp_degree)``, Megatron-style
    ``virtual_pipeline_model_parallel_size``). Each TICK runs one CHUNK
    forward + one chunk backward per rank, so fill/drain bubbles cost
    chunk-times rather than stage-times: total masked work is
    ``(V-1)p + 2(p-1)`` chunk-ticks against the plain schedule's
    ``2(p-1)`` FULL-stage ticks — about half the bubble time at V>=2
    (asymptote ~p chunk-ticks as V grows).

    Schedule (lock-step SPMD, all data-independent): rank r's i-th
    forward runs at tick ``t = i + r`` on chunk ``(i // p) % V`` for
    microbatch ``(i // (p*V)) * p + i % p`` — exactly the cyclic
    grouping that makes every producer finish one tick before its
    consumer on the NEXT rank (chunk boundaries included: rank p-1's
    chunk c feeds rank 0's chunk c+1 with the same uniform +1 ring
    ppermute). Backwards mirror with chunk order reversed and constant
    offset ``C = (V-1)p + 2(p-1)``; at V=1 both formulas collapse to
    :func:`one_f_one_b_value_and_grad`'s schedule.

    ``chunk_params``: pytree whose leaves carry a leading ``[V, ...]``
    chunk dim (this rank's chunks, cyclic layout). ``stage_fn`` must
    preserve activation shape (same contract as the other schedules).
    Requires ``m % p == 0`` (the Megatron interleave constraint — the
    grouped schedule needs whole microbatch groups).

    Returns ``(loss, chunk_grads)`` — grads stacked ``[V, ...]`` like
    the params, scaled for the mean loss over microbatches. The
    ``loss_params`` / ``return_input_grads`` channels behave exactly as
    on :func:`one_f_one_b_value_and_grad` (last-virtual-stage head
    grads; stage-0 input cotangents for an outside-the-pipeline
    embedding).
    """
    p = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    v = int(num_chunks)
    leading = jax.tree.leaves(chunk_params)[0].shape[0]
    if leading != v:
        # Silent dynamic-index clipping would otherwise train chunk
        # v-1's params in place of the missing virtual stages.
        raise ValueError(
            f"chunk_params carry {leading} chunks but num_chunks={v}")
    m = x_microbatches.shape[0]
    if m % p != 0:
        raise ValueError(
            f"interleaved 1F1B needs microbatches % pp == 0, got {m} % "
            f"{p} (the grouped schedule consumes whole groups)")
    mb_shape = x_microbatches.shape[1:]
    dtype = x_microbatches.dtype
    mv = m * v
    c_off = (v - 1) * p + 2 * (p - 1)
    # Forward-order-keyed stash of chunk INPUTS for the rematerialized
    # backward; capacity bounds the max (written i) - (read i_match).
    ring_cap = c_off + (v - 1) * p + 1

    fwd0 = jnp.zeros(mb_shape, dtype)
    bwd0 = jnp.zeros(mb_shape, dtype)
    ring0 = jnp.zeros((ring_cap,) + mb_shape, dtype)
    grads0 = jax.tree.map(jnp.zeros_like, chunk_params)
    loss0 = jnp.zeros((), jnp.float32)
    lgrads0 = (jax.tree.map(jnp.zeros_like, loss_params)
               if loss_params is not None else None)
    dx0_buf0 = (jnp.zeros((m,) + mb_shape, dtype)
                if return_input_grads else None)

    def decode_f(i):
        c = (i // p) % v
        j = (i // (p * v)) * p + (i % p)
        return c, j

    def tick(carry, t):
        fwd_in, bwd_in, ring, grads, loss_acc, lgrads, dx0_buf = carry

        # ---- forward: rank r's (t - r)-th chunk execution ------------
        i = t - rank
        f_active = (i >= 0) & (i < mv)
        i_c = jnp.clip(i, 0, mv - 1)
        c_f, j_f = decode_f(i_c)
        params_f = jax.tree.map(lambda a: a[c_f], chunk_params)
        # Virtual stage 0 (rank 0, chunk 0) ingests the raw microbatch;
        # everything else consumes the ring-delivered activation.
        ingest = (rank == 0) & (c_f == 0)
        x_in = jnp.where(ingest, x_microbatches[j_f], fwd_in)
        slot_w = i_c % ring_cap
        ring = ring.at[slot_w].set(
            jnp.where(f_active, x_in, ring[slot_w]))
        y = stage_fn(params_f, x_in)
        y = jnp.where(f_active, y, 0)

        # ---- backward: mirrored order, reversed chunk cycle ----------
        ib = t - c_off + rank
        b_active = (ib >= 0) & (ib < mv)
        ib_c = jnp.clip(ib, 0, mv - 1)
        # Same decode as the forward with the chunk cycle reversed —
        # one formula, so stash and read cannot desynchronize.
        cb_raw, j_b = decode_f(ib_c)
        cb = v - 1 - cb_raw
        # The forward-order index that stashed this (chunk, microbatch).
        i_match = cb * p + (ib_c // (p * v)) * (p * v) + (ib_c % p)
        x_saved = ring[i_match % ring_cap]
        params_b = jax.tree.map(lambda a: a[cb], chunk_params)

        tgt = jax.tree.map(lambda a: a[j_b], targets_microbatches)
        is_lastv = (rank == p - 1) & (cb == v - 1)
        loss_j, seed, lgrads = _loss_and_seed(
            loss_fn, loss_params, y, tgt, lgrads,
            (b_active & is_lastv).astype(jnp.float32))
        loss_acc = loss_acc + jnp.where(b_active & is_lastv,
                                        loss_j.astype(jnp.float32), 0.0)
        din = jnp.where(is_lastv, seed.astype(dtype), bwd_in)

        _, vjp = jax.vjp(stage_fn, params_b, x_saved)
        dparams, dx = vjp(din)
        bmask = b_active.astype(dtype)
        grads = jax.tree.map(
            lambda g, d: g.at[cb].add(bmask * d.astype(g.dtype)),
            grads, dparams)
        dx = dx * bmask
        if dx0_buf is not None:
            # Virtual stage 0's input cotangent (rank 0, chunk 0).
            keep = (b_active & (rank == 0) & (cb == 0)).astype(dtype)
            dx0_buf = dx0_buf.at[j_b].add(keep * dx)

        fwd_next = lax.ppermute(y, axis,
                                [(s, (s + 1) % p) for s in range(p)])
        bwd_next = lax.ppermute(dx, axis,
                                [(s, (s - 1) % p) for s in range(p)])
        return (fwd_next, bwd_next, ring, grads, loss_acc, lgrads,
                dx0_buf), None

    total_ticks = mv + c_off
    (_, _, _, grads, loss_acc, lgrads, dx0_buf), _ = lax.scan(
        tick, (fwd0, bwd0, ring0, grads0, loss0, lgrads0, dx0_buf0),
        jnp.arange(total_ticks))

    loss = lax.psum(loss_acc * (rank == p - 1), axis) / m
    return _pipeline_out(loss, grads, lgrads, dx0_buf, m, loss_params,
                         return_input_grads)


def make_pipeline_fn(mesh: Mesh, stage_fn, stacked_params_template, *,
                     axis: str = "pp", extra_in_specs: Tuple = ()):
    """Jitted wrapper: (stacked_params, x_microbatches) -> outputs."""
    import functools

    pspecs = stage_specs(stacked_params_template, axis)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(pspecs, P()) + extra_in_specs,
        out_specs=P(), check_vma=False)
    def run(stacked_params, x_mb):
        params_local = jax.tree.map(lambda a: a[0], stacked_params)
        return gpipe_apply(stage_fn, params_local, x_mb, axis=axis)

    return run
