"""Deep Gradient Compression as an optax transform.

Role of the reference DGC stack (``operators/optimizers/dgc_momentum_op``,
external dgc lib ``cmake/external/dgc.cmake``, strategy switch
``distributed_strategy.proto`` dgc/dgc_configs): top-k gradient
sparsification with local error accumulation (residual feedback), ramping
up after ``rampup_begin_step``.

TPU-first: under pjit the gradient allreduce is compiler-inserted, so DGC
cannot shrink the collective payload the way the NCCL-era reference did.
What it *can* still provide — and what makes it worth keeping API parity —
is the optimization-algorithm half: error-feedback sparsification of the
applied update (momentum correction per the DGC paper). The transform
zeroes all but the top-(1-sparsity) fraction of |grad + residual| entries
per leaf and carries the rest as residual into the next step — numerically
identical to reference DGC with compression ratio (1-sparsity).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class DGCState(NamedTuple):
    step: jax.Array          # int32 scalar
    residual: optax.Updates  # per-leaf error accumulator


def dgc_transform(sparsity: float = 0.999,
                  rampup_begin_step: int = 0) -> optax.GradientTransformation:
    """Error-feedback top-k sparsification (keep fraction = 1 - sparsity)."""
    if not 0.0 <= sparsity < 1.0:
        raise ValueError(f"sparsity must be in [0, 1), got {sparsity}")
    keep_q = sparsity * 100.0  # percentile below which entries are dropped

    def init(params):
        return DGCState(step=jnp.zeros((), jnp.int32),
                        residual=jax.tree_util.tree_map(jnp.zeros_like,
                                                        params))

    def update(grads, state, params=None):
        del params
        active = state.step >= rampup_begin_step

        def compress(g, r):
            acc = g + r
            mag = jnp.abs(acc)
            # Per-leaf threshold at the sparsity percentile; scalars and
            # tiny leaves keep everything (threshold 0 when keep-all).
            thr = jnp.percentile(mag.ravel(), keep_q) if mag.size > 1 \
                else jnp.zeros(())
            mask = mag >= thr
            sparse = jnp.where(mask, acc, 0.0)
            new_resid = jnp.where(mask, 0.0, acc)
            # Before rampup: dense pass-through, residual stays zero.
            out = jnp.where(active, sparse, g)
            resid = jnp.where(active, new_resid, r)
            return out, resid

        # One compress per leaf; tree_transpose splits the (out, resid)
        # pairs against the ORIGINAL treedef, which stays correct even
        # when the grads pytree itself contains tuples as containers.
        pairs = jax.tree_util.tree_map(compress, grads, state.residual)
        outs, resids = jax.tree_util.tree_transpose(
            jax.tree_util.tree_structure(grads),
            jax.tree_util.tree_structure((0, 0)), pairs)
        return outs, DGCState(step=state.step + 1, residual=resids)

    return optax.GradientTransformation(init, update)
