"""Tensor (model) parallel layer library.

Role of the reference's dygraph TP layers
(``fleet/meta_parallel/parallel_layers/mp_layers.py``):
``VocabParallelEmbedding`` (:30), ``ColumnParallelLinear`` (:95),
``RowParallelLinear`` (:171), ``ParallelCrossEntropy`` (:251) and the C++
ops ``c_embedding``, ``c_softmax_with_cross_entropy``
(``operators/collective/``).

TPU-first: each layer is a pure function designed to run inside
``shard_map`` over the ``mp`` mesh axis, with parameters held as the LOCAL
shard (leading/trailing dim already split). Collectives are explicit lax
ops on the mp axis — XLA schedules them over ICI. Init helpers return
full-size params plus the PartitionSpec to shard them with, so pjit can
alternatively partition automatically (GSPMD path).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


# -- vocab-parallel embedding ----------------------------------------------

def vocab_parallel_embedding_init(rng: jax.Array, vocab: int, dim: int,
                                  scale: float = 0.02):
    """Full table [vocab, dim]; shard with P("mp", None)."""
    return {"table": jax.random.normal(rng, (vocab, dim)) * scale}, \
        {"table": P("mp", None)}


def vocab_parallel_embedding(params: Dict, ids: jax.Array, *, axis: str = "mp"
                             ) -> jax.Array:
    """ids [**shape] int32 (replicated over mp) → [**shape, dim].

    Local shard holds rows [rank*V_local, (rank+1)*V_local); out-of-range
    ids contribute zeros, psum combines (role of c_embedding fwd +
    allreduce, mp_layers.py:75-85).
    """
    table = params["table"]           # local [V_local, D]
    v_local = table.shape[0]
    rank = lax.axis_index(axis)
    lo = rank * v_local
    local_ids = ids - lo
    in_range = (local_ids >= 0) & (local_ids < v_local)
    emb = table[jnp.clip(local_ids, 0, v_local - 1)]
    emb = jnp.where(in_range[..., None], emb, 0.0)
    return lax.psum(emb, axis)


# -- column/row parallel linear --------------------------------------------

def column_parallel_linear_init(rng: jax.Array, in_dim: int, out_dim: int):
    """W [in, out] sharded on out: P(None, "mp"); bias sharded on "mp"."""
    bound = (6.0 / (in_dim + out_dim)) ** 0.5
    w = jax.random.uniform(rng, (in_dim, out_dim), jnp.float32, -bound, bound)
    return {"w": w, "b": jnp.zeros((out_dim,))}, \
        {"w": P(None, "mp"), "b": P("mp")}


def column_parallel_linear(params: Dict, x: jax.Array, *,
                           gather_output: bool = False, axis: str = "mp"
                           ) -> jax.Array:
    """x [.., in] replicated → [.., out/mp] (or [.., out] if gathered).

    Identity fwd / allreduce bwd on x happens automatically through
    autodiff of the replicated input (role of ColumnParallelLinear,
    mp_layers.py:95).
    """
    y = jnp.dot(x, params["w"], preferred_element_type=jnp.float32)
    y = y + params["b"]
    if gather_output:
        y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel_linear_init(rng: jax.Array, in_dim: int, out_dim: int):
    """W [in, out] sharded on in: P("mp", None); bias replicated."""
    bound = (6.0 / (in_dim + out_dim)) ** 0.5
    w = jax.random.uniform(rng, (in_dim, out_dim), jnp.float32, -bound, bound)
    return {"w": w, "b": jnp.zeros((out_dim,))}, \
        {"w": P("mp", None), "b": P()}


def row_parallel_linear(params: Dict, x: jax.Array, *,
                        input_is_parallel: bool = True, axis: str = "mp"
                        ) -> jax.Array:
    """x [.., in/mp] (parallel) → [.., out] replicated via psum (role of
    RowParallelLinear allreduce fwd, mp_layers.py:171)."""
    if not input_is_parallel:
        rank = lax.axis_index(axis)
        in_local = params["w"].shape[0]
        x = lax.dynamic_slice_in_dim(x, rank * in_local, in_local,
                                     axis=x.ndim - 1)
    y = jnp.dot(x, params["w"], preferred_element_type=jnp.float32)
    y = lax.psum(y, axis)
    return y + params["b"]


# -- vocab-parallel cross entropy ------------------------------------------

def parallel_cross_entropy(logits_local: jax.Array, labels: jax.Array, *,
                           axis: str = "mp") -> jax.Array:
    """Softmax-CE over vocab sharded on mp (role of ParallelCrossEntropy /
    c_softmax_with_cross_entropy_op.cu).

    logits_local [.., V/mp]; labels [..] int32 global vocab ids.
    Returns per-token loss [..]. Numerically stable: global max via pmax,
    global sum-exp via psum, target logit fetched from its owner shard.
    """
    v_local = logits_local.shape[-1]
    rank = lax.axis_index(axis)
    lo = rank * v_local

    # Stabilizer max: analytically gradient-free (softmax-CE grad is
    # independent of the shift). pmax has no differentiation rule even on
    # a stopped operand, so take the cross-shard max via all_gather (which
    # is differentiable) over a stop_gradient'ed local max.
    local_max = lax.stop_gradient(jnp.max(logits_local, axis=-1))
    m = jnp.max(lax.all_gather(local_max, axis, axis=0, tiled=False),
                axis=0)                                           # [..]
    z = lax.psum(jnp.sum(jnp.exp(logits_local - m[..., None]), axis=-1),
                 axis)                                            # [..]
    local_label = labels - lo
    in_range = (local_label >= 0) & (local_label < v_local)
    tgt = jnp.take_along_axis(
        logits_local, jnp.clip(local_label, 0, v_local - 1)[..., None],
        axis=-1)[..., 0]
    tgt = lax.psum(jnp.where(in_range, tgt, 0.0), axis)           # [..]
    return jnp.log(z) + m - tgt
