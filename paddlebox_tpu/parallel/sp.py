"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

ABSENT from the 2022-era reference (SURVEY.md §5 "Long-context / sequence
parallelism: not present") — designed TPU-first here as a first-class
capability: long sequences are sharded over the ``sp`` mesh axis and
attention crosses shards either by

- **ring attention**: K/V blocks rotate around the sp ring via
  ``ppermute`` (ICI neighbor exchange) while each device keeps a running
  flash-attention-style online softmax over its Q block — ``lax.scan``
  keeps the rotation one fused XLA loop so transfer overlaps compute, or
- **Ulysses**: all-to-all exchanging the sequence axis for the head axis,
  so each device runs full-sequence attention for a head subset.

Both are pure functions for use inside ``shard_map`` with q/k/v already
sequence-sharded: [B, S_local, H, Dh].
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30


def _block_attn(q, k, v, bias, scale):
    """Un-normalized partial attention of one q-block against one kv-block.

    Returns (numerator [B,Sq,H,D], row-max m [B,Sq,H], row-sum l [B,Sq,H]).
    Fully-masked rows yield m=_NEG_BIG, l=0, num=0 (no NaNs).
    """
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m = jnp.maximum(jnp.max(s, axis=-1), _NEG_BIG)
    p = jnp.exp(s - m[..., None])          # exp(-inf - finite) = 0 for masks
    l = jnp.sum(p, axis=-1)
    num = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(p.dtype),
                     preferred_element_type=jnp.float32)
    return num, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis: str = "sp", causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention over a sequence sharded on the sp ring.

    q/k/v [B, S_local, H, Dh] (local shard). Output [B, S_local, H, Dh]
    exactly equals full-sequence attention (online-softmax merge across
    ring steps). causal masks by GLOBAL position (rank * S_local + t).
    """
    n = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    b, s_local, h, d = q.shape
    if scale is None:
        scale = float(d) ** -0.5
    q_pos = rank * s_local + jnp.arange(s_local)

    def step(carry, block_idx):
        k_blk, v_blk, acc, m_run, l_run = carry
        # Rotation sends blocks to rank+1, so after block_idx rotations the
        # block we hold originated at rank - block_idx.
        src = (rank - block_idx) % n
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :],
                             0.0, -jnp.inf)[None, :, None, :]
        else:
            bias = None
        num, m_blk, l_blk = _block_attn(q, k_blk, v_blk, bias, scale)
        m_new = jnp.maximum(m_run, m_blk)
        w_old = jnp.exp(m_run - m_new)
        w_blk = jnp.exp(m_blk - m_new)
        acc = acc * w_old[..., None] + num * w_blk[..., None]
        l_run = l_run * w_old + l_blk * w_blk
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return (k_blk, v_blk, acc, m_new, l_run), None

    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    m0 = jnp.full((b, s_local, h), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, s_local, h), jnp.float32)
    (_, _, acc, _, l_run), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(n))
    out = acc / jnp.maximum(l_run, 1e-20)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis: str = "sp", causal: bool = False,
                      scale: Optional[float] = None) -> jax.Array:
    """DeepSpeed-Ulysses-style SP: all-to-all seq<->head, full-sequence
    attention on a head subset, all-to-all back.

    q/k/v [B, S_local, H, Dh] with H divisible by the sp axis size.
    """
    n = lax.axis_size(axis)
    b, s_local, h, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by sp axis {n}")
    if scale is None:
        scale = float(d) ** -0.5

    def seq_to_head(x):
        # [B, S_local, H, D] -> [B, S, H/n, D]: exchange seq for heads.
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    s_full = qg.shape[1]
    if causal:
        pos = jnp.arange(s_full)
        bias = jnp.where(pos[:, None] >= pos[None, :],
                         0.0, -jnp.inf)[None, :, None, :]
    else:
        bias = None
    num, m, l = _block_attn(qg, kg, vg, bias, scale)
    out = num / jnp.maximum(l, 1e-20)[..., None]
    return head_to_seq(out.astype(q.dtype))
