"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

Role of the reference MoE stack (``python/paddle/incubate/distributed/
models/moe/moe_layer.py`` MoELayer, ``gate/gshard_gate.py``, C++
``global_scatter/global_gather`` ops, ``operators/collective/
global_scatter_op.cc``): top-k gating, capacity-limited dispatch to
experts sharded across devices, weighted combine on return.

TPU-first: GShard-style static-shape dispatch — position-in-expert via
cumsum over one-hot assignments, fixed capacity buffers, one all_to_all
out and one back (replacing brpc/NCCL global_scatter/global_gather). The
einsum-heavy dispatch/combine maps onto the MXU.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def top2_gate(logits: jax.Array, *, capacity: int
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """GShard top-2 gating (role of gshard_gate.py).

    logits [T, E] → (combine [T, E, C], dispatch [T, E, C] bool, aux_loss).
    combine[t, e, c] is the gate weight with which token t lands in
    expert e's capacity slot c.
    """
    t, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)

    # Top-1 and top-2 expert per token.
    idx1 = jnp.argmax(gates, axis=-1)                          # [T]
    mask1 = jax.nn.one_hot(idx1, e, dtype=gates.dtype)
    gates2 = gates * (1.0 - mask1)
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=gates.dtype)

    # Aux load-balancing loss (mean gate * mean assignment per expert).
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux_loss = jnp.sum(density * density_proxy) * (e * e) / e

    # Capacity positions: top-1 tokens first, then top-2.
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1           # pos in expert
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2 +
            jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(gates * keep1, axis=-1)
    g2 = jnp.sum(gates * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    loc1 = jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32)    # [T]
    loc2 = jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32)

    oh_c1 = jax.nn.one_hot(loc1, capacity, dtype=gates.dtype)  # [T, C]
    oh_c2 = jax.nn.one_hot(loc2, capacity, dtype=gates.dtype)
    combine = (g1[:, None, None] * keep1[:, :, None] * oh_c1[:, None, :] +
               g2[:, None, None] * keep2[:, :, None] * oh_c2[:, None, :])
    dispatch = combine > 0.0
    return combine, dispatch, aux_loss


def moe_layer(gate_w: jax.Array, expert_params: Dict[str, jax.Array],
              expert_fn: Callable[[Dict, jax.Array], jax.Array],
              x: jax.Array, *, axis: str = "ep",
              capacity_factor: float = 1.25
              ) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel MoE layer (call INSIDE shard_map).

    gate_w [F, E_total] (replicated); expert_params: pytree whose leaves
    have leading dim E_local (this device's experts); expert_fn(params_e,
    tokens [N, F]) -> [N, F] is vmapped over local experts.
    x [T_local, F] local tokens. Returns (y [T_local, F], aux_loss).
    """
    n = lax.axis_size(axis)
    t_local, f = x.shape
    e_local = jax.tree.leaves(expert_params)[0].shape[0]
    e_total = e_local * n
    capacity = max(int(capacity_factor * (2 * t_local) / e_total), 1)

    logits = jnp.dot(x, gate_w, preferred_element_type=jnp.float32)
    combine, dispatch, aux = top2_gate(logits, capacity=capacity)

    # Dispatch: [T, E, C] x [T, F] -> [E, C, F] buffers.
    dispatched = jnp.einsum("tec,tf->ecf", dispatch.astype(x.dtype), x,
                            preferred_element_type=jnp.float32)
    # all_to_all: split experts across ep, gather source-device dim:
    # [E_total, C, F] -> [n * E_local, C, F] -> recv [n, E_local, C, F]
    recv = lax.all_to_all(
        dispatched.reshape(n, e_local, capacity, f), axis,
        split_axis=0, concat_axis=0, tiled=False)      # [n, n?..]
    # tiled=False adds a leading axis: [n, 1, e_local, C, F] — normalize.
    recv = recv.reshape(n, e_local, capacity, f)
    # Per-local-expert token batch: [E_local, n*C, F].
    expert_in = recv.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, f)
    expert_out = jax.vmap(expert_fn)(expert_params, expert_in)
    # Return trip.
    back = expert_out.reshape(e_local, n, capacity, f).transpose(1, 0, 2, 3)
    returned = lax.all_to_all(back, axis, split_axis=0, concat_axis=0,
                              tiled=False).reshape(e_total, capacity, f)
    # Combine: [T, E, C] x [E, C, F] -> [T, F].
    y = jnp.einsum("tec,ecf->tf", combine.astype(returned.dtype), returned,
                   preferred_element_type=jnp.float32)
    return y.astype(x.dtype), aux
