"""ZeRO-style parameter/optimizer-state sharding helpers.

Role of the reference sharding stack (``meta_parallel/sharding_parallel.py``,
``sharding/group_sharded_stage{2,3}.py``, static ``ShardingOptimizer``,
``fleet/meta_optimizers/sharding_optimizer.py:46``): stage 1/2 shard
optimizer state + gradients across a sharding group, stage 3 shards the
parameters themselves.

TPU-first: ZeRO is NOT an algorithm here — it is a set of sharding
annotations. Shard a leaf's largest divisible dim over the ``sharding``
mesh axis and jit/pjit does the rest: XLA inserts reduce-scatter for
gradients into sharded state and all-gathers for sharded params at use
sites (exactly the stage-2/3 communication schedule, compiler-scheduled).
These helpers build those PartitionSpecs for arbitrary pytrees.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _spec_for_leaf(shape: Sequence[int], axis_size: int, axis: str,
                   min_size: int) -> P:
    """Shard the largest dim divisible by axis_size; P() if none/small."""
    if int(np.prod(shape)) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % axis_size == 0 and shape[d] >= axis_size:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def zero_specs(tree: Any, mesh: Mesh, *, axis: str = "sharding",
               min_size: int = 1 << 14) -> Any:
    """PartitionSpecs sharding every (large-enough) leaf over ``axis``.

    Apply to optimizer state only → ZeRO-1/2; apply to params too →
    ZeRO-3. Leaves smaller than ``min_size`` elements stay replicated
    (gather latency would dominate).
    """
    axis_size = int(mesh.shape[axis])
    if axis_size == 1:
        return jax.tree.map(lambda _: P(), tree)
    return jax.tree.map(
        lambda x: _spec_for_leaf(np.shape(x), axis_size, axis, min_size),
        tree)


def zero_shardings(tree: Any, mesh: Mesh, *, axis: str = "sharding",
                   min_size: int = 1 << 14,
                   memory_kind: Optional[str] = None) -> Any:
    """NamedShardings version of :func:`zero_specs` (for device_put /
    jit out_shardings). ``memory_kind`` pins the leaves to a device
    memory space (e.g. ``"pinned_host"`` for optimizer-state offload)."""
    kw = {} if memory_kind is None else {"memory_kind": memory_kind}
    return jax.tree.map(lambda s: NamedSharding(mesh, s, **kw),
                        zero_specs(tree, mesh, axis=axis, min_size=min_size))


def shard_tree(tree: Any, mesh: Mesh, *, axis: str = "sharding",
               min_size: int = 1 << 14) -> Any:
    """device_put a pytree with ZeRO shardings (host → sharded HBM)."""
    sh = zero_shardings(tree, mesh, axis=axis, min_size=min_size)
    return jax.tree.map(jax.device_put, tree, sh)


def sharded_dim(spec: P) -> Optional[int]:
    """The dim a :func:`zero_specs` PartitionSpec shards, or None."""
    for d, name in enumerate(spec):
        if name is not None:
            return d
    return None


def zero_slice(tree: Any, specs: Any, axis: str, axis_size: int) -> Any:
    """Inside shard_map: this device's ZeRO shard of a REPLICATED tree.

    ``specs`` is the matching :func:`zero_specs` tree — leaves whose spec
    shards dim ``d`` are dynamic-sliced at ``axis_index(axis)``; P()
    leaves pass through whole. Elementwise optimizers applied to the
    sliced tree compute bit-identical updates to the full-tree update
    (each element sees the same inputs), which is what makes the ZeRO
    step's f32 parity pinnable.
    """
    idx = jax.lax.axis_index(axis)

    def sl(x, spec):
        d = sharded_dim(spec)
        if d is None:
            return x
        size = x.shape[d] // axis_size
        return jax.lax.dynamic_slice_in_dim(x, idx * size, size, axis=d)

    return jax.tree.map(sl, tree, specs)


def zero_all_gather(tree: Any, specs: Any, axis: str) -> Any:
    """Inside shard_map: undo :func:`zero_slice` — tiled all-gather each
    sharded leaf back to its full (replicated) shape. The compiler pairs
    this with the upstream psum into the reduce-scatter/all-gather
    schedule of the weight-update-sharding paper."""
    def ag(x, spec):
        d = sharded_dim(spec)
        if d is None:
            return x
        return jax.lax.all_gather(x, axis, axis=d, tiled=True)

    return jax.tree.map(ag, tree, specs)


def tree_hbm_bytes_per_device(tree: Any) -> int:
    """Measured per-device DEVICE-memory bytes of a pytree of placed
    ``jax.Array`` leaves: each leaf contributes its per-shard size under
    its actual sharding; leaves pinned to a host memory kind contribute
    zero (they are host bytes — the whole point of offload). This is how
    the benches record ``dense/opt_state_hbm_bytes`` as a measurement of
    the live arrays, not an assertion about the flags."""
    total = 0
    for x in jax.tree.leaves(tree):
        if not isinstance(x, jax.Array):
            total += int(np.asarray(x).nbytes)
            continue
        sh = x.sharding
        kind = getattr(sh, "memory_kind", None)
        # "Host" is relative to the backend: CPU devices' DEFAULT memory
        # kind is itself "unpinned_host", so the test is whether the leaf
        # was pinned AWAY from the device default (offload), not whether
        # the kind's name mentions host.
        if kind is not None:
            try:
                default = x.devices().pop().default_memory().kind
            except Exception:
                default = None
            if default is not None and kind != default:
                continue
        try:
            shard_elems = int(np.prod(sh.shard_shape(x.shape)))
        except Exception:  # sharding without shard_shape (fully manual)
            shard_elems = int(np.prod(x.shape))
        total += shard_elems * x.dtype.itemsize
    return total


def reduce_gradients(grads: Any, axis: Any = "dp", *,
                     wire_dtype: Optional[str] = None,
                     block: Optional[int] = None) -> Any:
    """Cross-replica gradient reduce of the ZeRO stack — the explicit
    stage-1/2 reduce for step functions that sync grads by hand instead
    of leaning on sharding annotations (the trainer's dense sync does).

    Routes through ``parallel/collective.quantized_psum`` behind
    ``FLAGS_dense_allreduce_dtype``: ``f32`` is a verbatim ``lax.psum``
    (bit-identical), ``bf16``/``int8`` narrow the wire with f32
    accumulation (per-block scales via ``FLAGS_embedding_quant_block``).
    Call under shard_map / pjit manual axes with ``axis`` in scope.
    """
    from paddlebox_tpu.core import flags
    from paddlebox_tpu.parallel.collective import quantized_psum
    if wire_dtype is None:
        wire_dtype = str(flags.flag("dense_allreduce_dtype"))
    if block is None:
        block = int(flags.flag("embedding_quant_block"))
    return quantized_psum(grads, axis, wire_dtype=wire_dtype, block=block)


def _resolve_host_kind(mesh: Mesh, requested: str) -> str:
    """Map the canonical host memory kind to what the backend actually
    exposes: TPU runtimes advertise ``pinned_host``; CPU backends (the
    test mesh) only ``unpinned_host``. Asking for a kind the device does
    not have fails at device_put — resolve once at construction so the
    offload wrapper runs unchanged on both."""
    try:
        kinds = {m.kind for m in mesh.devices.flat[0].addressable_memories()}
    except Exception:  # backend without memory-space introspection
        return requested
    if requested in kinds:
        return requested
    for k in ("pinned_host", "unpinned_host"):
        if k in kinds:
            return k
    return requested


class OffloadedOptimizer:
    """optax-compatible wrapper keeping the optimizer STATE in host memory.

    Role of the reference's sharding optimizer-state offload (static
    ``ShardingOptimizer`` offload pass,
    ``fleet/meta_optimizers/sharding_optimizer.py:540-558`` +
    ``sharding/offload_helper.py``): Adam moments etc. live in host
    ("pinned_host") memory, crossing into HBM only transiently around the
    update — HBM holds ~zero optimizer-state bytes between steps, buying
    headroom for params/activations at the cost of PCIe/host-link traffic
    per update (the reference makes the same trade with cudaMallocHost
    buffers).

    The wrapped ``update`` is its OWN jitted program whose state inputs
    and outputs are pinned to ``memory_kind`` via shardings (sharded over
    ``axis`` where divisible — ZeRO-1/2 placement — so each host stores
    only its shard). Use exactly like the wrapped optax transformation:

        tx = OffloadedOptimizer(optax.adam(1e-3), mesh)
        state = tx.init(params)          # state leaves on pinned_host
        updates, state = tx.update(grads, state, params)
    """

    def __init__(self, tx, mesh: Mesh, *, axis: str = "sharding",
                 min_size: int = 0, memory_kind: str = "pinned_host"):
        self._tx = tx
        self._mesh = mesh
        self._axis = axis
        self._min_size = min_size
        self._memory_kind = _resolve_host_kind(mesh, memory_kind)
        # Cache keyed on the state TREEDEF: an optimizer swap or a param
        # tree that grew/shrank leaves produces a different structure,
        # and replaying the old jit/shardings against it would either
        # throw a structure error or (worse) silently place leaves with
        # a stale layout. One entry is live at a time — state structure
        # changes are rare events (re-init), not per-step.
        self._cache_treedef = None
        self._jit_update = None
        self._jit_update_apply = None
        self._dev_sh = None
        self._host_sh = None

    def _state_shardings(self, state: Any) -> Any:
        """Host-pinned shardings for array leaves; SCALAR leaves (e.g.
        adam's step count) stay in device memory — they are bytes, and
        XLA's SPMD partitioner rejects host-placement annotations on
        scalars under a mesh."""
        host = zero_shardings(state, self._mesh, axis=self._axis,
                              min_size=self._min_size,
                              memory_kind=self._memory_kind)
        dev = zero_shardings(state, self._mesh, axis=self._axis,
                             min_size=self._min_size)
        return jax.tree.map(
            lambda x, h, d: d if np.ndim(x) == 0 else h, state, host, dev)

    def init(self, params: Any) -> Any:
        state = self._tx.init(params)
        return jax.tree.map(jax.device_put, state,
                            self._state_shardings(state))

    def update(self, grads: Any, state: Any, params: Any = None):
        # Stage host → device OUTSIDE the jitted program (XLA's SPMD
        # partitioner currently rejects memory-space annotations mixed
        # with scalar outputs inside one program); the update itself is a
        # plain all-device jitted call, then the new state streams back
        # to its host pinning. The per-step cost is the two transfers —
        # inherent to offload (the reference pays the same PCIe trips,
        # offload_helper.py).
        # Shapes participate too: a same-structure state whose leaves
        # changed shape (param growth) needs fresh specs — divisibility
        # decides which dim shards.
        self._refresh_cache(state)
        s_dev = jax.tree.map(
            lambda x, d: x if np.ndim(x) == 0 else jax.device_put(x, d),
            state, self._dev_sh)
        updates, new_state = self._jit_update(grads, s_dev, params)
        new_state = jax.tree.map(
            lambda x, h: x if np.ndim(x) == 0 else jax.device_put(x, h),
            new_state, self._host_sh)
        return updates, new_state

    def update_apply(self, grads: Any, state: Any, params: Any):
        """``update`` + ``optax.apply_updates`` in ONE jitted program,
        returning ``(new_params, new_state)``. Bit-parity matters here:
        a separate apply program materializes ``updates`` and rounds the
        scale-and-add differently (no FMA fusion with the moment math)
        than an in-step fused update — one program keeps the offload
        path bit-identical to the non-offload trainer step in f32."""
        self._refresh_cache(state, params=params)
        s_dev = jax.tree.map(
            lambda x, d: x if np.ndim(x) == 0 else jax.device_put(x, d),
            state, self._dev_sh)
        new_params, new_state = self._jit_update_apply(grads, s_dev, params)
        new_state = jax.tree.map(
            lambda x, h: x if np.ndim(x) == 0 else jax.device_put(x, h),
            new_state, self._host_sh)
        return new_params, new_state

    def _refresh_cache(self, state: Any, params: Any = None) -> None:
        treedef = (jax.tree.structure(state),
                   tuple(np.shape(x) for x in jax.tree.leaves(state)))
        if self._jit_update is None or treedef != self._cache_treedef:
            import optax
            dev_sh = zero_shardings(state, self._mesh, axis=self._axis,
                                    min_size=self._min_size)
            self._dev_sh = dev_sh
            self._host_sh = self._state_shardings(state)
            self._cache_treedef = treedef
            # No donation: scalar leaves pass through the staging map
            # uncopied, and donating them would delete the caller's state
            # buffers (optax's contract leaves the input state readable).
            self._jit_update = jax.jit(
                lambda g, s, p: self._tx.update(g, s, p))

            def _upd_apply(g, s, p):
                u, s2 = self._tx.update(g, s, p)
                return optax.apply_updates(p, u), s2

            # Pin new_params to the INPUT params' placement: with
            # inference, the sharded state leaks its sharding into p+u
            # and the caller's replicated params silently become
            # ZeRO-3-sharded (this wrapper is a state offload, not a
            # param shard).
            out_sh = None
            if params is not None and all(
                    isinstance(x, jax.Array)
                    for x in jax.tree.leaves(params)):
                out_sh = (jax.tree.map(lambda x: x.sharding, params), None)
            self._jit_update_apply = jax.jit(
                _upd_apply,
                **({} if out_sh is None else {"out_shardings": out_sh}))
