"""ZeRO-style parameter/optimizer-state sharding helpers.

Role of the reference sharding stack (``meta_parallel/sharding_parallel.py``,
``sharding/group_sharded_stage{2,3}.py``, static ``ShardingOptimizer``,
``fleet/meta_optimizers/sharding_optimizer.py:46``): stage 1/2 shard
optimizer state + gradients across a sharding group, stage 3 shards the
parameters themselves.

TPU-first: ZeRO is NOT an algorithm here — it is a set of sharding
annotations. Shard a leaf's largest divisible dim over the ``sharding``
mesh axis and jit/pjit does the rest: XLA inserts reduce-scatter for
gradients into sharded state and all-gathers for sharded params at use
sites (exactly the stage-2/3 communication schedule, compiler-scheduled).
These helpers build those PartitionSpecs for arbitrary pytrees.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _spec_for_leaf(shape: Sequence[int], axis_size: int, axis: str,
                   min_size: int) -> P:
    """Shard the largest dim divisible by axis_size; P() if none/small."""
    if int(np.prod(shape)) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: -shape[d])
    for d in dims:
        if shape[d] % axis_size == 0 and shape[d] >= axis_size:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def zero_specs(tree: Any, mesh: Mesh, *, axis: str = "sharding",
               min_size: int = 1 << 14) -> Any:
    """PartitionSpecs sharding every (large-enough) leaf over ``axis``.

    Apply to optimizer state only → ZeRO-1/2; apply to params too →
    ZeRO-3. Leaves smaller than ``min_size`` elements stay replicated
    (gather latency would dominate).
    """
    axis_size = int(mesh.shape[axis])
    if axis_size == 1:
        return jax.tree.map(lambda _: P(), tree)
    return jax.tree.map(
        lambda x: _spec_for_leaf(np.shape(x), axis_size, axis, min_size),
        tree)


def zero_shardings(tree: Any, mesh: Mesh, *, axis: str = "sharding",
                   min_size: int = 1 << 14) -> Any:
    """NamedShardings version of :func:`zero_specs` (for device_put /
    jit out_shardings)."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        zero_specs(tree, mesh, axis=axis, min_size=min_size))


def shard_tree(tree: Any, mesh: Mesh, *, axis: str = "sharding",
               min_size: int = 1 << 14) -> Any:
    """device_put a pytree with ZeRO shardings (host → sharded HBM)."""
    sh = zero_shardings(tree, mesh, axis=axis, min_size=min_size)
    return jax.tree.map(jax.device_put, tree, sh)
