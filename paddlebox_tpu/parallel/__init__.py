"""Distributed parallelism: hybrid mesh topology, collectives, TP/PP/SP/EP.

Role of the reference's distributed stacks:
- ``python/paddle/distributed/fleet/base/topology.py`` (HybridCommunicateGroup)
- ``paddle/fluid/operators/collective/`` + ``distributed/collective/``
  (NCCL collective ops / ProcessGroupNCCL)
- ``fleet/meta_parallel/`` (TP/PP layers and schedules)

TPU-first: communication groups are named axes of one
``jax.sharding.Mesh``; collectives are XLA ops (`psum`, `all_gather`,
`ppermute`, ...) inserted by the partitioner or written explicitly inside
``shard_map`` — there is no NCCL analog to manage.
"""

from paddlebox_tpu.parallel.topology import (
    HybridTopology,
    build_mesh,
    get_default_topology,
    set_default_topology,
)
from paddlebox_tpu.parallel import auto
from paddlebox_tpu.parallel import collective
from paddlebox_tpu.parallel import dgc
from paddlebox_tpu.parallel import moe
from paddlebox_tpu.parallel import pp
from paddlebox_tpu.parallel import sp
from paddlebox_tpu.parallel import tp
from paddlebox_tpu.parallel import zero

__all__ = [
    "HybridTopology",
    "auto",
    "build_mesh",
    "collective",
    "dgc",
    "get_default_topology",
    "moe",
    "pp",
    "set_default_topology",
    "sp",
    "tp",
    "zero",
]
