"""AUC-runner: slot-replacement feature-importance evaluation.

Role of the reference's AUC-runner mode (``box_wrapper.h:900-989`` with
``SlotsShuffle``, ``box_wrapper.h:1190`` / ``BoxPSDataset.slots_shuffle``):
rank each slot's contribution to a trained model by shuffling that slot's
values across records (decorrelating it from the label), re-evaluating
AUC, and reporting the degradation — a large drop means the slot carries
real signal; a near-zero drop flags a dead feature whose embedding table
can be evicted.

The eval path is read-only (``CTRTrainer.eval_pass`` aborts the pass
without write-back), so importance runs are safe against a production
store between training passes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from paddlebox_tpu.core import log, monitor


def slot_replacement_eval(trainer, dataset, *,
                          slots: Optional[Sequence[str]] = None,
                          seed: int = 0) -> Dict[str, object]:
    """Evaluate per-slot AUC degradation on a trained CTRTrainer.

    Returns ``{"base_auc", "base_loss", "slots": {name: {"auc",
    "auc_drop", "loss"}}, "ranking": [names, most important first]}``.
    The dataset is restored to its original content afterwards.

    Results also land in the metric registry — ``quality/base_auc``
    plus per-slot ``quality/slot_auc/<slot>`` /
    ``quality/slot_auc_drop/<slot>`` gauges — so per-slot AUC
    degradation is recordable through the telemetry plane (JSONL
    export, ``metrics_snapshot`` scrape, ``bench.py deepfm
    --slot-auc``) instead of print-only.
    """
    base = trainer.eval_pass(dataset)
    names = list(slots) if slots is not None else [
        s.name for s in trainer.feed_config.sparse_slots]
    snap = dataset.snapshot_chunks()
    per_slot: Dict[str, Dict[str, float]] = {}
    try:
        for name in names:
            dataset.slots_shuffle([name], seed=seed)
            st = trainer.eval_pass(dataset)
            per_slot[name] = {
                "auc": float(st["auc"]),
                "auc_drop": float(base["auc"] - st["auc"]),
                "loss": float(st["loss"]),
            }
            dataset.restore_chunks(snap)
            log.vlog(1, "auc_runner slot %s: auc %.5f (drop %.5f)",
                     name, per_slot[name]["auc"],
                     per_slot[name]["auc_drop"])
    finally:
        dataset.restore_chunks(snap)
    ranking: List[str] = sorted(
        per_slot, key=lambda n: per_slot[n]["auc_drop"], reverse=True)
    monitor.set_gauge("quality/base_auc", float(base["auc"]))
    for name, st in per_slot.items():
        monitor.set_gauge(f"quality/slot_auc/{name}", st["auc"])
        monitor.set_gauge(f"quality/slot_auc_drop/{name}",
                          st["auc_drop"])
    return {"base_auc": float(base["auc"]),
            "base_loss": float(base["loss"]),
            "slots": per_slot,
            "ranking": ranking}
