"""DayRunner: the production day/pass training loop.

Role of the reference's outer CTR main loop (SURVEY.md §3.1 + FleetUtil,
``fleet_util.py:368-1196``): per day — for each online pass, load that
pass's data splits, shuffle, ``begin_pass → train → end_pass``, publish a
pass-level delta; at day end — shrink the table, dump the day-level base,
and publish both through the atomic done-file index. On restart, resume
from the done-file recovery chain (last base + following deltas), which
is exactly what the elastic manager's membership-change callback needs.

TPU-first: the runner is a thin host orchestration shell — all heavy
work is already in Dataset (threaded columnar load), PassEngine (table
build), and CTRTrainer's single jitted step. File layout convention:
``<data_root>/<day>/<split>/part-*`` with pass groups from
``get_online_pass_interval``.
"""

from __future__ import annotations

import glob
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.checkpoint.protocol import (CheckpointProtocol,
                                               get_online_pass_interval)
from paddlebox_tpu.core import (faults, flags, log, monitor,
                                pipeline_stats, quality, report, timers,
                                trace, watchdog)
from paddlebox_tpu.data.dataset import Dataset


class DayRunner:
    """Drives a CTRTrainer through days of pass-addressed data."""

    def __init__(self, trainer, feed_config, output_root: str, *,
                 data_root: str = "",
                 split_interval: int = 60, split_per_pass: int = 1,
                 hours: Sequence[int] = tuple(range(24)),
                 is_data_hourly_placed: bool = True,
                 shuffle: bool = True,
                 num_reader_threads: int = 4,
                 filelist_fn: Optional[Callable[[str, List[str]],
                                                List[str]]] = None,
                 min_show_shrink: float = 0.0,
                 save_xbox: bool = False,
                 pipeline_passes: bool = True,
                 is_rank0: bool = True,
                 pass_boundary_hook: Optional[Callable[[str, int],
                                                       None]] = None,
                 pass_retry_hook: Optional[Callable[[str, int,
                                                     BaseException],
                                                    None]] = None):
        self.trainer = trainer
        self.feed_config = feed_config
        self.data_root = data_root
        self.ckpt = CheckpointProtocol(output_root, is_rank0=is_rank0)
        self.pass_splits = get_online_pass_interval(
            list(hours), split_interval, split_per_pass,
            is_data_hourly_placed)
        self.shuffle = shuffle
        self.num_reader_threads = num_reader_threads
        self.filelist_fn = filelist_fn or self._default_filelist
        self.min_show_shrink = min_show_shrink
        self.save_xbox = save_xbox  # serving export per pass (xbox role)
        # Overlap pass k+1's data load + table build with pass k's
        # training (role of PreLoadIntoMemory/WaitFeedPassDone,
        # box_wrapper.h:1140,1161, and the double-buffered build threads,
        # ps_gpu_wrapper.cc:907).
        self.pipeline_passes = pipeline_passes
        self.is_rank0 = is_rank0
        # Called after each pass's delta is PUBLISHED — the checkpointed
        # boundary where cluster-topology events (the multihost elastic
        # reshard, multihost/reshard.py) are safe: the hook's state
        # transition is covered by recovery_chain(), and the hook owns
        # its own rollback (a leaked transient here would re-enter the
        # pass retry loop and replay an already-published pass).
        self.pass_boundary_hook = pass_boundary_hook
        # Called on a TRANSIENT pass failure BEFORE the rollback reload:
        # the seam where a replicated multihost tier repairs its
        # topology (promote a surviving backup off a dead shard host,
        # multihost/reshard.py ElasticReshardController.repair) so the
        # reset + recovery-chain reload that follows reaches only live
        # servers. Hook errors are logged, never raised — a broken
        # repair hook must not turn a retryable failure fatal.
        self.pass_retry_hook = pass_retry_hook
        self.timers = timers.TimerGroup()
        # Pipelined next-pass preload in flight (train_day): the pass
        # retry path must be able to join + invalidate it, so the handle
        # lives on self, not in train_day's locals.
        self._inflight_preload = None

    # -- data addressing ---------------------------------------------------

    def _default_filelist(self, day: str, splits: List[str]) -> List[str]:
        files: List[str] = []
        for s in splits:
            files.extend(sorted(glob.glob(
                os.path.join(self.data_root, day, s, "part-*"))))
        return files

    # -- recovery ----------------------------------------------------------

    def _save_dense(self, model_dir: str) -> None:
        """Dense params + optimizer state beside the sparse checkpoint
        (written BEFORE the done-file publish, so a published record
        always implies a complete model)."""
        from paddlebox_tpu.checkpoint.dense import save_pytree
        save_pytree({"params": self.trainer.params,
                     "opt_state": self.trainer.opt_state},
                    os.path.join(model_dir, "dense.npz"))

    def _load_dense(self, model_dir: str) -> bool:
        import zipfile

        from paddlebox_tpu.checkpoint.dense import (CheckpointCorruptError,
                                                    load_pytree)
        path = os.path.join(model_dir, "dense.npz")
        if not os.path.exists(path):
            return False
        template = {"params": self.trainer.params,
                    "opt_state": self.trainer.opt_state}
        try:
            state, _step = load_pytree(template, path)
        except (CheckpointCorruptError, zipfile.BadZipFile, EOFError,
                ValueError, OSError) as e:
            # Torn/corrupt dense.npz (crash mid-write before the fsync
            # discipline existed, disk corruption): one more warned
            # skip-to-older-record case — the restart this checkpoint
            # exists to serve must not die on it.
            log.warning("day_runner: dense checkpoint %s is corrupt "
                        "(%s) — skipping it", path, e)
            return False
        except KeyError as e:
            # Structure mismatch — e.g. the optimizer config changed
            # (grad_clip_norm re-nests opt_state under optax.chain) since
            # the checkpoint was written. Recovery falls back to an older
            # record or a warned fresh-dense resume rather than aborting.
            log.warning("day_runner: dense checkpoint %s does not match "
                        "the current optimizer/model structure (%s) — "
                        "skipping it", path, e)
            return False
        # Same key paths can still carry different SHAPES (model config
        # changed): restoring them would train garbage or crash later in
        # the jitted step — reject here with the same warned fallback.
        import jax
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(template)):
            if np.shape(a) != np.shape(b):
                log.warning(
                    "day_runner: dense checkpoint %s leaf shape %s != "
                    "current model's %s — skipping it", path,
                    np.shape(a), np.shape(b))
                return False
        # load_pytree returns HOST-format leaves; re-place them into the
        # trainer's live layout (replicated, ZeRO-sharded, or host-
        # pinned per FLAGS_dense_zero) — checkpoints are layout- and
        # world-agnostic, exactly like the sparse shard loads.
        self.trainer.params, self.trainer.opt_state = (
            self.trainer.place_dense(state["params"], state["opt_state"]))
        return True

    def recover(self) -> Optional[Dict[str, object]]:
        """Load last base + subsequent deltas from the done-file (role of
        the elastic restart consumers). Returns the resume point
        ``{"day": str, "pass_id": int}`` — the last day/pass whose state
        is already in the store — or None when starting fresh. The point
        is also remembered so a direct ``train_day`` call (the elastic
        worker pattern) skips already-published passes; pass_id 0 means
        the day completed through its base dump.

        A chain with deltas but NO base (crash during the first day)
        loads the deltas onto the fresh store — resuming costs at most
        the in-flight pass even before the first day-end base exists."""
        base, deltas = self.ckpt.recovery_chain()
        if base is None and not deltas:
            log.vlog(0, "day_runner: no published model, fresh start")
            self._recover_point = None
            return None
        store = self.trainer.engine.store
        if base is not None:
            store.load(base.path, "base")
        for d in deltas:
            store.load(d.path, "delta")
        # Dense state from the NEWEST record that carries it (sparse
        # deltas are cumulative; dense checkpoints are full snapshots).
        for rec in [*reversed(deltas)] + ([base] if base else []):
            if self._load_dense(rec.path):
                log.vlog(0, "day_runner: dense state from %s", rec.path)
                break
        else:
            log.warning("day_runner: no dense checkpoint in the recovery "
                        "chain — dense towers resume from current "
                        "(likely fresh) init")
        log.vlog(0, "day_runner: recovered base %s + %d deltas (day %s)",
                 base.path if base else "<none>", len(deltas),
                 base.day if base else (deltas[-1].day if deltas else "?"))
        if deltas:
            last = deltas[-1]
            point = {"day": last.day, "pass_id": last.pass_id}
        else:
            point = {"day": base.day, "pass_id": 0}
        self._recover_point = point
        return point

    # -- day loop ----------------------------------------------------------

    def _load_dataset(self, day: str, pass_id: int,
                      files: List[str]) -> Dataset:
        faults.faultpoint("day_runner/load")
        ds = Dataset(self.feed_config,
                     num_reader_threads=self.num_reader_threads)
        ds.set_filelist(files)
        # Occupancy: a pipelined day loop runs this in the preload
        # thread, so day_load overlapping a training window shows up in
        # that pass's verdict exactly like the reference's
        # PreLoadIntoMemory overlap would.
        with pipeline_stats.GLOBAL.busy("day_load"):
            ds.load_into_memory()
        if self.shuffle:
            # Deterministic digest — hash(str) is randomized per
            # process, which would make recovery replays and per-rank
            # batch orders irreproducible.
            import zlib
            ds.local_shuffle(seed=zlib.crc32(f"{day}:{pass_id}".encode()))
        return ds

    def _feed_keys(self, ds: Dataset, *, async_build: bool = True) -> None:
        """Register an online pass's keys. Defaults to the async build:
        with the split-key early build the engine overlaps everything it
        legally can with the active pass (and with the dataset work of
        THIS thread when no pass is active) — the serial build is only
        for callers that need the build's errors raised here."""
        eng = self.trainer.engine
        eng.feed_pass([ds.pass_keys(slots=g.slots) for g in eng.groups],
                      async_build=async_build)

    def _start_preload(self, day: str, pass_id: int, files: List[str]):
        """Background: load pass k+1's data and kick its table build while
        pass k trains. feed_pass blocks until pass k's begin_pass frees
        the pending slot, and the build's store pull is internally
        sequenced after pass k's end_pass write-back (split pull: only
        the shared-key intersection waits)."""
        import threading

        out = {"ds": None, "error": None}

        def body():
            try:
                faults.faultpoint("day_runner/preload")
                out["ds"] = self._load_dataset(day, pass_id, files)
                self._feed_keys(out["ds"], async_build=True)
            except BaseException as e:
                out["error"] = e

        t = threading.Thread(target=body, daemon=True)
        t.start()
        out["thread"] = t
        self._inflight_preload = out
        return out

    def train_pass(self, day: str, pass_id: int, files: List[str], *,
                   dataset: Optional[Dataset] = None,
                   feed_keys: bool = True) -> Dict[str, float]:
        """One online pass: load → shuffle → train → delta checkpoint.
        ``dataset``/``feed_keys`` let the pipelined day loop hand in a
        preloaded dataset whose table build is already in flight.

        Self-healing (``FLAGS_pass_max_retries``): a TRANSIENT failure
        (IO/connection/timeout, an injected drill fault, a watchdog
        stall) costs one pass retry, not the day — each retry drops the
        pending build, rolls the sparse store + dense state back to the
        last published record, reloads the pass's data with its
        deterministic shuffle, and replays; the retried pass is
        bit-identical to an unfailed run. Fatal errors (bad data, NaN
        loss, code bugs) raise immediately."""
        max_retries = max(0, int(flags.flag("pass_max_retries")))
        # Dense pre-pass snapshot (HOST copies — the train step donates
        # the device buffers, so by failure time the originals are
        # deleted): the rollback source when NO published record carries
        # dense state yet (a first-day first-pass failure — self.params
        # is only committed at train_pass success, so this equals the
        # last published dense whenever one exists).
        dense_snap = None
        if max_retries:
            import jax
            dense_snap = jax.tree.map(
                lambda x: np.array(x),
                (self.trainer.params, self.trainer.opt_state))
        attempt = 0
        while True:
            wd_armed = watchdog.arm_from_flags(
                phase=f"day {day} pass {pass_id}")
            try:
                return self._train_pass_inner(day, pass_id, files,
                                              dataset=dataset,
                                              feed_keys=feed_keys)
            except BaseException as e:
                # EVERY failure path drops the pending build (load error,
                # train-step error, checkpoint error): an exception
                # between feed_pass and begin_pass would otherwise orphan
                # a build holding the one-slot semaphore — a retry (or
                # the elastic restart's next pass) would deadlock in
                # feed_pass or silently consume the wrong pass's
                # table/keymap. The engine's cancellable boundary wait
                # makes this safe even when the failed pass never ran
                # end_pass.
                self.trainer.engine.cancel_pending()
                if attempt >= max_retries or not faults.is_transient(e):
                    raise
                attempt += 1
                monitor.add("pass/retries", 1)
                log.warning(
                    "day %s pass %d failed with transient %s: %r — "
                    "rolling back and retrying (%d/%d)", day, pass_id,
                    type(e).__name__, e, attempt, max_retries)
                trace.instant("pass/retry", day=day, pass_id=pass_id,
                              attempt=attempt, error=repr(e))
                if self.pass_retry_hook is not None:
                    try:
                        self.pass_retry_hook(day, pass_id, e)
                    except Exception as he:
                        log.warning("pass_retry_hook failed (%r) — "
                                    "continuing with the rollback", he)
                self._rollback_for_retry(dense_snap)
                backoff = min(
                    float(flags.flag("pass_retry_backoff_s"))
                    * (2.0 ** (attempt - 1)),
                    float(flags.flag("pass_retry_backoff_max_s")))
                if backoff > 0:
                    time.sleep(backoff)
                # Replay from scratch: the handed-in dataset/build may be
                # partially consumed or mid-flight — a fresh load with
                # the deterministic day:pass shuffle seed reproduces the
                # exact batch order of an unfailed run.
                dataset, feed_keys = None, True
            finally:
                if wd_armed:
                    watchdog.disarm()

    def _rollback_for_retry(self, dense_snap) -> None:
        """Restore the model to the last published state so the retry
        replays the pass against exactly the inputs an unfailed run
        would have seen.

        - Active pass dropped WITHOUT write-back (it may be mid-train).
        - Sparse store reset and rebuilt from ``recovery_chain()`` (the
          failed attempt may have inserted the pass's unseen keys, or —
          when the failure hit AFTER end_pass, in save/publish — already
          written the pass's updates back; replaying on top would
          double-apply them).
        - Dense state from the newest published record carrying it,
          falling back to the pre-pass in-memory snapshot (identical
          whenever a published record exists; the only source before the
          first publish).
        """
        eng = self.trainer.engine
        # An in-flight NEXT-pass preload (pipelined day loop) may still
        # be loading data or building its table: join it so its
        # feed_pass has happened, then cancel that build too — its
        # boundary state is stale after the rollback. The slot it would
        # wait on is already free (the caller's cancel_pending ran).
        pre = getattr(self, "_inflight_preload", None)
        if pre is not None and pre.get("thread") is not None:
            pre["thread"].join()
            pre["cancelled"] = True
        eng.cancel_pending()
        eng.abort_if_active()
        store = eng.store
        base, deltas = self.ckpt.recovery_chain()
        if hasattr(store, "reset"):
            store.reset()
        elif base is None:
            log.warning("day_runner: store %s has no reset(); rollback "
                        "without a base may leave the failed attempt's "
                        "writes in place", type(store).__name__)
        if base is not None:
            store.load(base.path, "base")
        for d in deltas:
            store.load(d.path, "delta")
        for rec in [*reversed(deltas)] + ([base] if base else []):
            if self._load_dense(rec.path):
                log.vlog(0, "day_runner: rollback dense from %s", rec.path)
                break
        else:
            params, opt = dense_snap
            self.trainer.params, self.trainer.opt_state = (
                self.trainer.place_dense(params, opt))
        monitor.add("pass/rollbacks", 1)

    def _train_pass_inner(self, day: str, pass_id: int, files: List[str],
                          *, dataset: Optional[Dataset],
                          feed_keys: bool) -> Dict[str, float]:
        report.init_telemetry_from_flags()
        faults.init_from_flags()
        # Stamp the quality tracker with this pass's identity (non-
        # override: a stream manifest's richer context wins) so the
        # quality_report line names day/pass beside the pass_report.
        quality.GLOBAL.set_pass_context(day, pass_id, override=False)
        with self.timers.scope("load"), \
                trace.span("day/load", day=day, pass_id=pass_id):
            ds = dataset if dataset is not None else self._load_dataset(
                day, pass_id, files)
        self.trainer.reset_metrics()
        with self.timers.scope("train"), \
                trace.span("day/train", day=day, pass_id=pass_id):
            stats = self.trainer.train_pass(ds, feed_keys=feed_keys)
        if self.is_rank0:
            # Only rank 0 writes model files — N ranks racing
            # savez on one shared path would corrupt the npz.
            with self.timers.scope("save_delta"), \
                    trace.span("day/save_delta", day=day,
                               pass_id=pass_id):
                faults.faultpoint("day_runner/save")
                mdir = self.ckpt.model_dir(day, pass_id)
                self.trainer.engine.store.save_delta(mdir)
                # Dense state rides with every sparse checkpoint (role
                # of save_persistables beside the table dumps): a
                # recovery that reloads the table but restarts the
                # dense towers from init would resume an inconsistent
                # model. data_norm stats live in params and ride too.
                self._save_dense(mdir)
                faults.faultpoint("day_runner/publish")
                self.ckpt.publish(day, pass_id)
            if self.save_xbox and hasattr(self.trainer.engine.store,
                                          "save_xbox"):
                with self.timers.scope("save_xbox"), \
                        trace.span("day/save_xbox", day=day,
                                   pass_id=pass_id):
                    self.trainer.engine.store.save_xbox(
                        self.ckpt.model_dir(day, pass_id))
                    self.ckpt.publish_xbox(day, pass_id)
        if self.pass_boundary_hook is not None:
            with trace.span("day/pass_boundary_hook", day=day,
                            pass_id=pass_id):
                self.pass_boundary_hook(day, pass_id)
        ds.clear()
        monitor.add("day_runner/passes", 1)
        # One report path: the day-loop timers land in the registry
        # (and thus the metrics JSONL) beside the trainer's pass stages.
        self.timers.publish("day_runner")
        log.vlog(0, "day %s pass %d: %s | %s", day, pass_id, stats,
                 self.timers.report())
        return stats

    def train_day(self, day: str,
                  start_pass: Optional[int] = None
                  ) -> List[Dict[str, float]]:
        """All passes of one day, then shrink + base dump (the day
        boundary sequence the reference runs: shrink → SaveBase →
        write_model_donefile).

        ``start_pass=None`` derives the start from the last ``recover()``
        point: a recovered pass of THIS day resumes after it, and a
        recovered day BASE (pass 0 — the day finished) skips the day
        outright — an elastic restart landing after the day completed
        must not retrain it and republish its passes (observed: a
        post-completion join regenerated deltas 1..6 over a finished
        day before this guard)."""
        # Arm fault injection before the FIRST dataset load/preload —
        # waiting for train_pass would leave the early load sites
        # un-drillable (and racy from the preload thread).
        faults.init_from_flags()
        if start_pass is None:
            p = getattr(self, "_recover_point", None)
            if p is not None and p["day"] == str(day):
                if p["pass_id"] == 0:
                    log.vlog(0, "day %s already complete in the recovery "
                             "chain: skipping", day)
                    return []
                start_pass = int(p["pass_id"]) + 1
            else:
                start_pass = 1
        all_stats = []
        resumed_past = 0  # passes skipped because recovery already holds them
        jobs: List = []
        for pass_id, splits in enumerate(self.pass_splits, start=1):
            files = self.filelist_fn(day, splits)
            if pass_id < start_pass:
                resumed_past += bool(files)
                continue
            if not files:
                log.warning("day %s pass %d: no files for splits %s, "
                            "skipping", day, pass_id, splits)
                continue
            jobs.append((pass_id, files))

        preloaded = None
        try:
            for i, (pass_id, files) in enumerate(jobs):
                if preloaded is not None:
                    preloaded["thread"].join()
                    self._inflight_preload = None
                    if preloaded["error"] is not None:
                        raise preloaded["error"]
                    ds, feed_keys = preloaded["ds"], False
                    if preloaded.get("cancelled"):
                        # The previous pass's retry rollback cancelled
                        # this preload's table build — re-feed from the
                        # (still loaded) dataset so begin_pass has a
                        # fresh build against the rolled-back store.
                        self._feed_keys(ds)
                elif self.pipeline_passes:
                    # First pass of the day: load + feed here so training
                    # can begin while the NEXT pass preloads. Async build
                    # (the default): begin_pass joins it; a build error
                    # surfaces there, inside the same try as every other
                    # pass failure.
                    ds = self._load_dataset(day, pass_id, files)
                    self._feed_keys(ds)
                    feed_keys = False
                else:
                    ds, feed_keys = None, True
                preloaded = None
                if self.pipeline_passes and i + 1 < len(jobs):
                    preloaded = self._start_preload(day, *jobs[i + 1])
                all_stats.append(self.train_pass(day, pass_id, files,
                                                 dataset=ds,
                                                 feed_keys=feed_keys))
        except BaseException:
            # A failed pass must not leave the NEXT pass's in-flight
            # preload occupying the engine's pending slot — a retry
            # would consume the orphaned (wrong-pass) table/keymap.
            if preloaded is not None:
                preloaded["thread"].join()
            self._inflight_preload = None
            self.trainer.engine.cancel_pending()
            raise
        if not all_stats and not resumed_past:
            # A day that trained nothing (data outage) must not decay the
            # model or publish a base marking the day done — the data may
            # arrive late and the day must remain trainable. Resuming
            # after the day's LAST delta is different: those passes are
            # already in the store, so day-end below must still run or
            # the day would never get its shrink + base.
            log.warning("day %s: no trainable passes; skipping day-end "
                        "shrink/base", day)
            return all_stats
        evicted = self.day_end(day)
        log.vlog(0, "day %s done: %d passes, %d evicted", day,
                 len(all_stats), evicted)
        return all_stats

    def day_end(self, day: str) -> int:
        """The day-boundary sequence the reference runs: table lifecycle
        shrink (show/click decay + unseen-days TTL + min-show eviction,
        FLAGS_table_*) → SaveBase → donefile publish. Shared between
        ``train_day`` and the streaming runner's day rollover
        (stream/runner.py) — both close a day the exact same way.
        Returns rows evicted by the shrink."""
        store = self.trainer.engine.store
        if self.is_rank0:
            with self.timers.scope("day_end"), \
                    trace.span("day/day_end", day=day):
                evicted = store.shrink(min_show=self.min_show_shrink)
                faults.faultpoint("day_runner/day_end_save")
                bdir = self.ckpt.model_dir(day, pass_id=-1)
                store.save_base(bdir)
                self._save_dense(bdir)
                faults.faultpoint("day_runner/publish")
                self.ckpt.publish(day, pass_id=-1)
        elif getattr(store, "shared", False):
            # Shared backing tier (e.g. PSBackedStore): rank 0 already
            # shrank the one store — running it again would apply
            # show/click decay and eviction world_size times per day
            # (the reference's day-end ShrinkTable runs once).
            evicted = 0
        else:
            evicted = store.shrink(min_show=self.min_show_shrink)
        monitor.add("day_runner/days", 1)
        monitor.add("day_runner/evicted_keys", int(evicted))
        # The per-day key window slides at the boundary by design —
        # the NEXT pass's churn alarm is suppressed, not a drift.
        quality.GLOBAL.note_day_rollover()
        return evicted

    def run_days(self, days: Sequence[str],
                 resume: bool = True) -> Dict[str, List[Dict[str, float]]]:
        """Multi-day loop with recovery. The resume point covers both the
        base day AND any trailing deltas already loaded into the store —
        the delta day's completed passes are skipped via ``start_pass``
        (re-training them would double-apply their updates)."""
        point = self.recover() if resume else None
        out = {}
        for day in days:
            day = str(day)
            if point is not None:
                if day < point["day"] or (day == point["day"]
                                          and point["pass_id"] == 0):
                    log.vlog(0, "day %s already covered by recovery: skip",
                             day)
                    continue
                if day == point["day"]:
                    # resume mid-day after the last published delta pass
                    out[day] = self.train_day(
                        day, start_pass=point["pass_id"] + 1)
                    continue
            out[day] = self.train_day(day)
        return out
