"""Trainer hierarchy: lifecycle base + factory + dense/pipeline trainers.

Role of the reference trainer layer (``framework/trainer.h:59-103``):
``TrainerBase`` lifecycle ``Initialize → InitTrainerEnv → InitOtherEnv →
Run → Finalize`` with dump-to-file machinery (:81-92), concrete trainers
created by name through ``TrainerFactory`` (``trainer_factory.cc``) from a
``TrainerDesc``: ``MultiTrainer``+``HogwildWorker`` (dense multi-device),
``PipelineTrainer``+``SectionWorker`` (1F1B microbatches), and the CTR
trainers (``BoxPSTrainer`` — here :class:`~paddlebox_tpu.train.
ctr_trainer.CTRTrainer`).

TPU-first: a "trainer" is lifecycle + host loop around ONE jitted step —
the per-device worker threads of the reference collapse into the sharded
program (hogwild's N threads == dp sharding; SectionWorker's microbatch
scopes == the pipeline scan). Dump/metrics/sanitizer hooks stay host-side.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import log, timers
from paddlebox_tpu.parallel import topology as topo_lib
from paddlebox_tpu.utils import sanitizer
from paddlebox_tpu.utils.dump import DumpWriter


@dataclasses.dataclass
class TrainerDesc:
    """Configuration record (role of trainer_desc.proto:21): trainer
    selection + loop knobs + dump settings."""

    trainer_class: str = "MultiTrainer"
    max_steps: int = 0                 # 0 = drain the iterator
    log_every: int = 50
    check_nan_inf: bool = False
    dump_path: str = ""                # per-line prediction dump target
    num_micro_batches: int = 1         # pipeline trainers
    # Pipeline schedule (role of the reference's forward_backward_pipeline
    # default, pipeline_parallel.py:82): "gpipe" differentiates through
    # the pipeline scan (O(num_micro_batches) stashed activations);
    # "1f1b" runs the explicit one-forward-one-backward schedule with
    # O(pp) bounded activation memory (parallel/pp.py).
    pipeline_schedule: str = "gpipe"
    # Block on the loss every N steps: keeps async dispatch deep enough to
    # overlap host and device but bounded — unbounded queues of
    # collective-heavy programs can starve the runtime's rendezvous
    # (observed as AwaitAndLogIfStuck aborts on the CPU backend).
    dispatch_depth: int = 16
    # Wall-clock bound for one HeterTrainer pipeline chunk (seconds); a
    # production pass must not die at an arbitrary default.
    pass_timeout: float = 3600.0


class TrainerBase:
    """Lifecycle contract (trainer.h:59): subclasses implement the four
    stages; ``fit`` drives them in order."""

    def __init__(self):
        self.desc: Optional[TrainerDesc] = None
        self.mesh: Optional[Mesh] = None
        self.dump: Optional[DumpWriter] = None
        self.timers = timers.TimerGroup()

    def initialize(self, desc: TrainerDesc) -> None:
        self.desc = desc

    def init_trainer_env(self, mesh: Optional[Mesh] = None) -> None:
        self.mesh = mesh or topo_lib.get_default_topology()[1]

    def init_other_env(self) -> None:
        if self.desc and self.desc.dump_path:
            self.dump = DumpWriter(self.desc.dump_path)

    def run(self, data: Iterable) -> Dict[str, float]:
        raise NotImplementedError

    def finalize(self) -> None:
        if self.dump is not None:
            self.dump.close()

    def fit(self, data: Iterable, desc: Optional[TrainerDesc] = None,
            mesh: Optional[Mesh] = None) -> Dict[str, float]:
        self.initialize(desc or self.desc or TrainerDesc())
        self.init_trainer_env(mesh)
        self.init_other_env()
        try:
            return self.run(data)
        finally:
            self.finalize()


_REGISTRY: Dict[str, Type[TrainerBase]] = {}


def register_trainer(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls
    return deco


def create_trainer(name: str, *args, **kw) -> TrainerBase:
    """TrainerFactory::CreateTrainer equivalent."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown trainer {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](*args, **kw)


@register_trainer("MultiTrainer")
class MultiTrainer(TrainerBase):
    """Dense data-parallel trainer (role of MultiTrainer+HogwildWorker,
    trainer.h:105 / device_worker.h:271): one jitted step, batch sharded
    over the dp axis — XLA's compiled allreduce replaces hogwild's shared
    scope + per-thread loops.

    ``loss_fn(params, batch) -> scalar`` defines the model; batches are
    pytrees of numpy arrays with leading batch dim.
    """

    def __init__(self, loss_fn: Callable[[Any, Any], jax.Array],
                 params: Any, tx: optax.GradientTransformation,
                 eval_fn: Optional[Callable[[Any, Any], Any]] = None):
        super().__init__()
        self.loss_fn = loss_fn
        self.eval_fn = eval_fn   # (params, batch) -> (preds, labels) dump
        self.params = params
        self.tx = tx
        self.opt_state = tx.init(params)
        self._step = None

    def init_other_env(self) -> None:
        if self.desc and self.desc.dump_path and self.eval_fn is None:
            # Refuse a dead knob: opening the writer truncates the target
            # file, and without eval_fn nothing would ever be written.
            raise ValueError(
                "TrainerDesc.dump_path set but MultiTrainer has no "
                "eval_fn to produce (preds, labels) for the dump")
        super().init_other_env()

    def init_trainer_env(self, mesh: Optional[Mesh] = None) -> None:
        super().init_trainer_env(mesh)

        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        if self.mesh is not None:
            data_sh = topo_lib.data_sharding(self.mesh)
            self._data_sharding = data_sh
            self._step = jax.jit(step,
                                 in_shardings=(None, None, data_sh),
                                 out_shardings=(None, None, None))
        else:
            self._data_sharding = None
            self._step = jax.jit(step)

    def run(self, data: Iterable) -> Dict[str, float]:
        desc = self.desc or TrainerDesc()
        # Keep losses as device arrays — float() per step would block the
        # host on every result and defeat async dispatch.
        first_loss = last_loss = None
        n = 0
        for batch in data:
            if self._data_sharding is not None:
                batch = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, self._data_sharding), batch)
            with self.timers.scope("step"):
                self.params, self.opt_state, loss = self._step(
                    self.params, self.opt_state, batch)
            if first_loss is None:
                first_loss = loss
            last_loss = loss
            if desc.check_nan_inf:
                sanitizer.check_batch(self.params, step=n, force=True)
            if self.dump is not None:
                preds, labels = self.eval_fn(self.params, batch)
                self.dump.write_batch(np.asarray(preds), np.asarray(labels))
            n += 1
            if desc.dispatch_depth and n % desc.dispatch_depth == 0:
                jax.block_until_ready(loss)
            if desc.log_every and n % desc.log_every == 0:
                log.vlog(0, "step %d loss %.5f", n, float(loss))
            if desc.max_steps and n >= desc.max_steps:
                break
        return {"steps": n,
                "loss_first": float(first_loss) if n else float("nan"),
                "loss_last": float(last_loss) if n else float("nan")}


@register_trainer("HeterTrainer")
class HeterTrainer(MultiTrainer):
    """Host↔device split trainer (role of the heter trainers,
    ``heterxpu_trainer.cc`` / ``heter_pipeline_trainer.cc`` +
    ``heter_section_worker.cc``): CPU stages and the accelerator stage run
    as pipelined actors so host preprocessing of batch N+1 overlaps the
    device step on batch N.

    TPU-first: the stages are FleetExecutor interceptors
    (:mod:`paddlebox_tpu.distributed.fleet_executor`) — ``host_fn(batch)``
    runs on its own TaskLoop thread (parse/feature-engineering/CPU
    lookups), the device stage is MultiTrainer's jitted step (inherited —
    one step builder, no divergence). The stream is consumed in bounded
    chunks so memory stays O(chunk) and a short dataset under a larger
    max_steps just ends the run (the reference's cross-device RPC,
    heter_service.proto, collapses into the in-process message bus).
    """

    def __init__(self, loss_fn: Callable[[Any, Any], jax.Array],
                 params: Any, tx: optax.GradientTransformation,
                 host_fn: Optional[Callable[[Any], Any]] = None,
                 buffer_size: int = 4, chunk_size: int = 64):
        super().__init__(loss_fn, params, tx)
        self.host_fn = host_fn or (lambda b: b)
        self.buffer_size = buffer_size
        self.chunk_size = chunk_size

    def run(self, data: Iterable) -> Dict[str, float]:
        import itertools

        from paddlebox_tpu.distributed.fleet_executor import (
            Carrier, linear_pipeline)
        desc = self.desc or TrainerDesc()
        it = iter(data)
        depth = desc.dispatch_depth  # 0 = never block (MultiTrainer parity)
        step_count = [0]

        def device_stage(batch):
            # Single interceptor thread owns params/opt_state: no lock
            # needed (the reference's SectionWorker has the same
            # one-thread-per-stage ownership).
            if self._data_sharding is not None:
                batch = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, self._data_sharding),
                    batch)
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, batch)
            step_count[0] += 1
            if depth and step_count[0] % depth == 0:
                # bounded async dispatch (see TrainerDesc.dispatch_depth)
                jax.block_until_ready(loss)
            return loss

        nodes = linear_pipeline([self.host_fn, device_stage],
                                buffer_size=self.buffer_size)
        carrier = Carrier(nodes)
        first_loss = last_loss = None
        n = 0
        while True:
            want = self.chunk_size
            if desc.max_steps:
                want = min(want, desc.max_steps - n)
            if want <= 0:
                break
            chunk = list(itertools.islice(it, want))
            if not chunk:
                break
            losses = carrier.run(len(chunk), feeds=chunk,
                                 timeout=desc.pass_timeout)
            if first_loss is None and losses:
                first_loss = losses[0]
            if losses:
                last_loss = losses[-1]
            n += len(chunk)
            if desc.check_nan_inf:
                sanitizer.check_batch(self.params, step=n, force=True)
        return {"steps": n,
                "loss_first": float(first_loss) if n else float("nan"),
                "loss_last": float(last_loss) if n else float("nan")}


@register_trainer("PipelineTrainer")
class PipelineTrainer(TrainerBase):
    """Pipeline-parallel trainer (role of PipelineTrainer+SectionWorker,
    trainer.h:307 / section_worker.cc:40): stages sharded over the pp
    mesh axis; microbatch scheduling compiles into the pipeline scan
    (parallel/pp) and autodiff differentiates through it, replacing the
    hand-built forward/backward op lists of the reference.

    ``stage_fn(stage_params, x) -> x`` is one stage; ``loss_head(y,
    batch) -> scalar`` terminates the pipeline.
    """

    def __init__(self, stage_fn, stacked_params: Any,
                 loss_head: Callable[[jax.Array, Any], jax.Array],
                 tx: optax.GradientTransformation):
        super().__init__()
        self.stage_fn = stage_fn
        self.params = stacked_params
        self.loss_head = loss_head
        self.tx = tx
        self.opt_state = tx.init(stacked_params)
        self._step = None

    def init_trainer_env(self, mesh: Optional[Mesh] = None) -> None:
        super().init_trainer_env(mesh)
        from paddlebox_tpu.parallel import pp as pp_lib
        desc = self.desc or TrainerDesc()
        mb = desc.num_micro_batches
        mesh = self.mesh
        schedule = desc.pipeline_schedule
        if schedule == "gpipe":
            pipe = pp_lib.make_pipeline_fn(mesh, self.stage_fn, self.params)

            def step(params, opt_state, batch):
                x, rest = batch["x"], batch

                def loss_fn(params):
                    xs = x.reshape((mb, x.shape[0] // mb) + x.shape[1:])
                    y = pipe(params, xs)
                    y = y.reshape((x.shape[0],) + y.shape[2:])
                    return self.loss_head(y, rest)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                updates, opt_state = self.tx.update(grads, opt_state,
                                                    params)
                return optax.apply_updates(params, updates), opt_state, loss

            self._step = jax.jit(step)
        elif schedule == "1f1b":
            # Explicit 1F1B (bounded activation memory). loss_head sees
            # per-MICROBATCH outputs + the batch dict microbatched the
            # same way; with equal microbatch sizes a mean-style loss
            # matches the gpipe full-batch value exactly.
            from jax.sharding import PartitionSpec as P_
            pspecs = pp_lib.stage_specs(self.params)
            stage_fn, loss_head = self.stage_fn, self.loss_head

            def body(stacked_params, x_mb, batch_mb):
                params_local = jax.tree.map(lambda a: a[0], stacked_params)
                loss, grads = pp_lib.one_f_one_b_value_and_grad(
                    stage_fn, loss_head, params_local, x_mb, batch_mb,
                    axis="pp")
                return loss, jax.tree.map(lambda g: g[None], grads)

            sm = jax.shard_map(
                body, mesh=mesh, in_specs=(pspecs, P_(), P_()),
                out_specs=(P_(), pspecs), check_vma=False)

            def step(params, opt_state, batch):
                x = batch["x"]
                xs = x.reshape((mb, x.shape[0] // mb) + x.shape[1:])
                batch_mb = jax.tree.map(
                    lambda a: a.reshape((mb, a.shape[0] // mb)
                                        + a.shape[1:]), batch)
                loss, grads = sm(params, xs, batch_mb)
                updates, opt_state = self.tx.update(grads, opt_state,
                                                    params)
                return optax.apply_updates(params, updates), opt_state, loss

            self._step = jax.jit(step)
        else:
            raise ValueError(
                f"unknown pipeline_schedule {schedule!r}; choose 'gpipe' "
                f"or '1f1b'")

    def run(self, data: Iterable) -> Dict[str, float]:
        desc = self.desc or TrainerDesc()
        mb = desc.num_micro_batches
        first_loss = last_loss = None
        n = 0
        for batch in data:
            bs = batch["x"].shape[0]
            if bs % mb:
                raise ValueError(
                    f"batch size {bs} not divisible by num_micro_batches "
                    f"{mb} — pad or drop the partial batch")
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, batch)
            if first_loss is None:
                first_loss = loss
            last_loss = loss
            if desc.check_nan_inf:
                sanitizer.check_batch(self.params, step=n, force=True)
            n += 1
            if desc.dispatch_depth and n % desc.dispatch_depth == 0:
                jax.block_until_ready(loss)
            if desc.log_every and n % desc.log_every == 0:
                log.vlog(0, "pp step %d loss %.5f", n, float(loss))
            if desc.max_steps and n >= desc.max_steps:
                break
        return {"steps": n,
                "loss_first": float(first_loss) if n else float("nan"),
                "loss_last": float(last_loss) if n else float("nan")}
