"""Trainers: pass-driven CTR training loop (role of L6 trainer runtime).

Role of ``BoxPSTrainer``/``BoxPSWorker`` (``framework/boxps_trainer.cc``,
``boxps_worker.cc``) and the ``train_from_dataset`` entry
(``python/paddle/fluid/executor.py:1787``).
"""

from paddlebox_tpu.train.ctr_trainer import CTRTrainer, TrainerConfig
from paddlebox_tpu.train.auc_runner import slot_replacement_eval

__all__ = ["CTRTrainer", "TrainerConfig", "slot_replacement_eval"]
