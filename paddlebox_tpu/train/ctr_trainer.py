"""Pass-driven CTR trainer: the BoxPSTrainer/BoxPSWorker equivalent.

Role of the reference hot loop (``boxps_worker.cc:666-724`` TrainFiles):
per minibatch — pack batch (``BuildSlotBatchGPU``), pull sparse
(``PullSparse``), run fwd/bwd ops, push sparse grads (``PushSparseGrad``),
sync dense (``SyncParam``), collect AUC (``AddAucMonitor``) — plus the
``train_from_dataset`` pass loop around it.

TPU-first: the whole per-batch sequence is ONE jitted shard_map program —
pull (all slots fused into one all-to-all), model fwd/bwd, exact global
logloss, dense psum + optax update, sparse push with fused optimizer, and
AUC histogram accumulation — so XLA overlaps compute with the pull/push
collectives and there is no per-op dispatch. Device threads, streams, and
the NCCL ring of the reference collapse into the compiled program.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import (faults, flags, log, monitor,
                                pipeline_stats, quality, report, timers,
                                trace, watchdog)
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch
from paddlebox_tpu.embedding import TableConfig, make_sparse_optimizer
from paddlebox_tpu.embedding.grouped import GroupedEngine
from paddlebox_tpu.embedding.lookup import (compute_bucketing, pull_local,
                                            push_local,
                                            record_exchange_stats)
from paddlebox_tpu.metrics import (AucState, auc_accumulate, auc_compute,
                                   auc_state_init)
from paddlebox_tpu.ops.data_norm import (data_norm_apply, data_norm_init,
                                         normalize_dense_and_strip)
from paddlebox_tpu.parallel.collective import (hierarchical_psum_tree,
                                               quantized_psum)
from paddlebox_tpu.parallel import zero as zero_lib


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    dense_learning_rate: float = 1e-3
    dense_optimizer: str = "adam"
    auc_num_buckets: int = 1 << 16
    check_nan_inf: bool = False
    # Dense gradient synchronization across the dp axis (role of the
    # BoxPSWorker dense-sync modes, boxps_worker.cc:584-645):
    #   "step"  — psum grads every step (default; c_allreduce_sum role)
    #   "kstep" — local-SGD: local update each step with the grad scaled
    #             by world size, params averaged (pmean) every
    #             dense_sync_interval steps (SyncParam's k-step
    #             ReduceScatter+SyncDense+AllGather role). Optimizer
    #             state stays worker-local between syncs, as in the
    #             reference. At k=1 with SGD this is exactly "step".
    #   "async" — the jitted step returns psum'd dense grads; a host
    #             AsyncDenseTable thread applies Adam and workers pull
    #             fresh params each step (BoxPSAsynDenseTable role).
    dense_sync_mode: str = "step"
    dense_sync_interval: int = 8
    # Forward/backward compute precision (role of paddle.amp / the AMP
    # meta-optimizer): "bfloat16" casts params + activations for the
    # model fwd/bwd so matmuls hit the MXU at native rate; master params,
    # optimizer state, loss, AUC, and the sparse push stay float32.
    compute_dtype: str = "float32"
    # DataNorm over the concatenated dense features (role of the
    # reference's data_norm op in CTR models, data_norm_op.cc): global
    # decayed statistics, synced across dp every step, threaded through
    # the step as state (f32 regardless of compute_dtype).
    data_norm: bool = False
    data_norm_slot_dim: int = -1
    data_norm_decay: float = 0.9999999
    # Scale sparse grads by the global batch size before the push (role
    # of scale_sparse_gradient_with_batch_size, trainer_desc.proto:64
    # default true, applied in fleet_wrapper.cc:294): the loss carries a
    # 1/global_batch factor, so without the scale each key's
    # per-occurrence gradient is O(1/batch) and the sparse optimizer
    # cannot move a key meaningfully within one pass; scaling restores
    # per-occurrence O(1) grads, which is the regime the sparse adagrad
    # defaults (initial_g2sum=3, lr=0.05, optimizer.cuh.h:31) are tuned
    # for.
    scale_sparse_grad_by_batch: bool = True
    # Global-norm clip on the dense gradients before the optimizer
    # (role of paddle.nn.ClipGradByGlobalNorm in fleet configs);
    # 0 disables. In "step" mode it is applied AFTER the cross-replica
    # psum — the clip sees the true global gradient, as the reference's
    # post-allreduce clip does. In "kstep" (local-SGD) mode the clip is
    # deliberately PER-REPLICA: between syncs each worker owns a local
    # trajectory (grads are the ndev-scaled local estimate, optimizer
    # state worker-local), so the clip bounds that local step; replicas
    # may make different clip decisions until the next param average —
    # accepted local-SGD semantics, not the "step"-mode global clip.
    grad_clip_norm: float = 0.0


class CTRTrainer:
    """Owns PassEngine + dense params + the fused train step.

    Usage (mirrors the BoxPS day/pass loop, SURVEY.md §3.1):

        trainer = CTRTrainer(model, feed_cfg, table_cfg, mesh=mesh)
        trainer.init(seed=0)
        for pass_files in day:
            dataset.set_filelist(pass_files); dataset.load_into_memory()
            stats = trainer.train_pass(dataset)
        trainer.engine.store.save_base(path)
    """

    def __init__(self, model, feed_config: DataFeedConfig,
                 table_config: TableConfig, *,
                 mesh: Optional[Mesh] = None, axis: str = "dp",
                 config: TrainerConfig = TrainerConfig(),
                 store=None, store_factory=None):
        self.model = model
        self.feed_config = feed_config
        self.config = config
        self.mesh = mesh
        self.axis = axis
        # Multi-slice (DCN) topology: the pass table is sharded over
        # `axis` INSIDE each slice and replicated across slices; the
        # batch splits over slice × axis. dcn_axis drives the
        # hierarchical dense sync and the sparse push's one DCN stage.
        self.dcn_axis = None
        if (mesh is not None and "slice" in mesh.axis_names
                and int(mesh.shape["slice"]) > 1):
            if axis == "slice":
                raise ValueError("table axis cannot be the DCN slice axis")
            self.dcn_axis = "slice"
        n_slices = (int(mesh.shape["slice"])
                    if self.dcn_axis is not None else 1)
        # ndev = REPLICA count (batch shards) = slice * table axis size;
        # the table itself has mesh.shape[axis] shards regardless.
        self.ndev = (int(mesh.shape[axis]) * n_slices
                     if mesh is not None else 1)
        if feed_config.batch_size % self.ndev:
            raise ValueError(
                f"batch_size {feed_config.batch_size} must be divisible by "
                f"the replica count {self.ndev} (slice x {axis})")
        # Per-slot mf widths (dynamic mf, role of CtrDymfAccessor): slots
        # declaring SlotConf.emb_dim get that width; the rest use the
        # table default. Slots are grouped by width — one PassEngine,
        # store, and fused pull/push per width group.
        slot_dims = {s.name: (s.emb_dim or table_config.dim)
                     for s in feed_config.sparse_slots}
        if self.num_tasks > 1 and feed_config.num_labels < self.num_tasks:
            raise ValueError(
                f"model has {self.num_tasks} tasks but the feed parses "
                f"only {feed_config.num_labels} label columns")
        # store: optional FeatureStore-shaped backing tier instance — a
        # TieredFeatureStore (RAM+SSD) or a distributed.ps.PSBackedStore
        # (remote CPU PS, the BuildPull flow). Single-width models only;
        # multi-width models pass store_factory(cfg) -> store instead.
        if store is not None:
            if store_factory is not None:
                raise ValueError("pass store or store_factory, not both")
            if len(set(slot_dims.values())) > 1:
                raise ValueError(
                    "a single store instance cannot back multiple widths "
                    "— pass store_factory instead")
            store_factory = lambda cfg: store  # noqa: E731
        self.table_config = table_config
        self.engine = GroupedEngine(table_config, slot_dims, mesh=mesh,
                                    table_axis=axis,
                                    store_factory=store_factory)
        self.sparse_opt = make_sparse_optimizer(table_config)
        self.params: Any = None
        self.opt_state: Any = None
        self.auc_state: Optional[AucState] = None
        self._async_dense = None
        self._sync_params_cache = None
        self._eval_fn = None
        self.timers = timers.TimerGroup()
        # Per-pass prefetch segment-cache observability (reset per pass;
        # surfaced as seg_cache_hit_rate in the pass report).
        self._seg_cache_hits = 0
        self._seg_cache_misses = 0
        self._step_fn = None
        # K-step scanned megastep (FLAGS_trainer_steps_per_dispatch > 1):
        # the compiled fn and the K it was built at — invalidated together
        # with _step_fn whenever the measured bucket caps change.
        self._mega_fn = None
        self._mega_k = 0
        self._eval_k = 0
        # Pass-loop observability (reset per pass, surfaced in stats):
        # dispatches = compiled-program launches; host_syncs = blocking
        # device fetches INSIDE the loop (the check_nan_inf finite-vector
        # reads — pass-end stat reductions are O(1) and not counted).
        self._dispatch_blocks = 0
        self._host_syncs = 0
        # Test hook: when True the pass loop retains per-step loss device
        # arrays (K=1: scalars, K>1: [K] blocks) in _debug_losses so
        # parity tests can compare per-step losses bitwise. Off by
        # default — retaining O(steps) arrays is exactly what the
        # running-sum path exists to avoid.
        self._debug_collect_losses = False
        self._debug_losses: List[Tuple[int, jax.Array, int]] = []
        # Measured bucket-capacity overrides the current _step_fn was
        # traced with (None = default n-based capacity).
        self._step_caps: Optional[Tuple[Optional[int], ...]] = None
        self._slot_names = [s.name for s in feed_config.sparse_slots]
        # Sharded capacities: always divisible by ndev (matches
        # SlotBatch.pack_sharded / Dataset.batches_sharded shapes).
        self._slot_caps = {
            s.name: feed_config.sparse_capacity(s, num_shards=self.ndev)
            for s in feed_config.sparse_slots}
        if self.config.dense_optimizer == "adam":
            self._optax = optax.adam(self.config.dense_learning_rate)
        elif self.config.dense_optimizer == "sgd":
            self._optax = optax.sgd(self.config.dense_learning_rate)
        else:
            raise ValueError(self.config.dense_optimizer)
        # The ZeRO-sharded step decomposes the chain by hand: the clip
        # must see the FULL gradient tree (its global norm spans every
        # leaf), then the elementwise inner optimizer runs on the local
        # shards — so keep the parts addressable next to the chain.
        self._optax_base = self._optax
        self._clip_tx = None
        if self.config.grad_clip_norm > 0:
            if self.config.dense_sync_mode == "async":
                # The async path applies updates in the host
                # AsyncDenseTable, not through self._optax — chaining
                # the clip there would be silently ignored.
                raise NotImplementedError(
                    "grad_clip_norm with dense_sync_mode='async' is not "
                    "supported (the host dense table applies updates)")
            self._clip_tx = optax.clip_by_global_norm(
                self.config.grad_clip_norm)
            self._optax = optax.chain(self._clip_tx, self._optax_base)
        # FLAGS_dense_zero placement, resolved at init() (the mesh and
        # sync mode decide whether 'shard' is meaningful); the offload
        # wrapper is built lazily.
        self._dense_zero = "off"
        self._offload_tx: Optional[zero_lib.OffloadedOptimizer] = None
        self._zero_warned = False

    # -- init -------------------------------------------------------------

    @property
    def num_tasks(self) -> int:
        """Multi-task models (SharedBottomMultiTask) advertise num_tasks
        and return [B, T] logits; the trainer then trains per-task BCE
        over labels[:, :T] with a stacked per-task AUC state (role of
        the MultiTaskMetricMsg AUC family, fleet/metrics.h:346)."""
        return int(getattr(self.model, "num_tasks", 1))

    def _auc_init(self):
        nb = self.config.auc_num_buckets
        if self.num_tasks == 1:
            return auc_state_init(nb)
        return jax.vmap(lambda _: auc_state_init(nb))(
            jnp.arange(self.num_tasks))

    def _make_loss_auc(self, axis):
        """One implementation of the masked (multi-)task BCE and the
        (stacked) AUC accumulation, shared by the train and eval steps —
        the two must never drift."""
        num_tasks = self.num_tasks

        def squeeze1(t):
            # A multi-task ARCHITECTURE configured with num_tasks=1
            # still emits [B, 1]; without the squeeze the single-task
            # BCE would broadcast [B, 1] against [B] into a [B, B]
            # matrix — finite loss, silently garbage training.
            return t[:, 0] if t.ndim == 2 else t

        def loss_of(logits, labels, validf):
            # Local masked sum over the GLOBAL valid count; callers psum
            # the result to finish the cross-replica mean.
            total_valid = lax.psum(jnp.sum(validf), axis)
            if num_tasks > 1:   # [B, T]: mean over tasks
                bce = optax.sigmoid_binary_cross_entropy(
                    logits, labels[:, :num_tasks])
                return (jnp.sum(bce * validf[:, None])
                        / jnp.maximum(total_valid * num_tasks, 1.0))
            bce = optax.sigmoid_binary_cross_entropy(squeeze1(logits),
                                                     labels[:, 0])
            return jnp.sum(bce * validf) / jnp.maximum(total_valid, 1.0)

        def auc_of(auc, probs, labels, valid):
            if num_tasks > 1:
                return jax.vmap(
                    lambda st, p, l: auc_accumulate(st, p, l, valid,
                                                    axis=axis),
                    in_axes=(0, 1, 1))(auc, probs, labels[:, :num_tasks])
            return auc_accumulate(auc, squeeze1(probs), labels[:, 0],
                                  valid, axis=axis)

        return loss_of, auc_of

    def _auc_stats(self, auc) -> Dict[str, float]:
        if self.num_tasks == 1:
            return auc_compute(auc)
        per_task = [auc_compute(jax.tree.map(lambda x: x[t], auc))
                    for t in range(self.num_tasks)]
        stats = dict(per_task[0])  # task 0 (click) is the headline
        for t, st in enumerate(per_task):
            for k, v in st.items():
                stats[f"{k}_task{t}"] = v
        return stats

    def init(self, seed: int = 0) -> None:
        rng = jax.random.PRNGKey(seed)
        self.params = self.model.init(rng)
        if self.config.data_norm:
            dense_dim = sum(s.dim for s in self.feed_config.dense_slots)
            if not dense_dim:
                raise ValueError("data_norm=True but the feed declares "
                                 "no dense slots")
            # Lives in the params tree (checkpointed with the dense
            # model) but is updated by the decayed summary path, not the
            # optimizer — _build_step overwrites it after the update.
            self.params["data_norm"] = data_norm_init(dense_dim)
        self._init_dense()
        self.auc_state = self._auc_init()
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            self.auc_state = jax.device_put(self.auc_state, rep)

    # -- dense placement (FLAGS_dense_zero) -------------------------------

    def _dense_zero_mode(self) -> str:
        """Resolve FLAGS_dense_zero against the mesh and sync mode.

        'shard' + 'kstep' degrades to 'off' with one warning: ZeRO
        removes REDUNDANCY, and k-step optimizer state is worker-local
        (intentionally divergent between syncs) — there is no replicated
        copy to shard away, and an all-gather would mix per-device
        trajectories. 'offload' requires the in-step grads of
        dense_sync_mode='step' ('async' already has its own host
        updater; 'kstep' state must stay device-local per step)."""
        z = str(flags.flag("dense_zero"))
        if z not in ("off", "shard", "offload"):
            raise ValueError(
                f"dense_zero must be off|shard|offload, got {z!r}")
        if z == "off" or self.mesh is None:
            return "off"
        if z == "offload" and self.config.dense_sync_mode != "step":
            raise ValueError(
                "dense_zero='offload' requires dense_sync_mode='step' "
                f"(got {self.config.dense_sync_mode!r})")
        if z == "shard" and self.config.dense_sync_mode == "kstep":
            if not self._zero_warned:
                self._zero_warned = True
                log.warning(
                    "dense_zero='shard' ignored under "
                    "dense_sync_mode='kstep': k-step optimizer state is "
                    "worker-local (no replicated copy to shard) — "
                    "running with replicated placement")
            return "off"
        return z

    def _init_dense(self) -> None:
        """Init + place the dense params/optimizer state. Params stay
        replicated (ZeRO-1/2, not ZeRO-3 — the CTR dense half is MBs,
        the state is the redundancy worth removing); opt_state placement
        follows FLAGS_dense_zero. Checkpoints stay layout-agnostic: the
        GLOBAL shapes are identical under every mode (sharding is
        placement, not format), so save gathers to the host format and
        :meth:`place_dense` re-shards on load."""
        self._dense_zero = self._dense_zero_mode()
        if self.mesh is None:
            self.opt_state = self._optax.init(self.params)
            return
        rep = NamedSharding(self.mesh, P())
        self.params = jax.device_put(self.params, rep)
        if self._dense_zero == "offload":
            self._offload_tx = zero_lib.OffloadedOptimizer(
                self._optax, self.mesh, axis=self.axis,
                min_size=int(flags.flag("dense_zero_min_size")))
            self.opt_state = self._offload_tx.init(self.params)
        else:
            self.opt_state = self._optax.init(self.params)
            self.opt_state = jax.tree.map(
                jax.device_put, self.opt_state,
                self._opt_shardings(self.opt_state))
        self.dense_memory_stats()

    def _opt_shardings(self, state: Any):
        """Per-leaf NamedShardings of the NON-offload opt_state
        placement: replicated under 'off', zero_shardings over the table
        axis under 'shard' (replicated across slices on a multi-slice
        mesh — the hierarchical psum keeps slice replicas bit-equal, so
        only intra-slice redundancy is worth removing)."""
        if self._dense_zero == "shard":
            return zero_lib.zero_shardings(
                state, self.mesh, axis=self.axis,
                min_size=int(flags.flag("dense_zero_min_size")))
        rep = NamedSharding(self.mesh, P())
        return jax.tree.map(lambda _: rep, state)

    def place_dense(self, params: Any, opt_state: Any) -> Tuple[Any, Any]:
        """device_put HOST-format dense state into this trainer's live
        placement — the checkpoint-load half of layout agnosticism
        (save is plain device_get: global shapes are mode-invariant)."""
        if self.mesh is None:
            return params, opt_state
        rep = NamedSharding(self.mesh, P())
        params = jax.device_put(params, rep)
        if self._dense_zero == "offload":
            assert self._offload_tx is not None
            opt_state = jax.tree.map(
                jax.device_put, opt_state,
                self._offload_tx._state_shardings(opt_state))
        else:
            opt_state = jax.tree.map(jax.device_put, opt_state,
                                     self._opt_shardings(opt_state))
        return params, opt_state

    def dense_memory_stats(self) -> Dict[str, Any]:
        """Measured per-device HBM bytes of the dense half (live array
        shardings, not flag arithmetic) + placement provenance; also
        lands the dense/*_hbm_bytes gauges the benches record."""
        pb = zero_lib.tree_hbm_bytes_per_device(self.params)
        ob = zero_lib.tree_hbm_bytes_per_device(self.opt_state)
        monitor.set_gauge("dense/params_hbm_bytes", pb)
        monitor.set_gauge("dense/opt_state_hbm_bytes", ob)
        return {"params_hbm_bytes": pb, "opt_state_hbm_bytes": ob,
                "dense_zero": self._dense_zero}

    # -- the fused step ----------------------------------------------------

    def _group_layout(self) -> Tuple[List[Tuple[str, ...]],
                                     List[Dict[str, slice]]]:
        """Width groups (dynamic mf): group g's slots share one PassTable
        and one fused pull/push; slot slices index into the group's fused
        arrays."""
        caps_local = {n: self._slot_caps[n] // self.ndev
                      for n in self._slot_names}
        group_slots: List[Tuple[str, ...]] = [
            g.slots for g in self.engine.groups]
        group_sl: List[Dict[str, slice]] = []
        for slots in group_slots:
            offs = np.cumsum([0] + [caps_local[n] for n in slots])
            group_sl.append({n: slice(int(offs[i]), int(offs[i + 1]))
                             for i, n in enumerate(slots)})
        return group_slots, group_sl

    def _make_forward(self, group_slots, group_sl):
        """Shared train/eval forward: slice each width group's fused pull
        into per-slot arrays and call the model. ``emb_alls``/``w_alls``
        override the pulled emb/w so the train step can differentiate
        with respect to them."""
        model = self.model
        bs_local = self.feed_config.batch_size // self.ndev
        has_dense = bool(self.feed_config.dense_slots)
        cdt = dict(float32=jnp.float32,
                   bfloat16=jnp.bfloat16)[self.config.compute_dtype]

        def cast(tree):
            if cdt == jnp.float32:
                return tree
            return jax.tree.map(
                lambda x: x.astype(cdt)
                if x.dtype == jnp.float32 else x, tree)

        dn_slot_dim = self.config.data_norm_slot_dim

        def forward(params, pulled, segments, dense_feats,
                    emb_alls=None, w_alls=None):
            # Normalize dense features by the global stats BEFORE the
            # bf16 cast (the ~1e4-scale accumulators must stay f32);
            # the stats update happens in the train body, not here.
            params, dense_feats = normalize_dense_and_strip(
                params, dense_feats, slot_dim=dn_slot_dim)
            params = cast(params)
            dense_feats = cast(dense_feats)
            if emb_alls is not None:
                emb_alls, w_alls = cast(emb_alls), cast(w_alls)
            emb: Dict[str, jax.Array] = {}
            w: Dict[str, jax.Array] = {}
            for gi, slots in enumerate(group_slots):
                src_e = (emb_alls[gi] if emb_alls is not None
                         else cast(pulled[gi]["emb"]))
                src_w = (w_alls[gi] if w_alls is not None
                         else cast(pulled[gi]["w"]))
                for n in slots:
                    emb[n] = src_e[group_sl[gi][n]]
                    w[n] = src_w[group_sl[gi][n]]
            kwargs = dict(batch_size=bs_local,
                          dense_feats=dense_feats if has_dense else None)
            if hasattr(model, "use_cvm"):  # Wide&Deep takes show/click
                show = {n: cast(pulled[gi]["show"])[group_sl[gi][n]]
                        for gi, slots in enumerate(group_slots)
                        for n in slots}
                click = {n: cast(pulled[gi]["click"])[group_sl[gi][n]]
                         for gi, slots in enumerate(group_slots)
                         for n in slots}
                logits = model.apply(params, emb, w, show, click,
                                     segments, **kwargs)
            else:
                logits = model.apply(params, emb, w, segments, **kwargs)
            return logits.astype(jnp.float32)

        return forward

    def _build_step(self, caps: Optional[Tuple[Optional[int], ...]] = None,
                    k_steps: int = 1):
        """The fused device step. ``k_steps == 1`` (default) builds the
        per-step program with its legacy signature; ``k_steps > 1``
        wraps the SAME per-step body in a ``lax.scan`` over a stacked
        [K, ...] batch block — one XLA dispatch runs K steps, with the
        kstep sync_flag derived from an in-scan global step counter and
        loss/overflow/finite-ness accumulated on device into [K]
        outputs (one host fetch per block, not per step). A partial
        tail block is handled by ``n_active``: steps with in-block
        index >= n_active compute on the padded (repeated) batch but
        their state updates are masked out, so padding never reaches
        the tables/params/AUC."""
        axis = self.axis
        dcn = self.dcn_axis
        # Per-width-group bucket-capacity overrides (measured
        # auto-capacity, FLAGS_embedding_auto_capacity): trace-time
        # constants, so a cap change means a rebuild — train_pass
        # pow2-buckets the measurement to keep steady-state passes on
        # the same compiled step.
        caps_list = (list(caps) if caps is not None
                     else [None] * len(self.engine.groups))
        # Replica-wide reductions (loss, AUC, stats) span slice x axis;
        # table collectives (all_to_all in pull/push) stay on `axis`
        # (intra-slice ICI) with the one accumulator psum over `dcn`.
        raxes = (dcn, axis) if dcn else axis
        ndev = self.ndev
        bs_local = self.feed_config.batch_size // ndev
        optimizer = self._optax
        sparse_opt = self.sparse_opt
        group_slots, group_sl = self._group_layout()
        forward = self._make_forward(group_slots, group_sl)

        mode = self.config.dense_sync_mode
        if mode not in ("step", "kstep", "async"):
            raise ValueError(f"unknown dense_sync_mode {mode!r}")
        # FLAGS_dense_zero (resolved at init): 'shard' decomposes the
        # in-step dense update — clip on the FULL psum'd grad tree (its
        # global norm spans every leaf), elementwise inner optimizer on
        # this device's zero_slice shard (bit-identical per element),
        # tiled all-gather of the updated param shards (the psum+slice/
        # all-gather pair is exactly the reduce-scatter/all-gather
        # schedule of the weight-update-sharding paper, compiler-
        # scheduled). 'offload' makes the dense update EXTERNAL like
        # async: the step returns psum'd grads and train_pass routes
        # them through OffloadedOptimizer.
        zmode = self._dense_zero
        zmin = int(flags.flag("dense_zero_min_size"))
        z_shard = zmode == "shard" and mode == "step"
        external_dense = mode == "async" or zmode == "offload"
        if z_shard:
            pz_specs = zero_lib.zero_specs(self.params, self.mesh,
                                           axis=axis, min_size=zmin)
            z_nsh = int(self.mesh.shape[axis])
        if zmode == "shard":
            opt_spec = zero_lib.zero_specs(self.opt_state, self.mesh,
                                           axis=axis, min_size=zmin)
        else:
            opt_spec = P()
        clip_tx = self._clip_tx
        base_tx = self._optax_base
        scale_sparse = self.config.scale_sparse_grad_by_batch
        sparse_scale = float(self.feed_config.batch_size)
        loss_of, auc_of = self._make_loss_auc(raxes)
        # Dense-grad wire dtype (FLAGS_dense_allreduce_dtype): trace-time
        # constant — 'f32' keeps the sync a verbatim lax.psum /
        # hierarchical tree (bit-parity pinned); 'bf16'/'int8' narrow
        # the allreduce wire with f32 accumulation (quantized_psum).
        dense_wire = str(flags.flag("dense_allreduce_dtype"))
        if dense_wire not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"dense_allreduce_dtype must be f32|bf16|int8, "
                f"got {dense_wire!r}")
        dense_qblock = int(flags.flag("embedding_quant_block"))
        monitor.set_gauge("dense/allreduce_wire_bits",
                          {"f32": 32, "bf16": 16, "int8": 8}[dense_wire])
        dn_on = self.config.data_norm
        if dn_on and mode == "async":
            # The reference routes data_norm stats through the async
            # dense table with update_norm=False (data_norm_op.cu:253);
            # this build updates them in-step, which the async host
            # table would overwrite.
            raise NotImplementedError(
                "data_norm with dense_sync_mode='async' is not supported")
        dn_slot_dim = self.config.data_norm_slot_dim
        dn_decay = self.config.data_norm_decay

        def body(tables, params, opt_state, auc, rows, segments, labels,
                 valid, dense_feats, sync_flag):
            dn_old = params.get("data_norm") if dn_on else None
            # rows[g]: [sum caps_local over group g's slots] — each width
            # group's slots fused into ONE pull (one all_to_all pair per
            # group; G = #distinct widths, typically 1-3). The
            # bucket-by-shard layout is computed ONCE per group and
            # shared by the pull and the push below (both bucket the
            # same dev_rows — CopyKeys computed once in the reference
            # too). Passing axis shares the rows exchange and the
            # sorted-stream kernels' argsort between pull and push, so
            # the step pays 3 collectives + 1 sort per group, not 4 + 2.
            bucketings = [compute_bucketing(t, r, cap=c, axis=axis)
                          for t, r, c in zip(tables, rows, caps_list)]
            # The bucketing tuples carry their capacity — pull/push mask
            # with the capacity the buckets were built at.
            pulled = [pull_local(t, r, axis=axis, bucketing=bk)
                      for t, r, bk in zip(tables, rows, bucketings)]

            labels1 = labels[:, 0]
            validf = valid.astype(jnp.float32)

            def loss_fn(params, emb_alls, w_alls):
                logits = forward(params, pulled, segments, dense_feats,
                                 emb_alls=emb_alls, w_alls=w_alls)
                # Exact global logloss: local sum / global valid count.
                return loss_of(logits, labels, validf), logits

            grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1, 2),
                                         has_aux=True)
            (loss, logits), (g_params, g_embs, g_ws) = grad_fn(
                params, tuple(p["emb"] for p in pulled),
                tuple(p["w"] for p in pulled))

            # Dense sync (see TrainerConfig.dense_sync_mode).
            if external_dense:
                # async / offload: the host applies the update — the
                # step's job is the exact cross-replica grad sum.
                g_params = quantized_psum(g_params, raxes,
                                          wire_dtype=dense_wire,
                                          block=dense_qblock)
            elif mode == "step":
                # Grads already carry the global 1/N via the global
                # denominator — the sum over replicas completes the
                # reduction (role of SyncParam / c_allreduce_sum). On a
                # multi-slice mesh the sum is hierarchical: reduce-
                # scatter on ICI, psum the 1/dp shard over DCN,
                # all-gather back (SyncParam's exact shape,
                # boxps_worker.cc:584-645).
                if dcn:
                    # Only the slow DCN hop narrows under a reduced
                    # dense wire; the ICI hops stay f32.
                    g_params = hierarchical_psum_tree(
                        g_params, inner_axis=axis, outer_axis=dcn,
                        outer_wire_dtype=dense_wire,
                        quant_block=dense_qblock)
                else:
                    g_params = quantized_psum(g_params, axis,
                                              wire_dtype=dense_wire,
                                              block=dense_qblock)
                if z_shard:
                    if clip_tx is not None:
                        clip_state, inner_state = opt_state
                        g_params, clip_state = clip_tx.update(
                            g_params, clip_state, params)
                    else:
                        inner_state = opt_state
                    g_sl = zero_lib.zero_slice(g_params, pz_specs, axis,
                                               z_nsh)
                    p_sl = zero_lib.zero_slice(params, pz_specs, axis,
                                               z_nsh)
                    updates, inner_state = base_tx.update(g_sl,
                                                          inner_state,
                                                          p_sl)
                    p_new = optax.apply_updates(p_sl, updates)
                    params = zero_lib.zero_all_gather(p_new, pz_specs,
                                                      axis)
                    opt_state = ((clip_state, inner_state)
                                 if clip_tx is not None else inner_state)
                else:
                    updates, opt_state = optimizer.update(
                        g_params, opt_state, params)
                    params = optax.apply_updates(params, updates)
            elif mode == "kstep":
                # Local step with the unbiased full-grad estimate
                # (local grad x world size, since the loss denominator is
                # global); params pmean'd when sync_flag fires.
                g_local = jax.tree.map(lambda g: g * float(ndev), g_params)
                updates, opt_state = optimizer.update(g_local, opt_state,
                                                      params)
                params = optax.apply_updates(params, updates)
                params = lax.cond(
                    sync_flag > 0,
                    lambda p: jax.tree.map(
                        lambda x: lax.pmean(x, raxes), p),
                    lambda p: p, params)
            if dn_on:
                # Decayed summary update from the SAME stats the forward
                # normalized with (the optimizer saw zero grads for them
                # — stop_gradient — so post-update stats are unchanged);
                # psum over dp = the sync_stats allreduce.
                _, dn_new = data_norm_apply(
                    dn_old, dense_feats.astype(jnp.float32),
                    slot_dim=dn_slot_dim, summary_decay_rate=dn_decay,
                    axis_name=raxes)
                params = {**params, "data_norm": {
                    **params["data_norm"],
                    **{k: dn_new[k] for k in (
                        "batch_size", "batch_sum", "batch_square_sum")}}}

            # Sparse push per group: show=1 per occurrence, click=its
            # row's label (role of show/click stats in PushSparseGrad).
            if scale_sparse:
                g_embs = tuple(g * sparse_scale for g in g_embs)
                g_ws = tuple(g * sparse_scale for g in g_ws)
            new_tables = []
            for gi, slots in enumerate(group_slots):
                seg_g = jnp.concatenate([segments[n] for n in slots])
                occ_valid = (seg_g < bs_local).astype(jnp.float32)
                clicks = jnp.where(
                    seg_g < bs_local,
                    labels1[jnp.minimum(seg_g, bs_local - 1)],
                    0.0) * occ_valid
                new_tables.append(push_local(
                    tables[gi], rows[gi], g_embs[gi], g_ws[gi], occ_valid,
                    clicks, axis=axis, opt=sparse_opt, dcn_axis=dcn,
                    bucketing=bucketings[gi]))

            probs = jax.nn.sigmoid(logits)
            auc = auc_of(auc, probs, labels, valid)
            loss_global = lax.psum(loss, raxes)
            # Dropped-lookup observability: bucket-overflow ids degraded
            # to zero-embedding pulls and dropped grads this step, summed
            # over devices and width groups.
            overflow_global = lax.psum(
                sum(p["overflow"][0] for p in pulled), raxes)
            out = (tuple(new_tables), params, opt_state, auc, loss_global,
                   overflow_global)
            if external_dense:
                out = out + (g_params,)
            return out

        if self.mesh is None:
            raise RuntimeError("CTRTrainer requires a mesh (1-device is a "
                               "1-axis mesh)")
        # P(axis) on the tables/rows tuples is a pytree PREFIX spec:
        # every leaf of every group shards its leading dim over axis
        # (replicated across slices on a multi-slice mesh — the push
        # keeps the replicas bit-equal). Batch args shard over the
        # full replica set (slice-major matches pack_sharded order).
        dspec = P((dcn, axis)) if dcn else P(axis)
        if k_steps == 1:
            out_specs = (P(axis), P(), opt_spec, P(), P(), P())
            if external_dense:
                out_specs = out_specs + (P(),)
            body_sm = jax.shard_map(
                body, mesh=self.mesh,
                in_specs=(P(axis), P(), opt_spec, P(), dspec, dspec,
                          dspec, dspec, dspec, P()),
                out_specs=out_specs,
                check_vma=False)
            return jax.jit(body_sm, donate_argnums=(0, 1, 2, 3))

        # K-step megastep: scan the per-step body over the stacked block
        # INSIDE shard_map (collectives run per scan iteration exactly as
        # in the K=1 program — the per-step op budget is unchanged ×K).
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        if external_dense:
            # The host updater (async dense table / offload optimizer)
            # needs a pull/push around EVERY step; train_pass forces
            # K=1 for these modes before building.
            raise ValueError("steps_per_dispatch > 1 requires a device-"
                             "side dense update ('step'/'kstep'), not "
                             "'async' or dense_zero='offload'")
        k_sync = max(1, self.config.dense_sync_interval)

        def mega(tables, params, opt_state, auc, step0, n_active, rows,
                 segments, labels, valid, dense_feats):
            def scan_step(carry, xs):
                tables_c, params_c, opt_c, auc_c = carry
                ki, rows_k, segs_k, labels_k, valid_k, dense_k = xs
                # Per-step sync_flag from the in-scan step counter: the
                # SAME (global_step + 1) % interval the host computes on
                # the K=1 path — a dense-sync boundary may fall anywhere
                # inside a block.
                if mode == "kstep":
                    sync_flag = (((step0 + ki + 1) % k_sync) == 0
                                 ).astype(jnp.int32)
                else:
                    sync_flag = jnp.zeros((), jnp.int32)
                out = body(tables_c, params_c, opt_c, auc_c, rows_k,
                           segs_k, labels_k, valid_k, dense_k, sync_flag)
                new_tables, new_params, new_opt, new_auc = out[:4]
                loss, overflow = out[4], out[5]
                # Tail-block mask: padded steps (repeat of the last real
                # batch) run the math but write NOTHING — carry passes
                # through untouched, and their loss/overflow report as
                # zero / finite so the per-block outputs stay clean.
                active = ki < n_active
                carry = (_tree_select(active, new_tables, tables_c),
                         _tree_select(active, new_params, params_c),
                         _tree_select(active, new_opt, opt_c),
                         _tree_select(active, new_auc, auc_c))
                return carry, (jnp.where(active, loss, 0.0),
                               jnp.where(active, overflow,
                                         jnp.zeros_like(overflow)),
                               jnp.where(active, jnp.isfinite(loss), True))

            ks = jnp.arange(k_steps, dtype=jnp.int32)
            (tables, params, opt_state, auc), outs = lax.scan(
                scan_step, (tables, params, opt_state, auc),
                (ks, rows, segments, labels, valid, dense_feats))
            losses, overflows, finites = outs
            return tables, params, opt_state, auc, losses, overflows, finites

        sdspec = P(None, (dcn, axis)) if dcn else P(None, axis)
        mega_sm = jax.shard_map(
            mega, mesh=self.mesh,
            in_specs=(P(axis), P(), opt_spec, P(), P(), P(), sdspec,
                      sdspec, sdspec, sdspec, sdspec),
            out_specs=(P(axis), P(), opt_spec, P(), P(), P(), P()),
            check_vma=False)
        return jax.jit(mega_sm, donate_argnums=(0, 1, 2, 3))

    def _build_eval_step(self, k_steps: int = 1):
        """Read-only twin of the train step: pull + forward + AUC, no
        pushes, no param updates (role of the AUC-runner test mode,
        box_wrapper.h:900-989 / SetTestMode). ``k_steps > 1`` scans the
        same body over a stacked [K, ...] block (one dispatch per K
        eval steps), with the tail mask of the train megastep."""
        axis = self.axis
        dcn = self.dcn_axis
        raxes = (dcn, axis) if dcn else axis
        group_slots, group_sl = self._group_layout()
        forward = self._make_forward(group_slots, group_sl)
        loss_of, auc_of = self._make_loss_auc(raxes)

        def body(tables, params, auc, rows, segments, labels, valid,
                 dense_feats):
            pulled = [pull_local(t, r, axis=axis)
                      for t, r in zip(tables, rows)]
            logits = forward(params, pulled, segments, dense_feats)
            validf = valid.astype(jnp.float32)
            loss = lax.psum(loss_of(logits, labels, validf), raxes)
            auc = auc_of(auc, jax.nn.sigmoid(logits), labels, valid)
            return auc, loss

        dspec = P((dcn, axis)) if dcn else P(axis)
        if k_steps == 1:
            body_sm = jax.shard_map(
                body, mesh=self.mesh,
                in_specs=(P(self.axis), P(), P(), dspec, dspec,
                          dspec, dspec, dspec),
                out_specs=(P(), P()),
                check_vma=False)
            return jax.jit(body_sm, donate_argnums=(2,))

        def mega(tables, params, auc, n_active, rows, segments, labels,
                 valid, dense_feats):
            def scan_step(auc_c, xs):
                ki, rows_k, segs_k, labels_k, valid_k, dense_k = xs
                new_auc, loss = body(tables, params, auc_c, rows_k,
                                     segs_k, labels_k, valid_k, dense_k)
                active = ki < n_active
                return (_tree_select(active, new_auc, auc_c),
                        jnp.where(active, loss, 0.0))

            ks = jnp.arange(k_steps, dtype=jnp.int32)
            auc, losses = lax.scan(
                scan_step, auc,
                (ks, rows, segments, labels, valid, dense_feats))
            return auc, losses

        sdspec = P(None, (dcn, axis)) if dcn else P(None, axis)
        mega_sm = jax.shard_map(
            mega, mesh=self.mesh,
            in_specs=(P(self.axis), P(), P(), P(), sdspec, sdspec,
                      sdspec, sdspec, sdspec),
            out_specs=(P(), P()),
            check_vma=False)
        return jax.jit(mega_sm, donate_argnums=(2,))

    def eval_pass(self, dataset: Dataset, *, feed_keys: bool = True
                  ) -> Dict[str, float]:
        """Evaluate one pass: AUC/loss only — the store is left exactly
        as-is (no write-back, no new keys persisted, nothing dirtied)."""
        if self.params is None:
            raise RuntimeError("call init() first")
        report.init_telemetry_from_flags()
        faults.init_from_flags()
        pass_t0 = time.perf_counter()
        stage_base = self.timers.snapshot_ms()
        boundary_base = self.engine.boundary_ms()
        pipe_base = pipeline_stats.GLOBAL.snapshot()
        disp_q_base = monitor.GLOBAL.quantile_digest("trainer/dispatch_ms")
        self._seg_cache_hits = 0
        self._seg_cache_misses = 0
        n_blocks = 0
        k_disp = max(1, int(flags.flag("trainer_steps_per_dispatch")))
        if self._eval_fn is None or self._eval_k != k_disp:
            self._eval_fn = self._build_eval_step(k_steps=k_disp)
            self._eval_k = k_disp
        eng = self.engine
        if feed_keys:
            eng.feed_pass([dataset.pass_keys(slots=g.slots)
                           for g in eng.groups], readonly=True)
        tables = eng.begin_pass()
        auc = self._auc_init()
        rep = (NamedSharding(self.mesh, P())
               if self.mesh is not None else None)
        if self.mesh is not None:
            auc = jax.device_put(auc, rep)
        # Running device-side loss sum: no O(steps) retained arrays and
        # no per-step host sync — one fetch at pass end.
        loss_sum = None
        nact_full = (_put_global(np.int32(k_disp), rep)
                     if k_disp > 1 else None)
        nsteps = 0
        try:
            for args in self._prefetch_batches(dataset, k=k_disp):
                t_disp0 = time.perf_counter()
                with self.timers.scope("dispatch"), \
                        pipeline_stats.GLOBAL.busy("device"), \
                        trace.span("pass/dispatch", kind="eval",
                                   block=n_blocks, k=k_disp):
                    if k_disp == 1:
                        rows, segs, labels, valid, dense = args
                        auc, loss = self._eval_fn(tables, self.params,
                                                  auc, rows, segs, labels,
                                                  valid, dense)
                        n_active = 1
                    else:
                        rows, segs, labels, valid, dense, n_active = args
                        nact = (nact_full if n_active == k_disp
                                else _put_global(np.int32(n_active), rep))
                        auc, losses = self._eval_fn(tables, self.params,
                                                    auc, nact, rows, segs,
                                                    labels, valid, dense)
                        loss = jnp.sum(losses)
                n_blocks += 1
                watchdog.beat()
                disp_ms = (time.perf_counter() - t_disp0) * 1e3
                monitor.observe("trainer/dispatch_ms", disp_ms)
                monitor.observe_quantile("trainer/dispatch_ms", disp_ms)
                loss_sum = loss if loss_sum is None else loss_sum + loss
                nsteps += n_active
        finally:
            eng.abort_pass()
        with self.timers.scope("sync"), \
                pipeline_stats.GLOBAL.busy("device"), \
                trace.span("pass/final_fetch"):
            stats = self._auc_stats(auc)
            # graftlint: allow-sync(pass-end stat fetch inside the sync scope)
            stats["loss"] = (float(loss_sum) / nsteps if nsteps
                             else float("nan"))
        stats["steps"] = nsteps
        stats["dispatch_blocks"] = n_blocks
        stats["steps_per_dispatch"] = k_disp
        stats["seg_cache_hit_rate"] = self._seg_cache_rate()
        stats["boundary"] = self._boundary_delta(boundary_base)
        wall_s = time.perf_counter() - pass_t0
        stats["bottleneck"] = self._bottleneck_verdict(
            pipe_base, stats["boundary"], wall_s)
        stats["dispatch_ms_quantiles"] = self._dispatch_quantiles(
            disp_q_base)
        stats["pass_report"] = report.emit_pass_report(
            "eval", steps=nsteps,
            samples=nsteps * self.feed_config.batch_size,
            wall_s=wall_s,
            stage_ms=report.stage_delta(self.timers, stage_base),
            stats=stats,
            extra={"steps_per_dispatch": k_disp,
                   "seg_cache_hit_rate": stats["seg_cache_hit_rate"]})
        self._observe_quality("eval", stats, dataset, auc_state=auc)
        return stats

    def _sync_params_fn(self):
        """Jitted cross-replica param average for k-step pass boundaries."""
        if self._sync_params_cache is None:
            axis = self.axis
            raxes = ((self.dcn_axis, axis) if self.dcn_axis is not None
                     else axis)

            @jax.jit
            @functools.partial(
                jax.shard_map, mesh=self.mesh, in_specs=P(),
                out_specs=P(), check_vma=False)
            def sync(params):
                return jax.tree.map(lambda x: lax.pmean(x, raxes), params)

            self._sync_params_cache = sync
        return self._sync_params_cache

    def _prefetch_batches(self, dataset: Dataset, k: int = 1):
        """Producer thread packs + host-maps batch k+1 while batch k's
        device step executes (role of the reference's pipelined batch
        packing + preload threads, MiniBatchGpuPack data_feed.cc:4611,
        PreLoadIntoMemory box_wrapper.h:1140). The host work (numpy pack,
        native keymap lookup — both GIL-releasing) runs concurrently with
        the asynchronously-dispatched device computation; a small bounded
        queue keeps the device fed without unbounded host memory.

        Transfer thrift (the host↔device link, not the pack, bounds this
        pipeline on tunnel-attached TPUs): per-slot segment arrays are
        usually IDENTICAL between consecutive full batches of fixed-length
        slots (identity layout), so the producer reuses the previous
        device copy when the host bytes match instead of re-transferring
        ~2 MB per batch; dense features ship in the compute dtype (bf16
        halves them under AMP).

        ``k > 1`` (FLAGS_trainer_steps_per_dispatch): the producer stacks
        K packed batches into ONE leading-axis block — yields 6-tuples
        ``(rows, segs, labels, valid, dense, n_active)`` with [K, ...]
        device arrays for the scanned megastep. The segment cache works
        on the stacked host arrays (consecutive full blocks of
        fixed-length slots are still byte-identical) and a partial tail
        block is padded by repeating the last real batch with
        ``n_active < K`` (the scan masks the padding out). ``k == 1``
        yields the legacy per-batch 5-tuples."""
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(flags.flag("trainer_prefetch_depth"))))
        _DONE = object()
        stop = threading.Event()
        seg_cache: Dict[str, Tuple[np.ndarray, jax.Array]] = {}
        dense_bf16 = self.config.compute_dtype == "bfloat16"
        # Explicit global placement: every process passes the SAME host
        # array and owns only its addressable shards — which is what makes
        # the identical code run under multi-process (jax.distributed)
        # clusters, where bare jnp.asarray would produce non-addressable
        # single-device arrays.
        dspec = (P((self.dcn_axis, self.axis))
                 if self.dcn_axis is not None else P(self.axis))
        data_sh = (NamedSharding(self.mesh, dspec)
                   if self.mesh is not None else None)
        # Stacked blocks shard dim 1 (dim 0 is the K steps axis).
        stk_spec = (P(None, (self.dcn_axis, self.axis))
                    if self.dcn_axis is not None else P(None, self.axis))
        stk_sh = (NamedSharding(self.mesh, stk_spec)
                  if self.mesh is not None else None)

        def _dev(host):
            return _put_global(host, data_sh)

        def _dev_stk(host):
            return _put_global(host, stk_sh)

        def _seg_dev(name: str, host: np.ndarray,
                     put=None) -> jax.Array:
            hit = seg_cache.get(name)
            if hit is not None and np.array_equal(hit[0], host):
                # Single-writer counters: only the producer thread
                # touches them mid-pass; the pass reader consumes after
                # the queue drains (and the reset happens pre-start).
                # graftlint: allow-lock(single producer; read post-drain)
                self._seg_cache_hits += 1
                return hit[1]
            # graftlint: allow-lock(single producer; read post-drain)
            self._seg_cache_misses += 1
            dev = (put or _dev)(host)
            seg_cache[name] = (host.copy(), dev)
            return dev

        def _put(item) -> bool:
            # blocked_down on the packer stage: time spent here with the
            # queue FULL means the device side is the slower half (a
            # healthy sign); near-zero put-wait with a starved consumer
            # means the host pipeline is the wall.
            with pipeline_stats.GLOBAL.blocked_down("packer"):
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        pipeline_stats.GLOBAL.sample_queue(
                            "producer_queue", q.qsize())
                        return True
                    except queue.Full:
                        continue
                return False

        n_groups = len(self.engine.groups)
        # Map-ahead worker (FLAGS_trainer_map_ahead): the host keymap
        # lookup of batch i+1 runs on this ONE worker while the producer
        # packs + transfers batch i — the CopyKeys host map leaves the
        # prefetch critical path entirely (the native hash probe and the
        # sharded numpy fallback both release the GIL, so the two
        # threads genuinely overlap).
        mapper = None
        if flags.flag("trainer_map_ahead"):
            from concurrent.futures import ThreadPoolExecutor
            mapper = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="pbx-map-ahead")

        def _map_rows_timed(batch):
            # Stage split (PrintSyncTimer vocabulary): "pull" is the host
            # half of PullSparse (feasign -> device-row keymap, the
            # CopyKeys role); "pack" is batch assembly + dtype prep.
            faults.faultpoint("trainer/map_ahead")
            with self.timers.scope("pull"), trace.span("prefetch/keymap"), \
                    pipeline_stats.GLOBAL.busy("keymap"):
                return self._map_batch_rows_host(batch)

        def _pack_host(batch, rows_h):
            faults.faultpoint("trainer/pack")
            with self.timers.scope("pack"), \
                    pipeline_stats.GLOBAL.busy("packer"):
                dense_h = _concat_dense_host(batch)
                if dense_bf16:
                    import ml_dtypes
                    dense_h = dense_h.astype(ml_dtypes.bfloat16)
                return (rows_h,
                        {n: batch.segments[n] for n in self._slot_names},
                        batch.labels, batch.valid, dense_h)

        def _stack_block(blk):
            with self.timers.scope("pack"), \
                    pipeline_stats.GLOBAL.busy("packer"):
                n_active = len(blk)
                # static-shape tail pad
                blk = blk + [blk[-1]] * (k - n_active)
                rows = tuple(_dev_stk(np.stack([b[0][g] for b in blk]))
                             for g in range(n_groups))
                segs = {n: _seg_dev(n, np.stack([b[1][n] for b in blk]),
                                    put=_dev_stk)
                        for n in self._slot_names}
                return (rows, segs,
                        _dev_stk(np.stack([b[2] for b in blk])),
                        _dev_stk(np.stack([b[3] for b in blk])),
                        _dev_stk(np.stack([b[4] for b in blk])),
                        n_active)

        _EOF = object()

        def producer():
            buf: List[tuple] = []
            it = iter(dataset.batches_sharded(self.ndev))

            def read_next():
                # "read" = waiting on the dataset iterator (columnar
                # slice/channel pop — the reference's ReadInstance
                # timer); separate from pack/pull so a starved pass
                # is distinguishable from a slow keymap.
                faults.faultpoint("trainer/prefetch")
                with self.timers.scope("read"), \
                        pipeline_stats.GLOBAL.busy("reader"):
                    return next(it, _EOF)

            try:
                batch = read_next()
                fut = (mapper.submit(_map_rows_timed, batch)
                       if mapper is not None and batch is not _EOF
                       else None)
                while batch is not _EOF:
                    # Kick batch i+1's keymap map NOW: it runs on the
                    # mapper worker while this thread packs + transfers
                    # batch i below.
                    nxt = read_next()
                    fut_n = (mapper.submit(_map_rows_timed, nxt)
                             if mapper is not None and nxt is not _EOF
                             else None)
                    rows_h = (fut.result() if fut is not None
                              else _map_rows_timed(batch))
                    if k == 1:
                        faults.faultpoint("trainer/pack")
                        with self.timers.scope("host_map"), \
                                trace.span("prefetch/host_map"):
                            with self.timers.scope("pack"), \
                                    pipeline_stats.GLOBAL.busy("packer"):
                                dense_h = _concat_dense_host(batch)
                                if dense_bf16:
                                    import ml_dtypes
                                    dense_h = dense_h.astype(
                                        ml_dtypes.bfloat16)
                                args = (tuple(_dev(h) for h in rows_h),
                                        {n: _seg_dev(n,
                                                     batch.segments[n])
                                         for n in self._slot_names},
                                        _dev(batch.labels),
                                        _dev(batch.valid),
                                        _dev(dense_h))
                        if not _put(args):
                            return  # consumer bailed early
                        batch, fut = nxt, fut_n
                        continue
                    with self.timers.scope("host_map"), \
                            trace.span("prefetch/host_map", k=k):
                        buf.append(_pack_host(batch, rows_h))
                        args = (_stack_block(buf) if len(buf) == k
                                else None)
                        if args is not None:
                            buf = []
                    if args is not None and not _put(args):
                        return
                    batch, fut = nxt, fut_n
                if buf:
                    with self.timers.scope("host_map"):
                        args = _stack_block(buf)
                    if not _put(args):
                        return
            except BaseException as e:
                _put(e)
                return
            _put(_DONE)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                # blocked_up on the device stage: the consumer (and so
                # the device's supply of new blocks) starved waiting on
                # the host pipeline — the device_idle_frac numerator.
                with pipeline_stats.GLOBAL.blocked_up("device"):
                    item = q.get()
                pipeline_stats.GLOBAL.sample_queue("producer_queue",
                                                   q.qsize())
                if item is _DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # Unblock the producer if we exited early (error mid-pass).
            stop.set()
            t.join(timeout=60.0)
            if mapper is not None:
                mapper.shutdown(wait=False)

    def _map_batch_rows_host(self, batch: SlotBatch) -> List[np.ndarray]:
        """Host map: batch feasigns → per-width-group fused device-row
        arrays (role of CopyKeys' host side, one array per dim group) —
        host side only, so the K-stacking prefetcher can np.stack K
        batches before the one device transfer."""
        rows = []
        for gi, g in enumerate(self.engine.groups):
            all_ids = np.concatenate([batch.ids[n] for n in g.slots])
            r = self.engine.lookup_rows(gi, all_ids)
            # Interleave per-device: [dev, slot, cap_local] flatten.
            rows.append(_interleave_slots(r, list(g.slots),
                                          self._slot_caps, self.ndev))
        return rows

    def _map_batch_rows(self, batch: SlotBatch) -> Tuple[jax.Array, ...]:
        dspec = (P((self.dcn_axis, self.axis))
                 if self.dcn_axis is not None else P(self.axis))
        data_sh = (NamedSharding(self.mesh, dspec)
                   if self.mesh is not None else None)
        return tuple(_put_global(h, data_sh)
                     for h in self._map_batch_rows_host(batch))

    def export_serving(self, path: str) -> Dict[str, object]:
        """One-call serving export: the xbox sparse model (emb + w, no
        optimizer state — save_xbox_base_model role, fleet_util.py:774)
        plus a BARE dense-params snapshot and a ``meta.json`` naming the
        table and the data_norm configuration — everything
        ``serving.load_serving_predictor(model, feed, path)`` needs to
        stand a predictor up (the meta matters: a hand-built fresh
        template would silently DROP the trainer-added data_norm stats
        and serve un-normalized probabilities). Training-resume
        snapshots (params + optimizer state) are the checkpoint
        protocol's job, not this artifact's."""
        import json
        import os

        from paddlebox_tpu.checkpoint.dense import save_pytree

        if self.params is None:
            raise RuntimeError("call init() (and train) before exporting")
        os.makedirs(path, exist_ok=True)
        xbox = os.path.join(path, "xbox")
        n = int(self.engine.store.save_xbox(xbox))
        dense = os.path.join(path, "dense.npz")
        save_pytree(jax.device_get(self.params), dense)
        meta = {
            "table": self.table_config.name,
            "data_norm": bool(self.config.data_norm),
            "dense_dim": int(sum(s.dim
                                 for s in self.feed_config.dense_slots)),
            "data_norm_slot_dim": int(self.config.data_norm_slot_dim),
            "compute_dtype": self.config.compute_dtype,
        }
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=1)
        return {"xbox": xbox, "dense": dense, "features": n,
                "meta": os.path.join(path, "meta.json")}

    def _measure_caps(self, tables, rows) -> List[Optional[int]]:
        """Per-group measured bucket capacity: the first batch's worst
        per-(device, shard) row count — UNIQUE rows when dedup is on (a
        cell holds a unique id), occurrences otherwise — with the
        shard-slack headroom, rounded up to a power of two
        (compile-stability bucketing) and clamped to the per-device id
        count. Role of the reference sizing its shard buffers from the
        actual batch (heter_comm_inl.h:273 walks real counts) — here the
        shapes must be static, so measure once per pass and retrace only
        when the pow2 bucket grows."""
        slack = float(flags.flag("embedding_shard_slack"))
        dedup = bool(flags.flag("embedding_dedup"))
        caps: List[Optional[int]] = []
        for t, r in zip(tables, rows):
            if t.num_shards == 1:
                caps.append(None)
                continue
            block = t.rows_per_shard + 1
            # r is flat [n] (per-step) or stacked [K, n] (megastep first
            # block): either way measure every (step, device) row set —
            # the scanned fn compiles ONCE for the block, so its caps
            # must cover the worst batch in it.
            arr = np.asarray(r)
            rr = arr.reshape(-1, arr.shape[-1] // self.ndev)
            worst = 1
            for d in range(rr.shape[0]):
                vals = np.unique(rr[d]) if dedup else rr[d]
                shard = np.clip(vals // block, 0, t.num_shards - 1)
                worst = max(worst, int(np.bincount(
                    shard, minlength=t.num_shards).max()))
            n_local = rr.shape[1]
            c = min(max(int(slack * worst) + 8, 8), n_local)
            c = min(1 << (c - 1).bit_length(), n_local)
            caps.append(c)
        return caps

    # -- pass loop ---------------------------------------------------------

    def train_pass(self, dataset: Dataset, *, feed_keys: bool = True
                   ) -> Dict[str, float]:
        """Train one pass over the dataset (role of train_from_dataset +
        begin_pass/end_pass, SURVEY.md §3.1)."""
        if self.params is None:
            raise RuntimeError("call init() first")
        # Telemetry is host-side only: flag-armed sinks, a per-pass stage
        # baseline (the TimerGroup is cumulative across passes — bench.py
        # reads the totals), and seg-cache counters. NOTHING below adds
        # ops or syncs to the jitted step.
        report.init_telemetry_from_flags()
        faults.init_from_flags()
        pass_t0 = time.perf_counter()
        stage_base = self.timers.snapshot_ms()
        boundary_base = self.engine.boundary_ms()
        pipe_base = pipeline_stats.GLOBAL.snapshot()
        disp_q_base = monitor.GLOBAL.quantile_digest("trainer/dispatch_ms")
        self._seg_cache_hits = 0
        self._seg_cache_misses = 0
        eng = self.engine
        mode = self.config.dense_sync_mode
        k = max(1, self.config.dense_sync_interval)
        profiling = bool(flags.flag("profile_trainer"))
        check_nan = (self.config.check_nan_inf
                     or flags.flag("check_nan_inf"))
        # K-step megastep (FLAGS_trainer_steps_per_dispatch): one scanned
        # XLA dispatch per K steps. Two configs force K=1: async dense
        # sync needs a host pull/push around every step, and the profiler
        # needs per-step dispatch boundaries to time.
        k_disp = max(1, int(flags.flag("trainer_steps_per_dispatch")))
        if k_disp > 1 and mode == "async":
            log.vlog(0, "trainer_steps_per_dispatch=%d ignored: "
                     "dense_sync_mode='async' pulls/pushes the host dense "
                     "table around every step — running K=1", k_disp)
            k_disp = 1
        # dense_zero='offload' is the other external-update mode: the
        # host-resident optimizer needs the grads around every step.
        offload = self._dense_zero == "offload"
        if k_disp > 1 and offload:
            log.vlog(0, "trainer_steps_per_dispatch=%d ignored: "
                     "dense_zero='offload' routes the dense update "
                     "through the host-pinned optimizer every step — "
                     "running K=1", k_disp)
            k_disp = 1
        if k_disp > 1 and profiling:
            log.vlog(0, "trainer_steps_per_dispatch=%d ignored under "
                     "FLAGS_profile_trainer (per-step timing needs "
                     "per-step dispatch) — running K=1", k_disp)
            k_disp = 1
        if feed_keys:
            with self.timers.scope("feed_pass"):
                eng.feed_pass([dataset.pass_keys(slots=g.slots)
                               for g in eng.groups])
        tables = eng.begin_pass()
        params, opt_state = self.params, self.opt_state
        auc = self.auc_state
        if mode == "async" and self._async_dense is None:
            from paddlebox_tpu.train.async_dense import AsyncDenseTable
            self._async_dense = AsyncDenseTable(
                # graftlint: allow-sync(async mode seeds the HOST dense table once)
                jax.device_get(params),
                learning_rate=self.config.dense_learning_rate)
        rep = (NamedSharding(self.mesh, P())
               if self.mesh is not None else None)
        # Pre-built replicated step flags: creating them per step would
        # issue host->device transfers (with cross-process consistency
        # collectives under jax.distributed) racing the prefetch thread's.
        flags_01 = (_put_global(np.int32(0), rep),
                    _put_global(np.int32(1), rep))
        nact_full = (_put_global(np.int32(k_disp), rep)
                     if k_disp > 1 else None)
        # Device-side running sums: the pass keeps TWO device scalars
        # alive instead of O(steps) retained loss/overflow arrays, and
        # nothing here blocks the dispatch pipeline.
        loss_sum = None
        overflow_sum = None
        group_n: Optional[List[int]] = None
        first_batch_dup = None
        nsteps = 0
        self._dispatch_blocks = 0
        self._host_syncs = 0
        if self._debug_collect_losses:
            self._debug_losses = []
        # check_nan_inf without the per-step float(loss) sync: each
        # dispatch also yields a device-side finite-ness vector; the host
        # fetches block i-1's verdict while block i executes (one sync
        # per BLOCK, one block late mid-pass, exact at pass end).
        pending_finite = None

        def _check_pending():
            nonlocal pending_finite
            if pending_finite is None:
                return
            base, fin, na = pending_finite
            pending_finite = None
            self._host_syncs += 1
            with self.timers.scope("sync"), \
                    pipeline_stats.GLOBAL.busy("device"), \
                    trace.span("pass/sync_finite"):
                fv = np.asarray(fin)[:na]
            if not fv.all():
                bad = base + int(np.argmin(fv)) + 1
                raise FloatingPointError(f"NaN/Inf loss at step {bad}")

        for args in self._prefetch_batches(dataset, k=k_disp):
            if k_disp == 1:
                rows, segs, labels, valid, dense = args
                n_active = 1
            else:
                rows, segs, labels, valid, dense, n_active = args
            if group_n is None:
                # Per-device id count per width group — static across the
                # pass, feeds the exchange-bytes observable below. The
                # duplication factor (occurrences per unique id in the
                # first batch) tells the operator how much headroom
                # FLAGS_embedding_unique_frac could reclaim: dedup means
                # bucket cells hold UNIQUE ids, so unique_frac can drop
                # toward 1/duplication before overflow risk returns.
                group_n = [int(r.shape[-1]) // max(self.ndev, 1)
                           for r in rows]
                addressable = all(getattr(r, "is_fully_addressable", True)
                                  for r in rows)
                if addressable:
                    # Duplication is a first-BATCH signal: slice step 0
                    # out of a stacked [K, n] block.
                    firsts = [np.asarray(r)[0] if k_disp > 1
                              else np.asarray(r) for r in rows]
                    occ = sum(int(f.shape[0]) for f in firsts)
                    uniq = sum(len(np.unique(f)) for f in firsts)
                    first_batch_dup = occ / max(uniq, 1)
                if addressable and flags.flag("embedding_auto_capacity"):
                    # Measured capacity (pow2-bucketed): size each
                    # group's bucket to the first batch's worst
                    # per-(device, shard) cell demand instead of the
                    # n-based binomial bound. Caps only RATCHET UP: a
                    # pass measuring smaller keeps the compiled (larger,
                    # still-safe) step, so re-measurement jitter across
                    # passes can never recompile mid-run — only a batch
                    # genuinely exceeding the warmed capacity does.
                    meas = self._measure_caps(tables, rows)
                    cur = self._step_caps
                    merged = tuple(
                        c if cur is None or cur[i] is None
                        else (None if c is None else max(c, cur[i]))
                        for i, c in enumerate(meas))
                    if merged != cur:
                        self._step_caps = merged
                        self._step_fn = None
                        self._mega_fn = None
                        log.vlog(0, "auto-capacity: bucket caps %s "
                                 "(measured from first %s)",
                                 list(merged),
                                 "stacked block" if k_disp > 1
                                 else "batch")
                else:
                    if (flags.flag("embedding_auto_capacity")
                            and not addressable
                            and not getattr(self, "_autocap_warned",
                                            False)):
                        # Multi-host: rows span processes, so the host
                        # cannot measure them — say so ONCE (per
                        # trainer) instead of silently delivering zero
                        # byte reduction every pass.
                        self._autocap_warned = True
                        log.warning(
                            "auto-capacity requested but batch rows are "
                            "not fully addressable (multi-host run) — "
                            "using the default n-based capacity")
                    if self._step_caps is not None:
                        # Flag turned off (or data not addressable):
                        # drop back to the default-capacity step.
                        self._step_caps = None
                        self._step_fn = None
                        self._mega_fn = None
                # Build (or reuse) the compiled fn for this pass's K —
                # AFTER the capacity measurement above, so the scanned
                # megastep is traced at the measured caps (caps only
                # ratchet up; a steady-state pass reuses the warm fn).
                if k_disp == 1:
                    if self._step_fn is None:
                        self._step_fn = self._build_step(
                            caps=self._step_caps)
                elif self._mega_fn is None or self._mega_k != k_disp:
                    self._mega_fn = self._build_step(
                        caps=self._step_caps, k_steps=k_disp)
                    self._mega_k = k_disp
            if mode == "async":
                # PullDense role: freshest host params each step.
                params = jax.device_put(self._async_dense.pull_dense(), rep)
            block_base = nsteps
            t_disp0 = time.perf_counter()
            # "dispatch" = the host-side enqueue wall of the (async)
            # compiled-program launch; under FLAGS_profile_trainer the
            # per-step sync runs inside, so the same scope degenerates to
            # the synced step wall (credited to fwd_bwd below).
            with self.timers.scope("device_step"), \
                    self.timers.scope("dispatch"), \
                    pipeline_stats.GLOBAL.busy("device"), \
                    trace.span("pass/dispatch",
                               block=self._dispatch_blocks, k=k_disp):
                if k_disp == 1:
                    sync_flag = flags_01[
                        1 if (mode == "kstep" and (nsteps + 1) % k == 0)
                        else 0]
                    out = self._step_fn(
                        tables, params, () if offload else opt_state,
                        auc, rows, segs, labels, valid, dense, sync_flag)
                    tables, params, opt_out, auc, loss, overflow = out[:6]
                    if not offload:
                        opt_state = opt_out
                    blk_losses, blk_overflow = loss, overflow
                    if profiling:
                        # Completion INSIDE the scope so device_step
                        # records the real step wall time, not async
                        # dispatch. Profiling trades the pipelining away
                        # on purpose (TrainFilesWithProfiler does the
                        # same).
                        # graftlint: allow-sync(FLAGS_profile_trainer syncs per step by design)
                        float(loss)
                else:
                    # ONE dispatch runs n_active steps; the in-scan step
                    # counter starts at this block's first global step.
                    step0 = _put_global(np.int32(nsteps), rep)
                    nact = (nact_full if n_active == k_disp
                            else _put_global(np.int32(n_active), rep))
                    out = self._mega_fn(
                        tables, params, opt_state, auc, step0, nact,
                        rows, segs, labels, valid, dense)
                    (tables, params, opt_state, auc, blk_losses,
                     blk_overflows, blk_finites) = out
                    blk_overflow = jnp.sum(blk_overflows)
            self._dispatch_blocks += 1
            # Stall-watchdog heartbeat: per-block dispatch progress is
            # the liveness signal (one cached-bool no-op when disarmed).
            watchdog.beat()
            disp_s = time.perf_counter() - t_disp0
            # Step-latency distribution (host-observed block enqueue
            # wall): the pass report's histogram feed, plus the
            # log-bucketed digest behind the per-pass p50/p90/p99/p999.
            monitor.observe("trainer/dispatch_ms", disp_s * 1e3)
            monitor.observe_quantile("trainer/dispatch_ms", disp_s * 1e3)
            if profiling and k_disp == 1:
                # Profiling syncs per step, so the block wall IS the
                # fused device step (pull+fwd-bwd+push) — the closest
                # host-observable stand-in for the fwd_bwd stage.
                self.timers["fwd_bwd"].add_elapsed(disp_s)
            if mode == "async":
                # PushDense role: hand psum'd grads to the host updater.
                # graftlint: allow-sync(async dense pulls grads to the host each step by design)
                self._async_dense.push_dense(jax.device_get(out[6]))
            elif offload:
                # The offload round-trip: stage host state -> HBM, run
                # the jitted update, stream the new state back to its
                # host pinning, apply updates to the replicated params.
                # All transfers are async dispatches — nothing here
                # blocks on the device.
                params, opt_state = self._offload_tx.update_apply(
                    out[6], opt_state, params)
            nsteps += n_active
            if profiling and k_disp == 1:
                # graftlint: allow-sync(FLAGS_profile_trainer per-step log)
                log.vlog(0, "step %d: loss=%.5f %s", nsteps, float(loss),
                         self.timers.report())
            blk_loss = (blk_losses if k_disp == 1
                        else jnp.sum(blk_losses))
            loss_sum = blk_loss if loss_sum is None else loss_sum + blk_loss
            overflow_sum = (blk_overflow if overflow_sum is None
                            else overflow_sum + blk_overflow)
            if self._debug_collect_losses:
                self._debug_losses.append((block_base, blk_losses,
                                           n_active))
            if check_nan:
                # Fetch block i-1's verdict while block i executes —
                # the device never idles waiting on the host check.
                _check_pending()
                fin = (jnp.isfinite(blk_losses).reshape(1)
                       if k_disp == 1 else blk_finites)
                pending_finite = (block_base, fin, n_active)
        if check_nan:
            _check_pending()
        if mode == "kstep" and nsteps % k != 0:
            # Pass boundary: leave params synchronized regardless of
            # where the last sync fell (the reference's pass-end
            # SyncParam does the same).
            params = self._sync_params_fn()(params)
        if mode == "async":
            self._async_dense.flush()
            params = jax.device_put(self._async_dense.pull_dense(), rep)
        eng.update_tables(tables)
        self.params, self.opt_state, self.auc_state = params, opt_state, auc
        # "push" = the host-visible half of PushSparse: the pass-end
        # table write-back into the persistent store (the in-step push
        # is fused into the jitted program and rides "dispatch").
        with self.timers.scope("end_pass"), self.timers.scope("push"), \
                trace.span("pass/end_pass"):
            eng.end_pass()
        # "sync" = blocking device fetches: the pass-end stat reductions
        # (plus any deferred finite-vector fetches counted above).
        with self.timers.scope("sync"), \
                pipeline_stats.GLOBAL.busy("device"), \
                trace.span("pass/final_fetch"):
            stats = self._auc_stats(self.auc_state)
            # graftlint: allow-sync(pass-end stat fetch inside the sync scope)
            stats["loss"] = (float(loss_sum) / nsteps if nsteps
                             else float("nan"))
        stats["steps"] = nsteps
        stats["steps_per_dispatch"] = k_disp
        stats["dispatch_blocks"] = self._dispatch_blocks
        stats["host_syncs"] = self._host_syncs
        with self.timers.scope("sync"):
            stats["lookup_overflow"] = (
                # graftlint: allow-sync(pass-end stat fetch inside the sync scope)
                int(overflow_sum) if overflow_sum is not None else 0)
        # Static per-device all-to-all bytes for one pull+push round —
        # what dedup + FLAGS_embedding_unique_frac shrink (the dedup-
        # before-exchange observable; heter_comm.h:192 transfers merged
        # keys for the same reason). record_exchange_stats also lands
        # it in the metric registry + trace counter.
        caps_now = (list(self._step_caps) if self._step_caps is not None
                    else [None] * len(group_n or []))
        stats["lookup_exchange_bytes"] = (
            record_exchange_stats(tables, group_n, caps_now)
            if group_n else 0)
        # Occurrences per unique id in the pass's first batch: the
        # operator's sizing signal for FLAGS_embedding_unique_frac
        # (safe floor ~= 1/duplication).
        stats["lookup_duplication"] = (
            round(first_batch_dup, 3) if group_n and first_batch_dup
            else None)
        stats["scale_sparse_grad_by_batch"] = bool(
            self.config.scale_sparse_grad_by_batch)
        if stats["lookup_overflow"]:
            monitor.add("embedding/lookup_overflow",
                        stats["lookup_overflow"])
            log.warning("pass had %d overflowed sparse lookups (dropped "
                        "pull+grad) — raise FLAGS_embedding_shard_slack "
                        "if the key distribution is skewed",
                        stats["lookup_overflow"])
        stats["seg_cache_hit_rate"] = self._seg_cache_rate()
        stats["boundary"] = self._boundary_delta(boundary_base)
        wall_s = time.perf_counter() - pass_t0
        # Critical-path attribution: the occupancy window over this pass
        # plus the boundary halves -> ONE bottleneck verdict, and the
        # dispatch-latency digest window -> p50/p90/p99/p999.
        stats["bottleneck"] = self._bottleneck_verdict(
            pipe_base, stats["boundary"], wall_s)
        stats["dispatch_ms_quantiles"] = self._dispatch_quantiles(
            disp_q_base)
        # The PrintSyncTimer moment: ONE structured per-pass summary
        # line + registry/JSONL publish (core.report).
        stats["pass_report"] = report.emit_pass_report(
            "train", steps=nsteps,
            samples=nsteps * self.feed_config.batch_size,
            wall_s=wall_s,
            stage_ms=report.stage_delta(self.timers, stage_base),
            stats=stats,
            extra={"steps_per_dispatch": k_disp,
                   "seg_cache_hit_rate": stats["seg_cache_hit_rate"],
                   "lookup_duplication": stats["lookup_duplication"]})
        self._observe_quality("train", stats, dataset)
        log.vlog(0, "pass done: steps=%d loss=%.5f auc=%.5f (%s)",
                 nsteps, stats["loss"], stats["auc"], self.timers.report())
        return stats

    def _observe_quality(self, kind: str, stats: Dict[str, float],
                         dataset, auc_state=None) -> None:
        """Fold the finished pass into the model-quality plane
        (FLAGS_quality_collect, core/quality.py): the host copy of the
        device AUC histogram localizes a COPC excursion into prediction
        buckets, the dataset's load-time slot-health snapshot carries
        coverage/churn/skew, and the tracker raises the drift alarms +
        the quality_report line beside the pass_report. Host-side only
        — one extra pass-end table fetch, zero device ops."""
        if not quality.enabled():
            return
        auc = auc_state if auc_state is not None else self.auc_state
        q_table = None
        if self.num_tasks == 1 and auc is not None:
            with self.timers.scope("sync"), \
                    pipeline_stats.GLOBAL.busy("device"):
                # graftlint: allow-sync(pass-end quality table fetch inside the sync scope)
                q_table = np.asarray(auc.table, np.float64)
        # Slot health rides TRAIN passes only: eval re-walks the same
        # dataset (slot_replacement_eval runs many evals per load), and
        # feeding the churn/coverage baselines duplicate snapshots of
        # one load would dilute the drift signal with zeros.
        health_fn = (getattr(dataset, "quality_health", None)
                     if kind == "train" else None)
        summary = quality.GLOBAL.observe_pass(
            kind, stats=stats, auc_table=q_table,
            health=health_fn() if health_fn is not None else None)
        if summary is not None:
            stats["quality_report"] = summary

    def _seg_cache_rate(self) -> Optional[float]:
        total = self._seg_cache_hits + self._seg_cache_misses
        return round(self._seg_cache_hits / total, 4) if total else None

    def _boundary_delta(self, base: Dict[str, float]) -> Dict[str, float]:
        """Per-pass pass-boundary breakdown: deltas of the engine's
        cumulative boundary timers over this pass's window. In a
        pipelined day loop the NEXT pass's (overlapped) build lands in
        this window — exactly the boundary this pass paid for.
        ``overlap_frac`` = the fraction of the build that ran while
        training still owned the store (1.0 = fully hidden; 0.0 = the
        r04 serial boundary)."""
        now = self.engine.boundary_ms()
        d = {key: round(now[key] - base.get(key, 0.0), 3) for key in now}
        build = d.get("build_ms", 0.0)
        wait = d.get("feed_wait_ms", 0.0)
        d["overlap_frac"] = (round(min(1.0, max(0.0, 1.0 - wait / build)),
                                   4)
                             if build > 1e-6 else None)
        # Background DCN exchange (MultiHostStore worker): the fraction
        # of exchange bytes that moved while the caller was doing other
        # work. No exchange work this pass -> no row (the gauge would
        # lie at 1.0 on single-host tiers).
        xbusy = d.get("exchange_busy_ms", 0.0)
        xwait = d.get("exchange_wait_ms", 0.0)
        if xbusy > 1e-6:
            d["exchange_overlap_frac"] = round(
                min(1.0, max(0.0, 1.0 - xwait / xbusy)), 4)
        return d

    def _bottleneck_verdict(self, pipe_base, boundary,
                            wall_s: float) -> Dict[str, Any]:
        """The pass's critical-path verdict: the occupancy window since
        ``pipe_base`` (reader/packer/keymap/device states + queue
        depths) with the engine's boundary halves injected as a
        ``boundary`` stage (build minus its blocked wait = busy; the
        wait itself = blocked_up; end_pass write-back counts as busy —
        it holds the store against the next build)."""
        win = pipeline_stats.GLOBAL.window(pipe_base)
        b = boundary or {}
        build = float(b.get("build_ms") or 0.0)
        wait = float(b.get("feed_wait_ms") or 0.0)
        end = float(b.get("end_ms") or 0.0)
        if build > 1e-6 or wait > 1e-6 or end > 1e-6:
            win["stages"]["boundary"] = {
                "busy_ms": round(max(build - wait, 0.0) + end, 3),
                "blocked_up_ms": round(wait, 3),
                "blocked_down_ms": 0.0, "count": 1}
        return pipeline_stats.bottleneck_verdict(win, wall_s * 1e3)

    def _dispatch_quantiles(self, base) -> Optional[Dict[str, float]]:
        """This pass's dispatch-latency p50/p90/p99/p999 from the
        cumulative registry digest, windowed by count subtraction."""
        d = monitor.GLOBAL.quantile_digest("trainer/dispatch_ms")
        if d is None:
            return None
        w = d.delta(base)
        if not w.count:
            return None
        out = {k: (round(v, 3) if v is not None else None)
               for k, v in w.quantiles().items()}
        out["count"] = w.count
        return out

    def reset_metrics(self) -> None:
        self.auc_state = self._auc_init()
        if self.mesh is not None:
            self.auc_state = jax.device_put(
                self.auc_state, NamedSharding(self.mesh, P()))


def _interleave_slots(rows_concat: np.ndarray, names: List[str],
                      caps: Dict[str, int], ndev: int) -> np.ndarray:
    """Reorder [slotA(all devs), slotB(all devs), ...] into per-device
    groups [dev0: slotA,slotB..., dev1: ...] so sharding the flat array
    over dp gives each device its own slots' local ids contiguously."""
    parts = []
    off = 0
    per_slot = {}
    for n in names:
        per_slot[n] = rows_concat[off:off + caps[n]].reshape(ndev, -1)
        off += caps[n]
    for d in range(ndev):
        for n in names:
            parts.append(per_slot[n][d])
    return np.concatenate(parts)


def _tree_select(pred, new, old):
    """Per-leaf ``where(pred, new, old)`` over matching pytrees — the
    megastep's tail mask (a padded scan step computes ``new`` but must
    leave the carried state byte-identical to ``old``)."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), new, old)


def _put_global(host, sharding) -> jax.Array:
    """Host array -> global device array under ``sharding``, WITHOUT any
    cross-process collective (jax.device_put to a multi-process sharding
    runs an assert-equal allgather, which would race other threads'
    collectives; make_array_from_callback materializes only this
    process's addressable shards). Single-process it is equivalent."""
    if sharding is None:
        return jnp.asarray(host)
    host = np.asarray(host)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def _concat_dense_host(batch: SlotBatch) -> np.ndarray:
    if batch.dense:
        return np.concatenate([batch.dense[k] for k in sorted(batch.dense)],
                              axis=-1)
    return np.zeros((batch.labels.shape[0], 0), np.float32)
