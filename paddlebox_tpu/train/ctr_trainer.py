"""Pass-driven CTR trainer: the BoxPSTrainer/BoxPSWorker equivalent.

Role of the reference hot loop (``boxps_worker.cc:666-724`` TrainFiles):
per minibatch — pack batch (``BuildSlotBatchGPU``), pull sparse
(``PullSparse``), run fwd/bwd ops, push sparse grads (``PushSparseGrad``),
sync dense (``SyncParam``), collect AUC (``AddAucMonitor``) — plus the
``train_from_dataset`` pass loop around it.

TPU-first: the whole per-batch sequence is ONE jitted shard_map program —
pull (all slots fused into one all-to-all), model fwd/bwd, exact global
logloss, dense psum + optax update, sparse push with fused optimizer, and
AUC histogram accumulation — so XLA overlaps compute with the pull/push
collectives and there is no per-op dispatch. Device threads, streams, and
the NCCL ring of the reference collapse into the compiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddlebox_tpu.core import flags, log, timers
from paddlebox_tpu.data.dataset import Dataset
from paddlebox_tpu.data.slots import DataFeedConfig, SlotBatch
from paddlebox_tpu.embedding import (PassEngine, TableConfig,
                                     make_sparse_optimizer)
from paddlebox_tpu.embedding.lookup import pull_local, push_local
from paddlebox_tpu.metrics import (AucState, auc_accumulate, auc_compute,
                                   auc_state_init)


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    dense_learning_rate: float = 1e-3
    dense_optimizer: str = "adam"
    auc_num_buckets: int = 1 << 16
    check_nan_inf: bool = False


class CTRTrainer:
    """Owns PassEngine + dense params + the fused train step.

    Usage (mirrors the BoxPS day/pass loop, SURVEY.md §3.1):

        trainer = CTRTrainer(model, feed_cfg, table_cfg, mesh=mesh)
        trainer.init(seed=0)
        for pass_files in day:
            dataset.set_filelist(pass_files); dataset.load_into_memory()
            stats = trainer.train_pass(dataset)
        trainer.engine.store.save_base(path)
    """

    def __init__(self, model, feed_config: DataFeedConfig,
                 table_config: TableConfig, *,
                 mesh: Optional[Mesh] = None, axis: str = "dp",
                 config: TrainerConfig = TrainerConfig(),
                 store=None):
        self.model = model
        self.feed_config = feed_config
        self.config = config
        self.mesh = mesh
        self.axis = axis
        self.ndev = int(mesh.shape[axis]) if mesh is not None else 1
        if feed_config.batch_size % self.ndev:
            raise ValueError(
                f"batch_size {feed_config.batch_size} must be divisible by "
                f"the {axis} axis size {self.ndev}")
        # store: optional FeatureStore-shaped backing tier — a
        # TieredFeatureStore (RAM+SSD) or a distributed.ps.PSBackedStore
        # (remote CPU PS, the BuildPull flow); default in-RAM store.
        self.engine = PassEngine(table_config, store, mesh=mesh,
                                 table_axis=axis)
        self.sparse_opt = make_sparse_optimizer(table_config)
        self.params: Any = None
        self.opt_state: Any = None
        self.auc_state: Optional[AucState] = None
        self.timers = timers.TimerGroup()
        self._step_fn = None
        self._slot_names = [s.name for s in feed_config.sparse_slots]
        # Sharded capacities: always divisible by ndev (matches
        # SlotBatch.pack_sharded / Dataset.batches_sharded shapes).
        self._slot_caps = {
            s.name: feed_config.sparse_capacity(s, num_shards=self.ndev)
            for s in feed_config.sparse_slots}
        if self.config.dense_optimizer == "adam":
            self._optax = optax.adam(self.config.dense_learning_rate)
        elif self.config.dense_optimizer == "sgd":
            self._optax = optax.sgd(self.config.dense_learning_rate)
        else:
            raise ValueError(self.config.dense_optimizer)

    # -- init -------------------------------------------------------------

    def init(self, seed: int = 0) -> None:
        rng = jax.random.PRNGKey(seed)
        self.params = self.model.init(rng)
        self.opt_state = self._optax.init(self.params)
        self.auc_state = auc_state_init(self.config.auc_num_buckets)
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            self.params = jax.device_put(self.params, rep)
            self.opt_state = jax.device_put(self.opt_state, rep)
            self.auc_state = jax.device_put(self.auc_state, rep)

    # -- the fused step ----------------------------------------------------

    def _build_step(self):
        model = self.model
        axis = self.axis
        ndev = self.ndev
        names = self._slot_names
        caps = self._slot_caps
        caps_local = {n: caps[n] // ndev for n in names}
        bs_local = self.feed_config.batch_size // ndev
        optimizer = self._optax
        sparse_opt = self.sparse_opt
        has_dense = bool(self.feed_config.dense_slots)

        def body(table, params, opt_state, auc, rows, segments, labels,
                 valid, dense_feats):
            # rows: [sum caps_local] — all slots' ids fused into ONE pull
            # (one all_to_all pair instead of per-slot collectives).
            pulled = pull_local(table, rows, axis=axis)

            offs = np.cumsum([0] + [caps_local[n] for n in names])
            sl = {n: slice(offs[i], offs[i + 1])
                  for i, n in enumerate(names)}
            labels1 = labels[:, 0]
            validf = valid.astype(jnp.float32)

            def loss_fn(params, emb_all, w_all):
                emb = {n: emb_all[sl[n]] for n in names}
                w = {n: w_all[sl[n]] for n in names}
                kwargs = dict(batch_size=bs_local,
                              dense_feats=dense_feats if has_dense else None)
                if hasattr(model, "use_cvm"):  # Wide&Deep takes show/click
                    show = {n: pulled["show"][sl[n]] for n in names}
                    click = {n: pulled["click"][sl[n]] for n in names}
                    logits = model.apply(params, emb, w, show, click,
                                         segments, **kwargs)
                else:
                    logits = model.apply(params, emb, w, segments, **kwargs)
                # Exact global logloss: local sum / global valid count.
                bce = optax.sigmoid_binary_cross_entropy(logits, labels1)
                total_valid = lax.psum(jnp.sum(validf), axis)
                loss = jnp.sum(bce * validf) / jnp.maximum(total_valid, 1.0)
                return loss, logits

            grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1, 2),
                                         has_aux=True)
            (loss, logits), (g_params, g_emb, g_w) = grad_fn(
                params, pulled["emb"], pulled["w"])

            # Dense sync: grads already carry the global 1/N via the global
            # denominator — psum completes the cross-replica reduction
            # (role of SyncParam / c_allreduce_sum).
            g_params = lax.psum(g_params, axis)
            updates, opt_state = optimizer.update(g_params, opt_state, params)
            params = optax.apply_updates(params, updates)

            # Sparse push: show=1 per occurrence, click=its row's label
            # (role of feature show/click stats in PushSparseGrad).
            seg_all = jnp.concatenate([segments[n] for n in names])
            occ_valid = (seg_all < bs_local).astype(jnp.float32)
            clicks = jnp.where(seg_all < bs_local,
                               labels1[jnp.minimum(seg_all, bs_local - 1)],
                               0.0) * occ_valid
            table = push_local(table, rows, g_emb, g_w, occ_valid, clicks,
                               axis=axis, opt=sparse_opt)

            probs = jax.nn.sigmoid(logits)
            auc = auc_accumulate(auc, probs, labels1, valid, axis=axis)
            loss_global = lax.psum(loss, axis)
            return table, params, opt_state, auc, loss_global

        if self.mesh is not None:
            body_sm = jax.shard_map(
                body, mesh=self.mesh,
                in_specs=(P(axis), P(), P(), P(), P(axis), P(axis), P(axis),
                          P(axis), P(axis)),
                out_specs=(P(axis), P(), P(), P(), P()),
                check_vma=False)
        else:
            raise RuntimeError("CTRTrainer requires a mesh (1-device is a "
                               "1-axis mesh)")
        return jax.jit(body_sm, donate_argnums=(0, 1, 2, 3))

    # -- pass loop ---------------------------------------------------------

    def train_pass(self, dataset: Dataset, *, feed_keys: bool = True
                   ) -> Dict[str, float]:
        """Train one pass over the dataset (role of train_from_dataset +
        begin_pass/end_pass, SURVEY.md §3.1)."""
        if self.params is None:
            raise RuntimeError("call init() first")
        if self._step_fn is None:
            self._step_fn = self._build_step()
        eng = self.engine
        if feed_keys:
            with self.timers.scope("feed_pass"):
                eng.feed_pass(dataset.pass_keys())
        table = eng.begin_pass()
        params, opt_state = self.params, self.opt_state
        auc = self.auc_state
        bs = self.feed_config.batch_size
        losses: List[float] = []
        nsteps = 0
        for batch in dataset.batches_sharded(self.ndev):
            with self.timers.scope("host_map"):
                all_ids = np.concatenate(
                    [batch.ids[n] for n in self._slot_names])
                rows = eng.lookup_rows(all_ids)
                # Interleave per-device: [dev, slot, cap_local] flatten.
                rows = _interleave_slots(rows, self._slot_names,
                                         self._slot_caps, self.ndev)
                segs = {n: jnp.asarray(batch.segments[n])
                        for n in self._slot_names}
                dense = _concat_dense(batch)
            with self.timers.scope("device_step"):
                table, params, opt_state, auc, loss = self._step_fn(
                    table, params, opt_state, auc, jnp.asarray(rows), segs,
                    jnp.asarray(batch.labels), jnp.asarray(batch.valid),
                    dense)
            nsteps += 1
            if self.config.check_nan_inf or flags.flag("check_nan_inf"):
                lf = float(loss)
                if not np.isfinite(lf):
                    raise FloatingPointError(
                        f"NaN/Inf loss at step {nsteps}")
            losses.append(loss)
        eng.update_table(table)
        self.params, self.opt_state, self.auc_state = params, opt_state, auc
        with self.timers.scope("end_pass"):
            eng.end_pass()
        stats = auc_compute(self.auc_state)
        stats["loss"] = float(jnp.mean(jnp.stack(losses))) if losses else float("nan")
        stats["steps"] = nsteps
        log.vlog(0, "pass done: steps=%d loss=%.5f auc=%.5f (%s)",
                 nsteps, stats["loss"], stats["auc"], self.timers.report())
        return stats

    def reset_metrics(self) -> None:
        self.auc_state = auc_state_init(self.config.auc_num_buckets)
        if self.mesh is not None:
            self.auc_state = jax.device_put(
                self.auc_state, NamedSharding(self.mesh, P()))


def _interleave_slots(rows_concat: np.ndarray, names: List[str],
                      caps: Dict[str, int], ndev: int) -> np.ndarray:
    """Reorder [slotA(all devs), slotB(all devs), ...] into per-device
    groups [dev0: slotA,slotB..., dev1: ...] so sharding the flat array
    over dp gives each device its own slots' local ids contiguously."""
    parts = []
    off = 0
    per_slot = {}
    for n in names:
        per_slot[n] = rows_concat[off:off + caps[n]].reshape(ndev, -1)
        off += caps[n]
    for d in range(ndev):
        for n in names:
            parts.append(per_slot[n][d])
    return np.concatenate(parts)


def _concat_dense(batch: SlotBatch):
    if batch.dense:
        return jnp.asarray(
            np.concatenate([batch.dense[k] for k in sorted(batch.dense)],
                           axis=-1))
    return jnp.zeros((batch.labels.shape[0], 0), jnp.float32)
