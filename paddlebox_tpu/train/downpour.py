"""Downpour-style async CPU-PS training: pull/push per batch over PSClient.

Role of the reference's CPU async-PS path (``DistMultiTrainer`` +
``DownpourWorker``, ``trainer.h:141``, ``device_worker.h:302``): each
worker pulls the batch's sparse values from the parameter server, runs
fwd/bwd locally, pushes sparse+dense gradients back asynchronously, while
a background ``PullDenseWorker`` (``device_worker.h:87``,
``pull_dense_worker.cc``) keeps a fresh copy of the dense params.

TPU-first: the device step is one jitted fn over STATIC shapes (ids are
pulled host-side into a padded [cap, dim] buffer); PS traffic is the
host-side :class:`~paddlebox_tpu.distributed.ps.PSClient`. This is the
``strategy.a_sync`` execution mode — the high-throughput BoxPS-style path
keeps tables in device HBM instead (:mod:`paddlebox_tpu.train.
ctr_trainer`).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.core import log
from paddlebox_tpu.distributed.ps import PSClient


class PullDenseWorker:
    """Background dense-param refresher (role of PullDenseWorker,
    device_worker.h:87): polls the PS and publishes versioned snapshots."""

    def __init__(self, client: PSClient, names, interval: float = 0.05):
        self.client = client
        self.names = list(names)
        self.interval = interval
        self._latest: Dict[str, np.ndarray] = {}
        self._version = 0
        self._lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def _pull_once(self) -> None:
        vals = {n: self.client.pull_dense(n) for n in self.names}
        with self._lock:
            self._latest = vals
            self._version += 1

    def start(self) -> None:
        self._pull_once()
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while self._running:
            try:
                self._pull_once()
            except Exception as e:
                log.warning("pull_dense failed: %s", e)
            time.sleep(self.interval)

    def latest(self) -> Dict[str, np.ndarray]:
        with self._lock:
            return dict(self._latest)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(5.0)


class DownpourTrainer:
    """Async-PS sparse trainer.

    ``loss_fn(dense_params, emb [cap, D], w [cap], batch) -> scalar`` —
    emb/w are the pulled values for ``batch["ids"]`` (padded to the static
    capacity with zeros; ``batch["ids"]`` is a [cap] uint64 array where 0
    marks padding). Gradients w.r.t. emb/w are pushed to the PS sparse
    table; gradients w.r.t. dense params are pushed to PS dense tables
    (server-side apply), with fresh dense params pulled in the background.
    """

    def __init__(self, client: PSClient, table: str,
                 loss_fn: Callable[..., jax.Array],
                 dense_init: Dict[str, np.ndarray], *,
                 pull_interval: float = 0.05,
                 dense_lr: float = 0.05):
        self.client = client
        self.table = table
        self.loss_fn = loss_fn
        # dense_lr: server-side SGD rate for the raw grads this trainer
        # pushes. The async pull means grads are computed at stale params
        # (several steps' worth at full speed) — a large rate here turns
        # that staleness into oscillation/divergence.
        for name, v in dense_init.items():
            self.client.set_dense(name, v, lr=dense_lr)
        self.pull_worker = PullDenseWorker(client, dense_init.keys(),
                                           pull_interval)
        self.pull_worker.start()
        self._grad_fn = None

    def _build(self):
        if self._grad_fn is None:
            def val_grad(dense, emb, w, batch):
                return self.loss_fn(dense, emb, w, batch)
            self._grad_fn = jax.jit(
                jax.value_and_grad(val_grad, argnums=(0, 1, 2)))
        return self._grad_fn

    def train_step(self, batch: Dict[str, Any]) -> float:
        """One async step: pull sparse → device fwd/bwd → push grads."""
        ids = np.asarray(batch["ids"], np.uint64)
        pad = ids == 0
        real = ~pad
        if not real.any():
            raise ValueError("batch has no real (nonzero) ids")
        # Pull only real ids: the server persists an initialized row for
        # every pulled key, so pulling padding zeros would CREATE a
        # feasign-0 row in the table.
        pulled = self.client.pull_sparse(self.table, ids[real])
        dense = {k: jnp.asarray(v)
                 for k, v in self.pull_worker.latest().items()}
        emb_np = np.zeros((ids.shape[0], pulled["emb"].shape[1]),
                          np.float32)
        w_np = np.zeros((ids.shape[0],), np.float32)
        emb_np[real] = pulled["emb"]
        w_np[real] = pulled["w"]
        emb = jnp.asarray(emb_np)
        w = jnp.asarray(w_np)
        loss, (g_dense, g_emb, g_w) = self._build()(dense, emb, w, batch)
        # Padding rows must not train feasign 0.
        if real.any():
            self.client.push_sparse(
                self.table, ids[real],
                emb_grad=np.asarray(g_emb)[real],
                w_grad=np.asarray(g_w)[real],
                show=np.ones(int(real.sum()), np.float32),
                click=np.asarray(batch.get(
                    "click", np.zeros(ids.shape[0], np.float32)))[real])
        for name, g in g_dense.items():
            self.client.push_dense(name, np.asarray(g))
        return float(loss)

    def fit(self, batches: Iterable[Dict[str, Any]], *,
            log_every: int = 0, window: int = 10) -> Dict[str, float]:
        """Run all batches; report mean loss over the first/last ``window``
        steps (single-batch losses are too noisy to compare under async
        dense-pull timing). O(window) memory: production day loops run
        millions of steps. When total steps <= 2*window the two windows
        overlap and first/last converge toward each other — convergence
        checks need runs longer than 2*window."""
        import collections
        window = max(1, window)
        head: list = []
        tail: collections.deque = collections.deque(maxlen=window)
        n = 0
        for batch in batches:
            loss = self.train_step(batch)
            if len(head) < window:
                head.append(loss)
            tail.append(loss)
            n += 1
            if log_every and n % log_every == 0:
                log.vlog(0, "downpour step %d loss %.5f", n, loss)
        first = float(np.mean(head)) if head else float("nan")
        last = float(np.mean(tail)) if tail else float("nan")
        return {"steps": n, "loss_first": first, "loss_last": last}

    def stop(self) -> None:
        self.pull_worker.stop()
