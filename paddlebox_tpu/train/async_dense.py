"""Async CPU dense table: background host optimizer over a grad ring.

Role of ``BoxPSAsynDenseTable`` (``framework/boxps_worker.cc:43-341``): a
CPU-side dense parameter server inside the trainer process — workers
``PushDense`` gradients into a ring of buffers and ``PullDense`` the
freshest params each step (used at ``boxps_worker.cc:683-692``); update
threads run host Adam with hardcoded β=0.99/0.9999 (:259-268) plus a
special datanorm rule, decoupling dense updates from the device step so
k-step device sync can proceed without blocking.

TPU-first: the device path normally folds dense updates into the jitted
step (CTRTrainer); this table serves the same *decoupling* role for
host-resident dense state — e.g. very large embedding-adjacent dense
blocks or multi-process CTR where dense lives host-side between k-step
syncs. numpy Adam, one background thread, bounded ring with drop-oldest
(matching the reference's async semantics where a slow updater coalesces
gradients rather than stalling workers).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from paddlebox_tpu.core import log, monitor


class AsyncDenseTable:
    """Host params + background Adam thread fed by a bounded grad ring."""

    def __init__(self, params: Any, *, learning_rate: float = 1e-3,
                 beta1: float = 0.99, beta2: float = 0.9999,
                 eps: float = 1e-8, ring_capacity: int = 8):
        self._leaves, self._treedef = jax.tree_util.tree_flatten(params)
        self._leaves = [np.asarray(x, np.float32).copy()
                        for x in self._leaves]
        self._m = [np.zeros_like(x) for x in self._leaves]
        self._v = [np.zeros_like(x) for x in self._leaves]
        self.lr = learning_rate
        self.b1, self.b2, self.eps = beta1, beta2, eps
        self._t = 0
        self._ring: "queue.Queue" = queue.Queue(ring_capacity)
        self._params_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._running = True
        self._thread = threading.Thread(target=self._update_loop,
                                        daemon=True)
        self._thread.start()

    # -- worker API (role of PullDense/PushDense) --------------------------

    def pull_dense(self) -> Any:
        """Snapshot of the freshest params (boxps_worker.cc:305)."""
        with self._params_lock:
            leaves = [x.copy() for x in self._leaves]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def push_dense(self, grads: Any) -> None:
        """Enqueue a gradient pytree; drops the oldest entry when the ring
        is full (async coalescing, not backpressure — a stalled updater
        must not stall the device loop)."""
        self._check_error()
        g, treedef = jax.tree_util.tree_flatten(grads)
        if treedef != self._treedef:
            raise ValueError(
                f"grad tree structure {treedef} != param tree "
                f"{self._treedef} — same leaf count with a different "
                "structure would update the wrong parameters")
        g = [np.asarray(x, np.float32) for x in g]
        for gi, pi in zip(g, self._leaves):
            if gi.shape != pi.shape:
                raise ValueError(
                    f"grad shape {gi.shape} != param shape {pi.shape}")
        while True:
            try:
                self._ring.put_nowait(g)
                return
            except queue.Full:
                try:
                    self._ring.get_nowait()
                    self._ring.task_done()
                    monitor.add("async_dense/dropped", 1)
                except queue.Empty:
                    continue

    # -- update thread (role of AsyncUpdate/ThreadUpdate) ------------------

    def _update_loop(self) -> None:
        while self._running:
            try:
                g = self._ring.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._apply(g)
            except BaseException as e:
                # A dead updater must not be silent: record and surface on
                # the next worker-side call instead of freezing params.
                with self._params_lock:
                    self._error = e
                log.error("async dense update failed: %s", e)
                self._ring.task_done()
                return
            self._ring.task_done()

    def _apply(self, g) -> None:
        with self._params_lock:
            self._t += 1
            b1t = 1.0 - self.b1 ** self._t
            b2t = 1.0 - self.b2 ** self._t
            for i, gi in enumerate(g):
                self._m[i] = self.b1 * self._m[i] + (1 - self.b1) * gi
                self._v[i] = self.b2 * self._v[i] + (1 - self.b2) * gi * gi
                self._leaves[i] -= self.lr * (self._m[i] / b1t) / (
                    np.sqrt(self._v[i] / b2t) + self.eps)

    # -- lifecycle ---------------------------------------------------------

    def _check_error(self) -> None:
        with self._params_lock:
            err = self._error
        if err is not None:
            raise RuntimeError("async dense updater died") from err

    def flush(self, timeout: float = 10.0) -> None:
        """Drain pending grads INCLUDING the in-flight one the updater has
        already dequeued (unfinished_tasks counts until task_done), so a
        post-flush pull/checkpoint sees every pushed gradient applied."""
        import time
        deadline = time.monotonic() + timeout
        while self._ring.unfinished_tasks:
            self._check_error()
            if time.monotonic() > deadline:
                raise TimeoutError("async dense flush timed out")
            time.sleep(0.005)
        self._check_error()

    def stop(self) -> None:
        with self._params_lock:
            died = self._error is not None
        if not died:
            self.flush()
        self._running = False
        self._thread.join(5.0)

    @property
    def steps_applied(self) -> int:
        with self._params_lock:
            return self._t
