"""Minimal functional NN layer library for the CTR dense towers.

Role of the dense-layer subset of ``python/paddle/fluid/layers/nn.py`` /
``paddle.nn`` used by CTR models. Deliberately functional (init fns return
param pytrees; apply fns are pure) so train steps control donation and
sharding explicitly; the transformer/vision model zoo uses flax on top.
"""

from paddlebox_tpu.nn.layers import (
    dense_init,
    dense_apply,
    mlp_init,
    mlp_apply,
)

__all__ = ["dense_init", "dense_apply", "mlp_init", "mlp_apply"]
