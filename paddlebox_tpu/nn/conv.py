"""Convolution / normalization layers for the vision stack (pure functional)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def conv2d_init(rng: jax.Array, in_ch: int, out_ch: int, kernel: int,
                dtype=jnp.float32) -> Dict[str, jax.Array]:
    fan_in = in_ch * kernel * kernel
    w = jax.random.normal(rng, (kernel, kernel, in_ch, out_ch), dtype)
    return {"w": w * (2.0 / fan_in) ** 0.5}


def conv2d_apply(params: Dict, x: jax.Array, *, stride: int = 1,
                 padding: str = "SAME") -> jax.Array:
    """x [B, H, W, C] (NHWC keeps the channel dim on the TPU lane axis).

    Inputs are cast to the weight dtype (lax.conv requires matching
    dtypes — under a bf16 policy the weights set the compute dtype).
    Output stays in the compute dtype, symmetric for autodiff: a mixed
    bf16-in/f32-out conv has no valid transpose (the cotangent dtype
    would mismatch the input), so accumulation precision is left to the
    MXU's internal f32 accumulate rather than preferred_element_type."""
    return lax.conv_general_dilated(
        x.astype(params["w"].dtype), params["w"],
        window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def batchnorm_init(ch: int, dtype=jnp.float32) -> Dict[str, jax.Array]:
    return {"g": jnp.ones((ch,), dtype), "b": jnp.zeros((ch,), dtype),
            "mean": jnp.zeros((ch,), dtype), "var": jnp.ones((ch,), dtype)}


def batchnorm_apply(params: Dict, x: jax.Array, *, train: bool,
                    momentum: float = 0.9, eps: float = 1e-5,
                    axis_name: str | None = None
                    ) -> Tuple[jax.Array, Dict]:
    """Returns (y, updated_params). Under data parallelism pass axis_name
    to compute sync batch stats (role of sync_batch_norm)."""
    xf = x.astype(jnp.float32)  # stats in f32 even under a bf16 policy
    if train:
        mu = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.mean(xf * xf, axis=(0, 1, 2)) - mu * mu
        if axis_name is not None:
            mu = lax.pmean(mu, axis_name)
            var = lax.pmean(var, axis_name)
        new = dict(params)
        new["mean"] = (momentum * params["mean"].astype(jnp.float32)
                       + (1 - momentum) * mu)
        new["var"] = (momentum * params["var"].astype(jnp.float32)
                      + (1 - momentum) * var)
    else:
        mu, var = (params["mean"].astype(jnp.float32),
                   params["var"].astype(jnp.float32))
        new = params
    y = ((xf - mu) * lax.rsqrt(var + eps) * params["g"].astype(jnp.float32)
         + params["b"].astype(jnp.float32))
    return y.astype(x.dtype), new
