"""Convolution / normalization layers for the vision stack (pure functional)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def conv2d_init(rng: jax.Array, in_ch: int, out_ch: int, kernel: int,
                dtype=jnp.float32) -> Dict[str, jax.Array]:
    fan_in = in_ch * kernel * kernel
    w = jax.random.normal(rng, (kernel, kernel, in_ch, out_ch), dtype)
    return {"w": w * (2.0 / fan_in) ** 0.5}


def conv2d_apply(params: Dict, x: jax.Array, *, stride: int = 1,
                 padding: str = "SAME") -> jax.Array:
    """x [B, H, W, C] (NHWC keeps the channel dim on the TPU lane axis)."""
    return lax.conv_general_dilated(
        x, params["w"], window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)


def batchnorm_init(ch: int, dtype=jnp.float32) -> Dict[str, jax.Array]:
    return {"g": jnp.ones((ch,), dtype), "b": jnp.zeros((ch,), dtype),
            "mean": jnp.zeros((ch,), dtype), "var": jnp.ones((ch,), dtype)}


def batchnorm_apply(params: Dict, x: jax.Array, *, train: bool,
                    momentum: float = 0.9, eps: float = 1e-5,
                    axis_name: str | None = None
                    ) -> Tuple[jax.Array, Dict]:
    """Returns (y, updated_params). Under data parallelism pass axis_name
    to compute sync batch stats (role of sync_batch_norm)."""
    if train:
        mu = jnp.mean(x, axis=(0, 1, 2))
        var = jnp.mean(x * x, axis=(0, 1, 2)) - mu * mu
        if axis_name is not None:
            mu = lax.pmean(mu, axis_name)
            var = lax.pmean(var, axis_name)
        new = dict(params)
        new["mean"] = momentum * params["mean"] + (1 - momentum) * mu
        new["var"] = momentum * params["var"] + (1 - momentum) * var
    else:
        mu, var = params["mean"], params["var"]
        new = params
    y = (x - mu) * lax.rsqrt(var + eps) * params["g"] + params["b"]
    return y, new
