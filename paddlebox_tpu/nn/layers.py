"""Pure-functional dense layers (param pytrees + apply fns)."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp


def dense_init(rng: jax.Array, in_dim: int, out_dim: int,
               dtype=jnp.float32) -> Dict[str, jax.Array]:
    """Xavier-uniform weight + zero bias."""
    bound = (6.0 / (in_dim + out_dim)) ** 0.5
    w = jax.random.uniform(rng, (in_dim, out_dim), dtype, -bound, bound)
    return {"w": w, "b": jnp.zeros((out_dim,), dtype)}


def dense_apply(params: Dict[str, jax.Array], x: jax.Array) -> jax.Array:
    return jnp.dot(x, params["w"],
                   preferred_element_type=jnp.float32) + params["b"]


def mlp_init(rng: jax.Array, in_dim: int, hidden: Sequence[int],
             dtype=jnp.float32) -> List[Dict[str, jax.Array]]:
    layers = []
    dims = [in_dim] + list(hidden)
    for i in range(len(hidden)):
        rng, sub = jax.random.split(rng)
        layers.append(dense_init(sub, dims[i], dims[i + 1], dtype))
    return layers


def mlp_apply(layers: List[Dict[str, jax.Array]], x: jax.Array,
              activation: Callable[[jax.Array], jax.Array] = jax.nn.relu,
              final_activation: bool = False) -> jax.Array:
    for i, layer in enumerate(layers):
        x = dense_apply(layer, x)
        if i + 1 < len(layers) or final_activation:
            x = activation(x)
    return x
