"""StreamRunner: the sub-day sibling of the DayRunner pass loop.

Role of the streaming scenario production CTR actually runs (the
reference's day/pass loop driven at minute granularity): events land in
a log directory, become an incremental pass within
``FLAGS_stream_pass_window_s``, train through the UNCHANGED
``DayRunner.train_pass`` machinery — self-heal retry, rollback,
watchdog, deterministic replay — and publish a per-pass delta through
``checkpoint/protocol.py``'s donefile, which the PR-9/PR-11 serving
publishers already tail: a running PredictServer or fleet replica picks
up minute-fresh models with ZERO new serving code.

Freshness is a first-class metric: per pass, the age of its OLDEST
event (file mtime) at the moment the delta is acked servable lands in
the ``stream/event_to_servable_ms`` registry quantile digest — the
worst-case event→servable latency an SLO would bind. ``ack_fn`` lets
the caller define "servable" (e.g. block until a replica's publisher
applied the delta); the default acks at donefile publication, the
instant the delta became visible to every tailing publisher.

Day rollover: when the source carves a pass for a NEW day label, the
previous day closes through ``DayRunner.day_end`` — lifecycle shrink
(show/click decay, unseen-days TTL, min-show eviction), base dump,
donefile publish — so the store stays bounded under infinite traffic.

Replay purity: the runner's clock is injected (``clock=``) and only
read OUTSIDE the replayed training closure (the freshness ack is
publication metadata, never training state); graftlint's replay-purity
pass walks ``StreamRunner.*`` as a root set to keep it that way.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

from paddlebox_tpu.core import (faults, flags, incident, log, monitor,
                                quality, trace)
from paddlebox_tpu.stream.source import (PassManifest, StreamCursor,
                                         StreamSource)
from paddlebox_tpu.train.day_runner import DayRunner


class StreamRunner(DayRunner):
    """Drive a CTRTrainer from a growing event log at sub-day freshness."""

    def __init__(self, trainer, feed_config, output_root: str, *,
                 log_dir: str,
                 day_of: Optional[Callable[[str], str]] = None,
                 clock: Callable[[], float] = time.time,
                 ack_fn: Optional[Callable[[str, int], Optional[float]]]
                 = None,
                 **day_kwargs):
        # The streaming pass loop addresses data by manifest, not by
        # <data_root>/<day>/<split> — pipelining is per carved pass.
        day_kwargs.setdefault("pipeline_passes", False)
        super().__init__(trainer, feed_config, output_root, **day_kwargs)
        self._clock = clock
        self._ack_fn = ack_fn
        self.cursor = StreamCursor(
            os.path.join(output_root, "stream_cursor.json"))
        self.source = StreamSource(log_dir, day_of=day_of, clock=clock,
                                   consumed=self.cursor.consumed_files())
        self._current_day: Optional[str] = None
        # (day, pass_id) pairs the donefile already covers (pass_id 0 =
        # the day's base, i.e. day_end ran).
        self._published = {(r.day, r.pass_id)
                           for r in self.ckpt.records()}

    # -- resume ------------------------------------------------------------

    def resume(self) -> Optional[Dict[str, object]]:
        """Restart path: load the published model (DayRunner.recover),
        then replay every cursor manifest the donefile does NOT cover —
        the carved-but-unpublished tail a crash left behind. File→pass
        assignment comes from the durable cursor, so the replay trains
        exactly the events the killed process would have: none lost,
        none twice."""
        # Arm fault injection before any cursor/replay work — the
        # stream/* faultpoints fire before the first train_pass would
        # arm it (same reasoning as train_day's early init).
        faults.init_from_flags()
        point = self.recover()
        self._published = {(r.day, r.pass_id)
                           for r in self.ckpt.records()}
        replayed = 0
        for m in self.cursor.manifests:
            replayed += self._run_manifest(m)
        if replayed:
            log.vlog(0, "stream: resumed %d unpublished pass(es) from "
                     "the cursor", replayed)
        return point

    # -- the poll loop -----------------------------------------------------

    def poll_once(self, *, flush: bool = False) -> int:
        """One tail step: scan the log dir, durably carve ready passes,
        train each, publish each delta. Returns passes trained. Tests,
        bench and the crash drill call this directly; ``run`` wraps it
        in the idle-sleep loop."""
        faults.init_from_flags()
        faults.faultpoint("stream/source_poll")
        with trace.span("stream/poll"):
            self.source.poll()
            protos = self.source.carve(flush=flush)
        manifests = [self.cursor.append(day, files, events, oldest)
                     for day, files, events, oldest in protos]
        trained = 0
        for m in manifests:
            trained += self._run_manifest(m)
        return trained

    def run(self, *, duration_s: float, flush_at_end: bool = True) -> int:
        """Tail the log for ``duration_s`` wall seconds (the example /
        soak entry point), sleeping ``FLAGS_stream_poll_s`` between
        empty polls. Returns total passes trained."""
        deadline = self._clock() + float(duration_s)
        total = 0
        while self._clock() < deadline:
            n = self.poll_once()
            total += n
            if n == 0:
                time.sleep(max(float(flags.flag("stream_poll_s")), 0.01))
        if flush_at_end:
            total += self.poll_once(flush=True)
        return total

    def end_day(self) -> int:
        """Explicitly close the current open day (end of a replayed log
        / operator-driven rollover): lifecycle shrink + base + publish
        via the shared DayRunner.day_end sequence."""
        if self._current_day is None:
            return 0
        day, self._current_day = self._current_day, None
        evicted = self.day_end(day)
        self._published.add((day, 0))
        return evicted

    # -- one manifest ------------------------------------------------------

    def _run_manifest(self, m: PassManifest) -> int:
        """Train one carved pass (idempotent: published manifests are
        skipped — the resume/crash-drill contract). Handles the day
        rollover BEFORE the first pass of a new day trains."""
        if self._current_day is not None and m.day != self._current_day:
            if (self._current_day, 0) not in self._published:
                self.day_end(self._current_day)
                self._published.add((self._current_day, 0))
        self._current_day = m.day
        if (m.day, m.pass_id) in self._published:
            return 0
        # One root trace context per carved pass (a no-op when tracing
        # is off): every training-write RPC of this pass — trainer push
        # → shard primary → synchronous backup forward — carries ONE
        # trace id, so a merged fleet trace shows the whole write path
        # of one incremental pass.
        # The carved manifest is the richest pass identity the quality
        # plane can get (event/file counts ride the quality_report) —
        # stamped BEFORE train_pass so the per-pass drift detection
        # over carved passes names the exact sub-day pass that drifted.
        quality.GLOBAL.set_pass_context(m.day, m.pass_id,
                                        events=int(m.events),
                                        files=len(m.files))
        # Same identity on the incident recorder: a bundle captured
        # mid-pass names the exact sub-day pass that was training.
        incident.set_context(day=m.day, pass_id=m.pass_id)
        with trace.use_context(trace.wire_context()), \
                trace.span("stream/pass", day=m.day, pass_id=m.pass_id,
                           files=len(m.files), events=m.events):
            self.train_pass(m.day, m.pass_id, list(m.files))
        # Delta published (train_pass's donefile write) — the window
        # between publication and the freshness ack: a kill here must
        # resume WITHOUT retraining the pass (the donefile covers it).
        faults.faultpoint("stream/delta_publish")
        self._published.add((m.day, m.pass_id))
        ack_ts = None
        if self._ack_fn is not None:
            ack_ts = self._ack_fn(m.day, m.pass_id)
        if ack_ts is None:
            ack_ts = self._clock()
        lat_ms = max(0.0, (float(ack_ts) - m.oldest_ts) * 1e3)
        monitor.observe_quantile("stream/event_to_servable_ms", lat_ms)
        monitor.add("stream/passes", 1)
        monitor.add("stream/events", int(m.events))
        log.vlog(0, "stream: %s pass %d (%d events, %d files) servable "
                 "in %.0f ms", m.day, m.pass_id, m.events, len(m.files),
                 lat_ms)
        return 1

    # -- freshness surface -------------------------------------------------

    def freshness_quantiles(self) -> Optional[Dict[str, float]]:
        """p50/p90/p99/p999 of event→servable ms (None before the first
        pass) — what `bench.py online` records and perf_gate gates."""
        d = monitor.GLOBAL.quantile_digest("stream/event_to_servable_ms")
        return d.quantiles() if d is not None else None
