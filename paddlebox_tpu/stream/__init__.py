"""Streaming online-learning tier (ONLINE.md): minute-level
event→servable freshness on top of the existing day/pass engine.

- :mod:`paddlebox_tpu.stream.source` — bounded files-as-stream tailer
  with a durable consumed-offset cursor (kill -9 safe).
- :mod:`paddlebox_tpu.stream.runner` — :class:`StreamRunner`, the
  sub-day sibling of ``DayRunner.train_pass``: trains each carved
  incremental pass, publishes its delta through the donefile protocol
  the serving publishers already tail, and measures event→servable
  latency as a registry quantile digest.
"""

from paddlebox_tpu.stream.runner import StreamRunner
from paddlebox_tpu.stream.source import (PassManifest, StreamCursor,
                                         StreamSource)

__all__ = ["PassManifest", "StreamCursor", "StreamRunner", "StreamSource"]
