"""Streaming ingest source: a growing log directory as a pass stream.

Production CTR events arrive continuously; the engine trains in passes.
This module closes the gap without touching the ingest stack:
:class:`StreamSource` tails a log directory (files-as-stream — the
universal hand-off from any collector: each log segment appears
ATOMICALLY, write-tmp-then-rename, and file names sort in arrival
order), carves newly arrived files into sub-day incremental passes by
event count (``FLAGS_stream_pass_events``) / time window
(``FLAGS_stream_pass_window_s``) / day change, and hands each pass to
the EXISTING ``Dataset`` loaders as a plain file list — the PR-8
mp-ingest workers, shm hand-off and sorted-run key collection run
unchanged.

Durability: :class:`StreamCursor` is the consumed-offset cursor — an
append-only list of pass manifests (day, pass_id, files, event count,
oldest event mtime) rewritten atomically (tmp + fsync + rename, the
donefile discipline) BEFORE a pass trains. The file→pass assignment is
therefore decided exactly once and survives kill -9: a crash before the
commit re-carves the same pending files (nothing trained, nothing
lost); a crash after it replays the identical manifest; a crash after
the donefile publish skips it (the runner cross-checks the donefile).
No event is ever lost or trained twice — tests/test_stream_drill.py
proves it by dying at every window.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.core import faults, flags, log, monitor
from paddlebox_tpu.data.dataset import BYTE_RANGE_SEP, split_byte_range


@dataclasses.dataclass(frozen=True)
class PassManifest:
    """One carved incremental pass: the durable unit of stream consumption."""

    day: str
    pass_id: int
    files: Tuple[str, ...]
    events: int
    oldest_ts: float     # min mtime across the pass's files (epoch s)

    def to_dict(self) -> Dict[str, object]:
        return {"day": self.day, "pass_id": self.pass_id,
                "files": list(self.files), "events": self.events,
                "oldest_ts": self.oldest_ts}

    @staticmethod
    def from_dict(d: Dict[str, object]) -> "PassManifest":
        return PassManifest(day=str(d["day"]), pass_id=int(d["pass_id"]),
                            files=tuple(d["files"]),
                            events=int(d["events"]),
                            oldest_ts=float(d["oldest_ts"]))


class StreamCursor:
    """Durable file→pass assignment (the stream's consumed offset).

    One JSON file holding the ordered manifest list. ``append`` assigns
    the next per-day pass id and commits atomically; on restart the
    cursor is the single source of truth for which files belong to
    which pass — the donefile then says which of those passes already
    published."""

    def __init__(self, path: str):
        self.path = path
        self.manifests: List[PassManifest] = []
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            self.manifests = [PassManifest.from_dict(m)
                              for m in data.get("manifests", [])]

    def consumed_files(self) -> set:
        return {f for m in self.manifests for f in m.files}

    def next_pass_id(self, day: str) -> int:
        ids = [m.pass_id for m in self.manifests if m.day == day]
        return (max(ids) + 1) if ids else 1

    def append(self, day: str, files: Sequence[str], events: int,
               oldest_ts: float) -> PassManifest:
        """Assign the pass id and commit the manifest durably BEFORE the
        pass trains. The fsync-before-rename means a visible cursor
        always implies a complete manifest list."""
        m = PassManifest(day=day, pass_id=self.next_pass_id(day),
                         files=tuple(files), events=int(events),
                         oldest_ts=float(oldest_ts))
        self.manifests.append(m)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1,
                       "manifests": [x.to_dict() for x in self.manifests]},
                      f)
            f.flush()
            os.fsync(f.fileno())
        # The crash window this drill-proves: manifest written, not yet
        # visible — restart re-carves the same files, trains them once.
        faults.faultpoint("stream/cursor_commit")
        os.replace(tmp, self.path)
        monitor.add("stream/cursor_commits", 1)
        return m


class StreamSource:
    """Bounded tailer over a growing log directory.

    Holds only file names, event counts and mtimes (never rows — the
    Dataset loaders read the bytes when the pass trains). ``day_of``
    maps a file path to its day label (default: one endless virtual
    day ``"stream"``); a day change always closes the open pass, so a
    pass never spans the day boundary the lifecycle shrink runs at.

    ``clock`` is injected (seconds, ``time.time`` semantics) so the
    replay path stays wall-clock-free for graftlint's replay-purity
    pass — file mtimes are event PROPERTIES, not clock reads.
    """

    def __init__(self, log_dir: str, *, pattern_suffix: str = "",
                 day_of: Optional[Callable[[str], str]] = None,
                 clock: Callable[[], float] = time.time,
                 consumed: Optional[set] = None):
        self.log_dir = log_dir
        self.pattern_suffix = pattern_suffix
        self._day_of = day_of or (lambda path: "stream")
        self._clock = clock
        # Whole files fully consumed (plain paths), and — tail mode
        # (FLAGS_stream_tail_bytes) — per-file consumed byte offsets
        # reconstructed from the cursor's "path@@start-end" range specs:
        # the durable mid-file resume point.
        self._consumed: set = set()
        self._offsets: Dict[str, int] = {}
        self.mark_consumed(consumed or ())
        # spec -> (events, mtime); counted once per registration, never
        # re-read. In tail mode a spec names a byte range of a file
        # still being appended; one pending (uncarved) range per file.
        self._meta: Dict[str, Tuple[int, float]] = {}
        self._tail_pending: Dict[str, str] = {}   # base path -> spec

    # -- scanning ----------------------------------------------------------

    @staticmethod
    def _count_events(path: str) -> int:
        """Non-empty lines = events (the parser's row unit)."""
        n = 0
        with open(path, "rb") as f:
            for line in f:
                if line.strip():
                    n += 1
        return n

    def day_of(self, spec: str) -> str:
        """Day label of a file-list entry (byte-range specs label by
        their base file)."""
        return self._day_of(split_byte_range(spec)[0])

    def mark_consumed(self, files: Sequence[str]) -> None:
        """Record already-consumed entries (cursor replay): plain paths
        are whole files; range specs advance the file's byte offset —
        the durable mid-file cut kill -9 resumes from."""
        for f in files:
            base, _start, end = split_byte_range(f)
            if end is None:
                self._consumed.add(f)
            else:
                self._offsets[base] = max(self._offsets.get(base, 0),
                                          end)

    def poll(self) -> int:
        """Scan the directory for newly arrived files (whole-segment
        mode: files must appear atomically, write-then-rename) or newly
        appended bytes (``FLAGS_stream_tail_bytes``: every file is an
        append stream, consumed up to its last complete newline).
        Returns how many new files/ranges were registered."""
        tail = bool(flags.flag("stream_tail_bytes"))
        try:
            names = sorted(os.listdir(self.log_dir))
        except FileNotFoundError:
            names = []
        new = 0
        for name in names:
            if self.pattern_suffix and not name.endswith(
                    self.pattern_suffix):
                continue
            path = os.path.join(self.log_dir, name)
            if path in self._consumed or not os.path.isfile(path):
                continue
            if tail:
                new += self._poll_tail(path)
                continue
            if path in self._meta:
                continue
            if path in self._offsets:
                # A byte-offset cursor consumed part of this file in a
                # previous (tail-mode) run: whole-segment mode cannot
                # re-consume it without duplicating events.
                log.warning("stream source: %s has a mid-file cursor at "
                            "byte %d but FLAGS_stream_tail_bytes is off "
                            "— skipping the file (re-enable tail mode "
                            "to drain it)", path, self._offsets[path])
                continue
            try:
                mtime = os.path.getmtime(path)
                events = self._count_events(path)
            except OSError as e:
                # Rotated away between listdir and stat: next poll.
                log.warning("stream source: %s vanished mid-poll (%s)",
                            path, e)
                continue
            self._meta[path] = (events, mtime)
            new += 1
            monitor.add("stream/files", 1)
        monitor.set_gauge("stream/pending_files", float(len(self._meta)))
        return new

    def _poll_tail(self, path: str) -> int:
        """Register one file's newly appended COMPLETE lines as a byte
        range ``path@@offset-cut`` (cut = last newline). One pending
        range per file; the next bytes register after it carves. A
        trailing unterminated line is never consumed — the writer owns
        it until its newline lands."""
        if path in self._tail_pending:
            return 0
        offset = self._offsets.get(path, 0)
        try:
            size = os.path.getsize(path)
            if size <= offset:
                return 0
            mtime = os.path.getmtime(path)
            events = 0
            cut = offset
            with open(path, "rb") as f:
                f.seek(offset)
                buf = f.read(size - offset)
            last_nl = buf.rfind(b"\n")
            if last_nl < 0:
                return 0
            cut = offset + last_nl + 1
            events = sum(1 for ln in buf[:last_nl + 1].split(b"\n")
                         if ln.strip())
        except OSError as e:
            log.warning("stream source: %s vanished mid-poll (%s)",
                        path, e)
            return 0
        if events == 0:
            return 0
        spec = f"{path}{BYTE_RANGE_SEP}{offset}-{cut}"
        self._meta[spec] = (events, mtime)
        self._tail_pending[path] = spec
        monitor.add("stream/files", 1)
        monitor.add("stream/tail_bytes", int(cut - offset))
        return 1

    def pending(self) -> List[str]:
        """Registered-but-uncarved files in carve order (name-sorted)."""
        return sorted(self._meta)

    # -- carving -----------------------------------------------------------

    def carve(self, *, flush: bool = False
              ) -> List[Tuple[str, List[str], int, float]]:
        """Group pending files into incremental proto-passes.

        A pass closes when (a) its event count reaches
        ``FLAGS_stream_pass_events`` (> 0), (b) the day label changes
        between consecutive files, or — for the TAIL group only —
        (c) its oldest event is ``FLAGS_stream_pass_window_s`` old
        (> 0), or (d) ``flush=True`` (end of stream / shutdown).
        Returns ``[(day, files, events, oldest_ts), ...]``; carved
        files leave the pending set (the caller commits them to the
        cursor before training)."""
        max_events = int(flags.flag("stream_pass_events"))
        window_s = float(flags.flag("stream_pass_window_s"))
        out: List[Tuple[str, List[str], int, float]] = []
        cur_files: List[str] = []
        cur_events = 0
        cur_oldest = float("inf")
        cur_day: Optional[str] = None

        def close() -> None:
            nonlocal cur_files, cur_events, cur_oldest, cur_day
            if cur_files:
                out.append((cur_day, cur_files, cur_events, cur_oldest))
            cur_files, cur_events, cur_oldest = [], 0, float("inf")
            cur_day = None

        for path in self.pending():
            day = self.day_of(path)
            if cur_files and day != cur_day:
                close()
            events, mtime = self._meta[path]
            cur_files.append(path)
            cur_events += events
            cur_oldest = min(cur_oldest, mtime)
            cur_day = day
            if max_events > 0 and cur_events >= max_events:
                close()
        # Tail group: time-triggered (oldest pending event too stale to
        # keep waiting for a full count) or flushed.
        if cur_files:
            stale = (window_s > 0
                     and self._clock() - cur_oldest >= window_s)
            if flush or stale:
                close()
            else:
                cur_files = []  # leave the tail pending
        for _day, files, _ev, _ts in out:
            for f in files:
                self._meta.pop(f, None)
                base, _s, end = split_byte_range(f)
                if end is not None:
                    # Tail mode: the file's consumed offset advances to
                    # the carved cut; the next poll registers whatever
                    # bytes landed after it.
                    self._offsets[base] = max(
                        self._offsets.get(base, 0), end)
                    self._tail_pending.pop(base, None)
                else:
                    self._consumed.add(f)
        monitor.set_gauge("stream/pending_files", float(len(self._meta)))
        return out
