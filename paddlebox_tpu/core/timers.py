"""Hot-path timers and timer groups.

Role of ``platform::Timer`` (``paddle/fluid/platform/timer.h``) and the
per-device timer block in ``DeviceBoxData`` printed by ``PrintSyncTimer``
(``fleet/box_wrapper.h:395-420``): resumable accumulating timers used to
attribute pass wall-time to pipeline stages (read / pack / pull / fwd-bwd /
push / sync).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Iterator


class Timer:
    """Accumulating resumable timer (Pause/Resume/Reset semantics)."""

    __slots__ = ("_elapsed", "_start", "_count")

    def __init__(self) -> None:
        self._elapsed = 0.0
        self._start = None
        self._count = 0

    def start(self) -> None:
        if self._start is None:
            self._start = time.perf_counter()

    resume = start

    def pause(self) -> None:
        if self._start is not None:
            self._elapsed += time.perf_counter() - self._start
            self._start = None
            self._count += 1

    def reset(self) -> None:
        self._elapsed = 0.0
        self._start = None
        self._count = 0

    def add_elapsed(self, seconds: float) -> None:
        """Credit an externally-measured interval (a duration observed
        by other means — e.g. the profiler's synced step wall — without
        re-running it under this timer)."""
        self._elapsed += seconds
        self._count += 1

    @property
    def elapsed_sec(self) -> float:
        extra = 0.0
        if self._start is not None:
            extra = time.perf_counter() - self._start
        return self._elapsed + extra

    @property
    def count(self) -> int:
        return self._count

    @contextmanager
    def scope(self) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.pause()


class TimerGroup:
    """Named timers for pass-stage attribution (role of DeviceBoxData timers)."""

    def __init__(self) -> None:
        self._timers: Dict[str, Timer] = {}

    def __getitem__(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            t = self._timers[name] = Timer()
        return t

    @contextmanager
    def scope(self, name: str) -> Iterator[None]:
        with self[name].scope():
            yield

    def report(self) -> str:
        parts = []
        for name in sorted(self._timers):
            t = self._timers[name]
            parts.append(f"{name}={t.elapsed_sec * 1e3:.1f}ms/{t.count}")
        return " ".join(parts)

    # -- unified report path (core.monitor registry) ----------------------
    # TimerGroup predates the metric registry; these bridge the two so
    # there is ONE report surface (the old report() string stays as a
    # shim for existing log lines).

    def snapshot_ms(self) -> Dict[str, float]:
        """Cumulative elapsed ms per timer — the delta basis for
        per-pass stage attribution (core.report.stage_delta)."""
        return {n: t.elapsed_sec * 1e3 for n, t in self._timers.items()}

    def report_dict(self) -> Dict[str, Dict[str, float]]:
        return {n: {"ms": round(t.elapsed_sec * 1e3, 3),
                    "count": t.count}
                for n, t in sorted(self._timers.items())}

    def publish(self, prefix: str, registry=None) -> None:
        """Mirror every timer into the metric registry as float gauges
        ``<prefix>/<name>_ms`` (+ ``_count`` counters) — one exporter
        (the metrics JSONL) covers timers too."""
        if registry is None:
            from paddlebox_tpu.core import monitor
            registry = monitor.GLOBAL
        for n, t in self._timers.items():
            registry.set_gauge(f"{prefix}/{n}_ms", t.elapsed_sec * 1e3)
            registry.set(f"{prefix}/{n}_count", t.count)

    def reset(self) -> None:
        for t in self._timers.values():
            t.reset()
