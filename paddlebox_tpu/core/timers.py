"""Hot-path timers and timer groups.

Role of ``platform::Timer`` (``paddle/fluid/platform/timer.h``) and the
per-device timer block in ``DeviceBoxData`` printed by ``PrintSyncTimer``
(``fleet/box_wrapper.h:395-420``): resumable accumulating timers used to
attribute pass wall-time to pipeline stages (read / pack / pull / fwd-bwd /
push / sync).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Iterator


class Timer:
    """Accumulating resumable timer (Pause/Resume/Reset semantics)."""

    __slots__ = ("_elapsed", "_start", "_count")

    def __init__(self) -> None:
        self._elapsed = 0.0
        self._start = None
        self._count = 0

    def start(self) -> None:
        if self._start is None:
            self._start = time.perf_counter()

    resume = start

    def pause(self) -> None:
        if self._start is not None:
            self._elapsed += time.perf_counter() - self._start
            self._start = None
            self._count += 1

    def reset(self) -> None:
        self._elapsed = 0.0
        self._start = None
        self._count = 0

    @property
    def elapsed_sec(self) -> float:
        extra = 0.0
        if self._start is not None:
            extra = time.perf_counter() - self._start
        return self._elapsed + extra

    @property
    def count(self) -> int:
        return self._count

    @contextmanager
    def scope(self) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.pause()


class TimerGroup:
    """Named timers for pass-stage attribution (role of DeviceBoxData timers)."""

    def __init__(self) -> None:
        self._timers: Dict[str, Timer] = {}

    def __getitem__(self, name: str) -> Timer:
        t = self._timers.get(name)
        if t is None:
            t = self._timers[name] = Timer()
        return t

    @contextmanager
    def scope(self, name: str) -> Iterator[None]:
        with self[name].scope():
            yield

    def report(self) -> str:
        parts = []
        for name in sorted(self._timers):
            t = self._timers[name]
            parts.append(f"{name}={t.elapsed_sec * 1e3:.1f}ms/{t.count}")
        return " ".join(parts)

    def reset(self) -> None:
        for t in self._timers.values():
            t.reset()
