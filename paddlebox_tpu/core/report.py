"""PaddleBox-style pass report: one structured summary line per pass.

Role of ``PrintSyncTimer`` (``fleet/box_wrapper.h:395-420``): at every
pass boundary the reference prints the per-device stage timers
(read / pack / pull / fwd-bwd / push / sync) that attribute the pass's
wall time to pipeline stages. Here the same stage names are host-side
timers (the TPU step fuses pull/fwd-bwd/push into ONE jitted program, so
their device time cannot be split without adding syncs — the host-visible
halves carry the names instead; see OBSERVABILITY.md for the exact
mapping) and the report is one machine-parseable line:

    pass_report {"kind": "train", "steps": 13, "samples_per_s": ..., ...}

The emit also lands in the metric registry (counters/gauges + the
step-latency histogram feed happens at the call sites) and appends one
labeled snapshot line to the metrics JSONL when configured — one report
path for log line, registry, and exporter.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from paddlebox_tpu.core import log, monitor, timers, trace

# Canonical stage-timer names (the PrintSyncTimer vocabulary). Every
# pass summary carries ALL of them — a stage the host could not observe
# this pass reports 0.0 rather than disappearing, so downstream tooling
# (tools/trace_report.py, PROFILE rounds) sees a stable schema.
STAGES = ("read", "pack", "pull", "fwd_bwd", "push", "dispatch", "sync")

# Last emitted summaries, stashed for the incident flight recorder
# (core/incident.py): a bundle answers "what was the last pass doing"
# without scraping the log.
LAST_PASS_REPORT: Optional[Dict[str, Any]] = None
LAST_QUALITY_REPORT: Optional[Dict[str, Any]] = None


def stage_delta(group: "timers.TimerGroup",
                base_ms: Dict[str, float]) -> Dict[str, float]:
    """Per-pass stage ms from a cumulative TimerGroup: current snapshot
    minus the snapshot taken at pass start (the group is shared across
    passes — bench.py reads its cumulative totals — so the pass report
    must difference, not read raw)."""
    now = group.snapshot_ms()
    out = {s: round(now.get(s, 0.0) - base_ms.get(s, 0.0), 3)
           for s in STAGES}
    for name, ms in now.items():
        if name not in out:
            out[name] = round(ms - base_ms.get(name, 0.0), 3)
    return out


def emit_pass_report(kind: str, *, steps: int, samples: int,
                     wall_s: float, stage_ms: Dict[str, float],
                     stats: Optional[Dict[str, Any]] = None,
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Build + publish one per-pass summary. Returns the summary dict
    (callers may attach it to their stats).

    - logs ONE ``pass_report {json}`` line (the PrintSyncTimer moment)
    - bumps registry counters/gauges under ``pass/``
    - appends a labeled snapshot to the metrics JSONL (if configured)
    - drops a trace instant so the report is visible in the timeline
    """
    summary: Dict[str, Any] = {
        "kind": kind,
        "steps": int(steps),
        "samples": int(samples),
        "wall_s": round(wall_s, 4),
        "samples_per_s": round(samples / wall_s, 1) if wall_s > 0 else 0.0,
        "stage_ms": {s: round(float(stage_ms.get(s, 0.0)), 3)
                     for s in STAGES},
    }
    # Non-canonical timers (host_map, feed_pass, ...) ride along without
    # polluting the stable stage schema.
    other = {k: v for k, v in stage_ms.items() if k not in STAGES}
    if other:
        summary["other_ms"] = other
    for src in (stats or {}), (extra or {}):
        for k, v in src.items():
            if k not in summary:
                summary[k] = v

    reg = monitor.GLOBAL
    reg.add(f"pass/{kind}_passes", 1)
    reg.add(f"pass/{kind}_steps", int(steps))
    reg.add(f"pass/{kind}_samples", int(samples))
    reg.set_gauge(f"pass/{kind}_samples_per_s", summary["samples_per_s"])
    reg.set_gauge(f"pass/{kind}_wall_s", summary["wall_s"])
    for s in STAGES:
        reg.set_gauge(f"pass/{kind}_{s}_ms", summary["stage_ms"][s])
    if stats:
        # Model-health headline beside the systems stages: the shared
        # AUC sweep computes bucket_error / copc / ctr ratios every
        # pass — they land as gauges (and ride the summary via the
        # stats merge above) instead of being dropped on the floor.
        for k in ("loss", "auc", "bucket_error", "copc",
                  "actual_ctr", "predicted_ctr"):
            v = stats.get(k)
            if isinstance(v, (int, float)):
                reg.set_gauge(f"pass/{kind}_{k}", float(v))
        for k in ("dispatch_blocks", "host_syncs", "lookup_overflow",
                  "lookup_exchange_bytes"):
            v = stats.get(k)
            if isinstance(v, (int, float)):
                reg.set(f"pass/{kind}_{k}", int(v))
    # Pass-boundary breakdown (split build / fused end-begin, round 8):
    # end_ms / build_ms / feed_wait_ms / overlap_frac ride the summary
    # AND land as gauges so the JSONL exporter carries the overlap win.
    b = summary.get("boundary")
    if isinstance(b, dict):
        for k in ("end_ms", "build_ms", "feed_wait_ms", "overlap_frac",
                  "exchange_overlap_frac"):
            v = b.get(k)
            if isinstance(v, (int, float)):
                reg.set_gauge(f"pass/{kind}_boundary_{k}", float(v))
    # Critical-path verdict (round 11): headline fractions + per-stage
    # occupancy land as gauges under pipeline/ so trace_report.py can
    # render the occupancy table from the metrics JSONL alone.
    bn = summary.get("bottleneck")
    if isinstance(bn, dict):
        for k in ("device_idle_frac", "host_critical_share"):
            v = bn.get(k)
            if isinstance(v, (int, float)):
                reg.set_gauge(f"pass/{kind}_{k}", float(v))
        for stage, sh in (bn.get("stages") or {}).items():
            for k in ("busy_ms", "busy_frac", "blocked_up_frac",
                      "blocked_down_frac"):
                v = sh.get(k)
                if isinstance(v, (int, float)):
                    reg.set_gauge(f"pipeline/{stage}_{k}", float(v))
    dq = summary.get("dispatch_ms_quantiles")
    if isinstance(dq, dict):
        for k, v in dq.items():
            if k != "count" and isinstance(v, (int, float)):
                reg.set_gauge(f"pass/{kind}_dispatch_ms_{k}", float(v))

    line = json.dumps(summary, default=str)
    log.info("pass_report %s", line)
    trace.instant(f"pass_report/{kind}", steps=steps,
                  samples_per_s=summary["samples_per_s"])
    reg.flush_jsonl(labels={"event": "pass_report", "kind": kind})
    global LAST_PASS_REPORT
    LAST_PASS_REPORT = summary
    return summary


def emit_quality_report(kind: str, summary: Dict[str, Any]
                        ) -> Dict[str, Any]:
    """Publish one model-quality summary (core/quality.py) the same
    three ways the pass report goes out: ONE structured
    ``quality_report {json}`` log line beside ``pass_report``, a trace
    instant, and a labeled metrics-JSONL snapshot — so a COPC
    excursion or a dark slot is greppable, timeline-visible, and
    scrape-able through the same plane."""
    reg = monitor.GLOBAL
    reg.add("quality/reports", 1)
    line = json.dumps(summary, default=str)
    log.info("quality_report %s", line)
    trace.instant(f"quality_report/{kind}",
                  alarms=len(summary.get("alarms") or ()),
                  copc=summary.get("copc"))
    reg.flush_jsonl(labels={"event": "quality_report", "kind": kind})
    global LAST_QUALITY_REPORT
    LAST_QUALITY_REPORT = summary
    return summary


def init_telemetry_from_flags() -> None:
    """One-call arming of every telemetry plane from flags (trace path,
    metrics path, history sampler, alert engine). Idempotent and
    near-free when all are unset — the trainer/bench/serving entry
    points call it unconditionally."""
    trace.init_from_flags()
    monitor.init_from_flags()
    from paddlebox_tpu.core import alerts, timeseries
    timeseries.init_from_flags()
    alerts.init_from_flags()
