"""Heartbeat stall watchdog: abort a hung pass instead of hanging forever.

bench.py grew an ad-hoc watchdog after r05 (a device call blocked on the
axon tunnel socket for 30+ minutes with zero progress and the run
recorded nothing). This module is that watchdog moved into the library
proper, generalized for the training loop: the day runner arms it around
each pass, the trainer's per-block dispatch path feeds it, and a stall
(``FLAGS_stall_timeout_s`` with no heartbeat) dumps
``trace.stall_forensics()`` — every thread's Python stack + the span-ring
tail — into the log, then aborts the pass by raising :class:`StallError`
*in the armed thread* so the failure flows through the same
cancel/rollback/retry machinery as any other transient fault.

The async raise (``PyThreadState_SetAsyncExc``) lands when the target
thread next executes Python bytecode. A thread blocked inside a C call
(a dead socket read with no timeout) won't see it until the call
returns — which is why the forensic dump happens FIRST: even if the
abort cannot land, the log names the blocked frame.

Zero cost when disarmed: ``beat()`` checks ONE cached bool
(the ``core/trace.py`` discipline). Nothing here touches jitted code.
"""

from __future__ import annotations

import ctypes
import sys
import threading
import time
from typing import Callable, Optional

from paddlebox_tpu.core import flags, log, monitor, trace


class StallError(RuntimeError):
    """No heartbeat within the stall timeout. Classified transient: the
    observed stalls (wedged device tunnel, dead socket) are exactly the
    faults a pass retry recovers from."""

    transient = True


def _async_raise(thread_ident: int, exc_type: type) -> bool:
    """Raise ``exc_type`` in the thread with ``thread_ident`` the next
    time it runs Python bytecode. Returns whether the raise was armed."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), ctypes.py_object(exc_type))
    if res > 1:  # pragma: no cover - interpreter-level invariant
        # Undo: >1 means we hit multiple states (stale ident) — leaving
        # the exception pending there would corrupt an innocent thread.
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident), None)
        return False
    return res == 1


class Watchdog:
    """One armed window at a time: ``arm()`` starts (or re-targets) the
    monitor thread, ``beat()`` feeds it, ``disarm()`` closes the window.

    ``on_stall(phase, idle_s)`` overrides the default abort action —
    bench.py uses it to print its structured failure JSON and hard-exit;
    the default dumps forensics and async-raises :class:`StallError` in
    the armed thread, once per armed window."""

    def __init__(self, timeout_s: float, *, name: str = "watchdog",
                 on_stall: Optional[Callable[[str, float], None]] = None,
                 poll_s: float = 0.0,
                 heartbeat_s: float = 0.0):
        self.name = name
        self._timeout = float(timeout_s)
        self._on_stall = on_stall
        self._poll = float(poll_s) if poll_s > 0 else None
        self._heartbeat_s = float(heartbeat_s)
        self._armed = False            # the ONE beat() check
        self._lock = threading.Lock()
        self._t = time.monotonic()
        self._t0 = self._t
        self._phase = ""
        self._target: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fired = False

    # -- arm/feed ----------------------------------------------------------

    @property
    def armed(self) -> bool:
        return self._armed

    def set_timeout(self, timeout_s: float) -> None:
        """Re-tier the limit mid-window (bench's short-until-proven-alive
        then relaxed two-tier scheme)."""
        self._timeout = float(timeout_s)

    def arm(self, *, thread: Optional[threading.Thread] = None,
            phase: str = "armed") -> None:
        """Open a watch window targeting ``thread`` (default: the calling
        thread — the one a stall should abort). Re-arming re-targets and
        resets the heartbeat; the monitor thread is started once."""
        with self._lock:
            t = thread if thread is not None else threading.current_thread()
            self._target = t.ident
            self._t = time.monotonic()
            self._phase = phase
            self._fired = False
            self._armed = True
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name=f"{self.name}-monitor",
                    daemon=True)
                self._thread.start()

    def beat(self, phase: Optional[str] = None) -> None:
        if not self._armed:
            return
        self._t = time.monotonic()
        if phase is not None:
            self._phase = phase

    def disarm(self) -> None:
        self._armed = False

    def close(self) -> None:
        """Stop the monitor thread (tests; long-lived runners just
        disarm between windows)."""
        self._armed = False
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def idle_s(self) -> float:
        return time.monotonic() - self._t

    @property
    def phase(self) -> str:
        return self._phase

    # -- the monitor -------------------------------------------------------

    def _loop(self) -> None:
        last_hb = time.monotonic()
        while not self._stop.is_set():
            poll = self._poll or max(0.05, min(1.0, self._timeout / 8.0))
            if self._stop.wait(poll):
                return
            if not self._armed:
                continue
            now = time.monotonic()
            if self._heartbeat_s > 0 and now - last_hb >= self._heartbeat_s:
                last_hb = now
                print(f"[{self.name} hb] phase={self._phase} "
                      f"idle={now - self._t:.0f}s "
                      f"elapsed={now - self._t0:.0f}s",
                      file=sys.stderr, flush=True)
            # Check-and-set under the lock: arm() resets _fired from the
            # training thread, and an unguarded race here could double-
            # fire (two async raises) into a freshly re-armed window.
            with self._lock:
                idle = now - self._t
                fire = (self._armed and idle > self._timeout
                        and not self._fired)
                if fire:
                    self._fired = True
            if fire:
                self._fire(idle)

    def _fire(self, idle: float) -> None:
        monitor.add("watchdog/stalls", 1)
        monitor.set_gauge("watchdog/last_stall_idle_s", round(idle, 3))
        phase = self._phase
        if self._on_stall is not None:
            self._on_stall(phase, idle)
            return
        # Default action: forensics into the log, then abort the armed
        # thread through the normal exception path. The RPC plane leads
        # (rpc.poller_table / rpc.inflight_table via the forensics
        # providers): a stall in the event-loop plane should name the
        # POLLER THREAD and its deepest worker queue first — a wedged
        # poller or a backed-up worker pool stalls every conn it owns —
        # then the in-flight remotes (a stall blocked on a dead peer
        # should name the REMOTE, not bury it under thread stacks).
        fx = trace.stall_forensics()
        pollers = fx.get("rpc_pollers") or []
        plane = "; ".join(
            f"{p['service']}@{p['endpoint']} thread={p['thread']} "
            f"queue={p['worker_queue_depth']} "
            f"lag={p['loop_lag_ms']:.1f}ms conns={p['conns']}"
            for p in pollers if isinstance(p, dict)) or "none"
        inflight = fx.get("inflight_rpcs") or []
        remote = "; ".join(
            f"{e['service']}.{e['method']} -> {e['endpoint']} "
            f"(in flight {e['age_s']:.1f}s, "
            f"{e.get('outstanding', 1)} outstanding)"
            for e in inflight if isinstance(e, dict)) or "none"
        log.warning(
            "%s: no progress in phase %r for %.0fs — rpc pollers "
            "(deepest queue first): %s — in-flight RPCs: %s — dumping "
            "stall forensics and aborting the pass:\n%s",
            self.name, phase, idle, plane, remote,
            "\n".join(fx.get("thread_stacks", [])))
        # Flight recorder (core/incident.py): persist the forensics
        # just gathered — a stall at 3am should leave a bundle, not
        # only a log line. Contained + rate-limited inside trigger.
        from paddlebox_tpu.core import incident
        incident.trigger("watchdog_stall",
                         context={"watchdog": self.name,
                                  "phase": phase,
                                  "idle_s": round(idle, 3)},
                         forensics=fx)
        target = self._target
        if target is not None and _async_raise(target, StallError):
            monitor.add("watchdog/aborts", 1)
            trace.instant("watchdog/abort", phase=phase,
                          idle_s=round(idle, 3))
        else:  # pragma: no cover - target already gone
            log.warning("%s: armed thread %s is gone; nothing to abort",
                        self.name, target)


# Process-global instance for the training loop: the day runner arms it
# per pass (FLAGS_stall_timeout_s), the trainer's dispatch path feeds it.
GLOBAL = Watchdog(timeout_s=0.0, name="pass-watchdog")

beat = GLOBAL.beat


def arm_from_flags(*, phase: str = "pass",
                   thread: Optional[threading.Thread] = None) -> bool:
    """Arm the global pass watchdog when FLAGS_stall_timeout_s > 0.
    Returns whether it armed (caller pairs with ``disarm()``)."""
    timeout = float(flags.flag("stall_timeout_s"))
    if timeout <= 0:
        return False
    GLOBAL.set_timeout(timeout)
    GLOBAL.arm(thread=thread, phase=phase)
    return True


def disarm() -> None:
    GLOBAL.disarm()
