"""Deterministic fault injection: named faultpoints driven by a spec flag.

The recovery story of the reference system — donefile resume, elastic
restart, pass-exactly-once — is only credible if the failure paths are
exercised deliberately. This module lets a test (or an operator drill)
break a *named site* in the pipeline on a *chosen traversal*: raise a
typed exception, inject latency, or kill the process outright — all from
one spec string, with no code changes at the site.

Spec grammar (``FLAGS_fault_spec``; ``;``-separated clauses)::

    <site>[:hit=N][:times=M]:<action>

    actions:   raise=<ExcName>     raise that exception type at the site
               delay_ms=<float>    sleep that long, then continue
               kill[=SIG]          os.kill(self, SIG) — crash drills
                                   (default SIGKILL)

    hit=N      trigger on the Nth traversal of the site (1-based,
               default 1); earlier traversals pass through untouched
    times=M    how many consecutive traversals trigger once armed
               (default 1; 0 = every traversal from N on)

Examples::

    FLAGS_fault_spec='pass_engine/build:hit=2:raise=IOError'
    FLAGS_fault_spec='transport/get:delay_ms=500;day_runner/publish:kill'

Design constraints (sites sit on pass-loop paths):

- **Zero cost when disabled.** ``faultpoint(site)`` checks ONE cached
  bool and returns — no flag-registry read, no lock, no allocation
  (the ``core/trace.py`` discipline). Arming is explicit
  (``configure()`` or ``init_from_flags()``), never inferred per call.
- **Host-side only.** A faultpoint may never appear inside a jitted
  program; sites wrap host orchestration (builds, dispatch boundaries,
  checkpoint IO, sockets).
- **Observable.** Every triggered injection bumps
  ``fault/<site>_injected`` in the metric registry and drops a trace
  instant, so a drill's forensics name what was broken and when.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Dict, List, Optional

from paddlebox_tpu.core import flags, log, monitor, trace


class InjectedFault(RuntimeError):
    """Default injected exception (used when raise= names no builtin).
    Carries ``site`` and is classified transient by default."""

    transient = True

    def __init__(self, msg: str, site: str = ""):
        super().__init__(msg)
        self.site = site


# Exception types a spec may name. Anything else becomes InjectedFault
# with the requested name in the message (never a silent typo-noop).
_EXC_TYPES = {
    t.__name__: t
    for t in (OSError, RuntimeError, ValueError, KeyError,
              ConnectionError, ConnectionResetError, BrokenPipeError,
              TimeoutError, FloatingPointError, MemoryError, EOFError,
              InterruptedError, InjectedFault)
}
# IOError is an alias of OSError whose __name__ says 'OSError' — keep
# the spelling drills actually use.
_EXC_TYPES["IOError"] = OSError

# Exception types (and supertypes) the self-healing pass loop treats as
# TRANSIENT — worth a rollback + retry. Everything else is fatal: a
# ValueError/KeyError/FloatingPointError means wrong data or wrong code,
# and retrying would just fail again (or worse, hide a real bug).
_TRANSIENT_TYPES = (TimeoutError, ConnectionError, InterruptedError,
                    BrokenPipeError, OSError, EOFError, InjectedFault)


def is_transient(exc: BaseException) -> bool:
    """Classify an exception for the pass-retry loop. An explicit
    ``exc.transient`` attribute wins (StallError sets True; a fault spec
    raising ValueError stays fatal by design); otherwise IO-flavored
    types are transient and everything else — including BaseExceptions
    like KeyboardInterrupt — is fatal."""
    t = getattr(exc, "transient", None)
    if t is not None:
        return bool(t)
    if not isinstance(exc, Exception):
        return False  # KeyboardInterrupt / SystemExit: never retry
    return isinstance(exc, _TRANSIENT_TYPES)


@dataclasses.dataclass
class FaultSpec:
    site: str
    hit: int = 1            # 1-based traversal that first triggers
    times: int = 1          # consecutive triggers once armed (0 = forever)
    raise_name: Optional[str] = None
    delay_ms: float = 0.0
    kill_sig: Optional[int] = None

    def should_trigger(self, n_hit: int) -> bool:
        if n_hit < self.hit:
            return False
        if self.times == 0:
            return True
        return n_hit < self.hit + self.times


class FaultError(ValueError):
    """Malformed FLAGS_fault_spec — raised at configure time, never at a
    site (a drill with a typo'd spec must fail loudly up front)."""


def parse_fault_spec(spec: str) -> List[FaultSpec]:
    """Parse the spec string. Empty/whitespace → []."""
    out: List[FaultSpec] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        fs = FaultSpec(site=parts[0].strip())
        if not fs.site:
            raise FaultError(f"fault clause without a site: {clause!r}")
        has_action = False
        for p in parts[1:]:
            p = p.strip()
            key, _, val = p.partition("=")
            if key == "hit":
                fs.hit = int(val)
            elif key == "times":
                fs.times = int(val)
            elif key == "raise":
                fs.raise_name = val or "InjectedFault"
                has_action = True
            elif key == "delay_ms":
                fs.delay_ms = float(val)
                has_action = True
            elif key == "kill":
                fs.kill_sig = int(val) if val else int(signal.SIGKILL)
                has_action = True
            else:
                raise FaultError(
                    f"unknown fault directive {p!r} in {clause!r}")
        if not has_action:
            raise FaultError(
                f"fault clause {clause!r} has no action "
                "(raise= / delay_ms= / kill)")
        if fs.hit < 1:
            raise FaultError(f"hit must be >= 1 in {clause!r}")
        out.append(fs)
    return out


class FaultRegistry:
    """Process-global faultpoint registry (one per process, like the
    tracer and the metric registry)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed = False          # the ONE hot-path check
        self._specs: Dict[str, FaultSpec] = {}
        self._hits: Dict[str, int] = {}
        self._flags_checked = False

    # -- lifecycle ---------------------------------------------------------

    @property
    def armed(self) -> bool:
        return self._armed

    def configure(self, spec: str) -> int:
        """Arm from a spec string (replaces any previous config; empty
        disarms). Returns the number of active fault clauses."""
        specs = parse_fault_spec(spec)
        with self._lock:
            self._specs = {fs.site: fs for fs in specs}
            self._hits = {}
            self._armed = bool(self._specs)
        if self._armed:
            log.warning("fault injection ARMED: %s",
                        "; ".join(sorted(self._specs)))
        return len(specs)

    def clear(self) -> None:
        with self._lock:
            self._specs = {}
            self._hits = {}
            self._armed = False
            self._flags_checked = False

    def init_from_flags(self) -> bool:
        """Idempotent flag-driven arm (called at pass/bench/service entry
        points beside telemetry init): a non-empty ``FLAGS_fault_spec``
        configures the registry ONCE. Returns armed."""
        if not self._flags_checked:
            self._flags_checked = True
            spec = flags.flag("fault_spec")
            if spec:
                self.configure(spec)
        return self._armed

    # -- introspection (tests / drills) ------------------------------------

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def sites(self) -> List[str]:
        with self._lock:
            return sorted(self._specs)

    # -- the faultpoint ----------------------------------------------------

    def faultpoint(self, site: str) -> None:
        """Declare a named fault site. Disabled path: one cached-bool
        check, nothing else."""
        if not self._armed:
            return
        with self._lock:
            fs = self._specs.get(site)
            if fs is None:
                return
            n = self._hits.get(site, 0) + 1
            self._hits[site] = n
            if not fs.should_trigger(n):
                return
        self._trigger(site, fs, n)

    def _trigger(self, site: str, fs: FaultSpec, n_hit: int) -> None:
        monitor.add(f"fault/{site}_injected", 1)
        trace.instant("fault/injected", site=site, hit=n_hit)
        if fs.delay_ms > 0:
            log.warning("faultpoint %s (hit %d): injecting %.0f ms delay",
                        site, n_hit, fs.delay_ms)
            time.sleep(fs.delay_ms / 1e3)
        if fs.kill_sig is not None:
            # Crash drill: no cleanup, no atexit — the whole point is to
            # die the way a SIGKILL'd/OOM'd production worker dies.
            log.warning("faultpoint %s (hit %d): killing pid %d with "
                        "signal %d", site, n_hit, os.getpid(), fs.kill_sig)
            os.kill(os.getpid(), fs.kill_sig)
            time.sleep(30)  # SIGKILL needs no help; give softer sigs time
        if fs.raise_name is not None:
            exc_type = _EXC_TYPES.get(fs.raise_name)
            msg = (f"injected fault at {site!r} "
                   f"(hit {n_hit}, spec {fs.raise_name})")
            log.warning("faultpoint %s (hit %d): raising %s",
                        site, n_hit, fs.raise_name)
            if exc_type is None or exc_type is InjectedFault:
                raise InjectedFault(msg, site=site)
            raise exc_type(msg)


GLOBAL = FaultRegistry()

faultpoint = GLOBAL.faultpoint
configure = GLOBAL.configure
clear = GLOBAL.clear
init_from_flags = GLOBAL.init_from_flags
armed = lambda: GLOBAL.armed  # noqa: E731
hits = GLOBAL.hits
sites = GLOBAL.sites
