"""Incident flight recorder: forensics captured when nobody is watching.

PRs 14-16 built the forensic surfaces — trace ring, stall forensics
providers (thread stacks + ``rpc.inflight_table()`` /
``rpc.poller_table()``), metric history — but each only helps if a
human is at the terminal when things break. This module snapshots all
of them into ONE JSON bundle the moment something goes wrong:

Triggers (the incident matrix):
- a FIRING **page**-severity alert (core/alerts.py publishes here),
- a **watchdog stall** (core/watchdog.py's default fire path),
- a fleet **replica eject** (serving/fleet.py),
- a **STALE_PRIMARY burst** (multihost/shard_service.py's redirect
  errors arriving faster than failover should produce them).

Bundle layout (one dict, rendered by ``tools/incident_report.py``):
``kind/ts/seq/context`` header, ``alerts`` (active + resolved),
``history`` (the metric ring window), ``forensics`` (thread stacks,
trace tail, in-flight RPCs, poller tables — the same providers the
watchdog prints), ``pass_report``/``quality_report`` (last emitted),
and a flat ``metrics`` snapshot.

Write discipline: bundle goes to ``<dir>/.incident-*.tmp`` then ONE
``os.replace`` — a reader (or the crash drill's kill window at
``incident/capture``) can never mistake a torn bundle for a complete
one, because complete bundles only ever appear atomically. Captures
are rate-limited (``FLAGS_incident_min_interval_s``) so a flapping
alert cannot fill a disk, and CONTAINED: a capture crash is counted
(``incident/capture_errors``), warned, and never propagates into the
serving/training thread that tripped it. Default-off: with
``FLAGS_incident_dir`` empty, ``trigger()`` is one cached-bool check.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from paddlebox_tpu.core import faults, flags, log, monitor, trace

# STALE_PRIMARY burst detection: this many redirect errors inside the
# window means clients are storming a demoted primary (routing is not
# converging) — an incident, not a blip.
STALE_BURST = 3
STALE_WINDOW_S = 10.0

# Keep bundles bounded: trace tail length and history points captured.
TRACE_TAIL = 256
HISTORY_POINTS = 120


class IncidentRecorder:
    """One per process (module-level default below); tests build their
    own with injected clocks and a tmp dir."""

    def __init__(self, directory: Optional[str] = None, *,
                 min_interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self._dir = directory
        self._min_interval = min_interval_s
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._last: Optional[float] = None
        self._seq = 0
        self._context: Dict[str, Any] = {}
        self._stale: deque = deque(maxlen=STALE_BURST)

    # -- configuration -----------------------------------------------------

    def _directory(self) -> str:
        d = self._dir if self._dir is not None \
            else str(flags.flag("incident_dir") or "")
        return d

    def _interval(self) -> float:
        if self._min_interval is not None:
            return float(self._min_interval)
        return float(flags.flag("incident_min_interval_s"))

    @property
    def enabled(self) -> bool:
        return bool(self._directory())

    def set_context(self, **kv: Any) -> None:
        """Stamp ambient context (stream runner: day/pass) carried in
        every subsequent bundle. ``None`` values clear keys."""
        with self._lock:
            for k, v in kv.items():
                if v is None:
                    self._context.pop(k, None)
                else:
                    self._context[k] = v

    # -- capture -----------------------------------------------------------

    def trigger(self, kind: str, *,
                context: Optional[Dict[str, Any]] = None,
                forensics: Optional[Dict[str, Any]] = None,
                force: bool = False) -> Optional[str]:
        """Capture one bundle. Returns the bundle path, or None when
        disabled / rate-limited / failed. NEVER raises — the
        containment contract (ROBUSTNESS.md ``incident/capture``)."""
        if not self.enabled:
            return None
        now = self._clock()
        with self._lock:
            if (not force and self._last is not None
                    and now - self._last < self._interval()):
                monitor.add("incident/rate_limited", 1)
                return None
            # Claim the slot BEFORE the (slow) capture so concurrent
            # triggers in the window collapse to one bundle; release
            # the claim on failure so the next trigger retries.
            prev_last, self._last = self._last, now
            self._seq += 1
            seq = self._seq
        try:
            path = self._capture(kind, seq, context, forensics)
        except Exception as e:  # noqa: BLE001 - containment contract
            with self._lock:
                self._last = prev_last
            monitor.add("incident/capture_errors", 1)
            log.warning("incident: capture %r failed (contained): %r",
                        kind, e)
            return None
        monitor.add("incident/captured", 1)
        trace.instant("incident/capture", kind=kind, path=path)
        log.warning("incident: captured %r -> %s", kind, path)
        return path

    def _capture(self, kind: str, seq: int,
                 context: Optional[Dict[str, Any]],
                 forensics: Optional[Dict[str, Any]]) -> str:
        from paddlebox_tpu.core import alerts, report, timeseries
        directory = self._directory()
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            ctx = dict(self._context)
        ctx.update(context or {})
        hist = timeseries.history_for(create=False)
        fx = forensics if forensics is not None \
            else trace.stall_forensics(max_events=TRACE_TAIL)
        bundle: Dict[str, Any] = {
            "schema": "incident/1",
            "kind": kind,
            "seq": seq,
            "ts": self._wall(),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "context": ctx,
            "alerts": alerts.active_alerts(),
            "history": (hist.to_dict(last_n=HISTORY_POINTS)
                        if hist is not None else None),
            "forensics": fx,
            "pass_report": report.LAST_PASS_REPORT,
            "quality_report": report.LAST_QUALITY_REPORT,
            "metrics": monitor.snapshot(),
        }
        stamp = time.strftime("%Y%m%dT%H%M%S",
                              time.gmtime(bundle["ts"]))
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in kind)
        final = os.path.join(directory,
                             f"incident-{stamp}-{seq:04d}-{slug}.json")
        tmp = os.path.join(directory,
                           f".incident-{seq:04d}-{slug}.tmp")
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        try:
            # THE crash window (tools/crash_drill.py --matrix incident):
            # bundle bytes durable under the tmp name, rename pending —
            # a kill here leaves a torn ``.tmp`` that ``list_bundles``
            # never mistakes for a complete bundle.
            faults.faultpoint("incident/capture")
            os.replace(tmp, final)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        return final

    # -- stale-primary burst detector --------------------------------------

    def note_stale_primary(self) -> None:
        """Called on every STALE_PRIMARY redirect error (shard tier).
        Cheap deque append; trips ``trigger`` when STALE_BURST arrive
        inside STALE_WINDOW_S."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            self._stale.append(now)
            burst = (len(self._stale) == STALE_BURST
                     and now - self._stale[0] <= STALE_WINDOW_S)
            if burst:
                self._stale.clear()
        if burst:
            self.trigger("stale_primary_burst",
                         context={"burst": STALE_BURST,
                                  "window_s": STALE_WINDOW_S})


def list_bundles(directory: str) -> list:
    """Complete bundles only, oldest first — ``.tmp`` files are torn
    captures by definition and never listed."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(os.path.join(directory, n) for n in names
                  if n.startswith("incident-") and n.endswith(".json"))


GLOBAL = IncidentRecorder()


def trigger(kind: str, *, context: Optional[Dict[str, Any]] = None,
            forensics: Optional[Dict[str, Any]] = None,
            force: bool = False) -> Optional[str]:
    return GLOBAL.trigger(kind, context=context, forensics=forensics,
                          force=force)


def note_stale_primary() -> None:
    GLOBAL.note_stale_primary()


def set_context(**kv: Any) -> None:
    GLOBAL.set_context(**kv)


def enabled() -> bool:
    return GLOBAL.enabled
