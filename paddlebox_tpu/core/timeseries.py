"""Bounded in-process metric history: the trend half of the registry.

``core/monitor.py`` answers "what is the counter NOW"; this module
answers "what was it over the last N windows" — the missing input for
burn-rate alerting (core/alerts.py), fleet_top sparklines and incident
bundles. A :class:`MetricHistory` is a bounded ring of per-window
points over ONE registry:

- **counters** land as per-window deltas (``rate()`` divides by span),
- **gauges** land as last-value,
- **quantile digests** land as :meth:`LogQuantileDigest.delta` window
  sketches — exact count-subtraction windows, so ``window_quantiles``
  gives the p99 *of the window*, not of process lifetime.

One process-wide :class:`HistorySampler` daemon thread ticks every
``FLAGS_history_interval_s`` and samples every registered history
(weakly held — instance registries on PredictServer/ShardServer/
FleetRouter ride the same thread). The clock is injected everywhere:
tests drive ``sample(now=...)`` with planted timestamps, and graftlint
replay purity holds because nothing on a replay root reads wall time
through this module. Default-off: with the interval at 0 the sampler
thread never starts and the hot-path cost is zero (histories are
sampled off-thread; nothing is observed inline).

Points are plain JSON dicts — ``to_dict()`` is the ``metrics_history``
RPC payload, and :func:`merge_history` folds per-host rings into one
cluster series (counter deltas summed, gauges meaned, digests merged
per aligned bucket), the same associativity story as
``monitor.merge_snapshots``.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.core import flags, log, monitor
from paddlebox_tpu.core.quantiles import DEFAULT_QS, LogQuantileDigest

Number = float


class MetricHistory:
    """Bounded ring of per-window points over one ``monitor.Monitor``.

    ``sample()`` diffs the registry's cumulative state against the
    previous sample: counters become deltas, digests become
    ``delta()`` window sketches, gauges pass through as last-value.
    Query methods never touch the registry or a clock — they read the
    ring only, so a wire-transported or merged history answers the
    same API through :meth:`from_dict`.
    """

    def __init__(self, registry: Optional[monitor.Monitor] = None, *,
                 points: Optional[int] = None, label: str = "",
                 clock: Callable[[], float] = time.time):
        self._registry = monitor.GLOBAL if registry is None else registry
        cap = int(points if points is not None
                  else flags.flag("history_points"))
        self._points: deque = deque(maxlen=max(cap, 2))
        self._clock = clock
        self._lock = threading.Lock()
        self._prev_counters: Dict[str, Number] = {}
        self._prev_digests: Dict[str, LogQuantileDigest] = {}
        self._sampled = False
        self.label = label

    # -- sampling ----------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Take one history point (sampler thread or a test driving an
        injected timestamp). The FIRST sample establishes the delta
        base and records an empty-delta point."""
        ts = float(self._clock() if now is None else now)
        snap = self._registry.snapshot_all()
        counters = snap.get("counters") or {}
        gauges = snap.get("gauges") or {}
        qdicts = snap.get("quantiles") or {}
        with self._lock:
            deltas: Dict[str, Number] = {}
            for k, v in counters.items():
                if isinstance(v, (int, float)):
                    d = v - self._prev_counters.get(k, 0)
                    if d:
                        deltas[k] = d
            qdelta: Dict[str, Any] = {}
            for name, d in qdicts.items():
                cur = LogQuantileDigest.from_dict(d)
                win = cur.delta(self._prev_digests.get(name))
                if win.count:
                    qdelta[name] = win.to_dict()
                self._prev_digests[name] = cur
            self._prev_counters = {k: v for k, v in counters.items()
                                   if isinstance(v, (int, float))}
            point = {"ts": round(ts, 3), "counters": deltas,
                     "gauges": {k: v for k, v in gauges.items()
                                if isinstance(v, (int, float))},
                     "quantiles": qdelta}
            self._points.append(point)
            self._sampled = True
        return point

    # -- queries (ring-only: work identically on merged/wire histories) ----

    def points(self, window_s: Optional[float] = None
               ) -> List[Dict[str, Any]]:
        """Points newest-last; ``window_s`` measures back from the
        NEWEST point's ts (no wall-clock read — replay-pure)."""
        with self._lock:
            pts = list(self._points)
        if window_s is None or not pts:
            return pts
        horizon = pts[-1]["ts"] - float(window_s)
        return [p for p in pts if p["ts"] > horizon]

    def series(self, name: str, *, window_s: Optional[float] = None
               ) -> List[Tuple[float, Number]]:
        """(ts, value) pairs: counter per-window deltas, else gauge
        last-values. A counter absent from a point contributes 0 (the
        ring stores only nonzero deltas)."""
        pts = self.points(window_s)
        if any(name in p["counters"] for p in pts):
            return [(p["ts"], p["counters"].get(name, 0)) for p in pts]
        return [(p["ts"], p["gauges"][name]) for p in pts
                if name in p["gauges"]]

    def rate(self, name: str, window_s: Optional[float] = None
             ) -> Optional[float]:
        """Counter events/second over the window: sum of deltas divided
        by the covered span. None with fewer than two points (no span
        to divide by — the first point is the delta base)."""
        pts = self.points(window_s)
        if len(pts) < 2:
            return None
        span = pts[-1]["ts"] - pts[0]["ts"]
        if span <= 0:
            return None
        # The first point's delta belongs to the window BEFORE pts[0].ts.
        total = sum(p["counters"].get(name, 0) for p in pts[1:])
        return total / span

    def delta(self, name: str, window_s: Optional[float] = None,
              *, prefix: bool = False) -> float:
        """Sum of counter deltas over the window; ``prefix=True`` sums
        every counter whose name starts with ``name`` (the
        ``quality/alarms/*`` family read)."""
        total = 0.0
        for p in self.points(window_s)[1:]:
            c = p["counters"]
            if prefix:
                total += sum(v for k, v in c.items()
                             if k.startswith(name))
            else:
                total += c.get(name, 0)
        return total

    def window_quantiles(self, name: str,
                         window_s: Optional[float] = None,
                         qs: Sequence[float] = DEFAULT_QS
                         ) -> Dict[str, float]:
        """Quantiles of the *window*: merge the per-point digest deltas
        covering the window and query the merged sketch. Empty dict
        when the metric was never observed in the window."""
        merged: Optional[LogQuantileDigest] = None
        for p in self.points(window_s):
            d = p["quantiles"].get(name)
            if not d:
                continue
            win = LogQuantileDigest.from_dict(d)
            if merged is None:
                merged = win
            else:
                merged.merge(win)
        if merged is None or not merged.count:
            return {}
        out = merged.quantiles(qs)
        out["count"] = merged.count
        return out

    def latest(self, name: str) -> Optional[Number]:
        s = self.series(name)
        return s[-1][1] if s else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._points)

    # -- wire --------------------------------------------------------------

    def to_dict(self, window_s: Optional[float] = None,
                last_n: Optional[int] = None) -> Dict[str, Any]:
        pts = self.points(window_s)
        if last_n is not None:
            pts = pts[-int(last_n):]
        return {"label": self.label,
                "capacity": self._points.maxlen,
                "points": pts}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MetricHistory":
        """Rehydrate a wire/merged history as a query-only ring (its
        ``sample()`` would diff against a fresh base — don't)."""
        pts = list(d.get("points") or ())
        h = cls(monitor.Monitor(),
                points=max(int(d.get("capacity") or len(pts) or 2),
                           len(pts), 2),
                label=str(d.get("label") or ""))
        h._points.extend(pts)
        return h


def merge_history(dicts: Sequence[Dict[str, Any]], *,
                  bucket_s: Optional[float] = None) -> Dict[str, Any]:
    """Fold per-host history dicts into ONE cluster series: points are
    aligned on ``bucket_s`` buckets (default: the median inter-point
    gap of the inputs, floored at 1s); within a bucket counter deltas
    SUM, gauges MEAN, digest windows MERGE — associative like
    ``monitor.merge_snapshots``, so merge order never changes the
    answer."""
    pts = [p for d in dicts for p in (d.get("points") or ())]
    if not pts:
        return {"label": "merged", "capacity": 2, "points": []}
    if bucket_s is None:
        gaps: List[float] = []
        for d in dicts:
            ps = d.get("points") or ()
            gaps.extend(b["ts"] - a["ts"] for a, b in zip(ps, ps[1:]))
        gaps = sorted(g for g in gaps if g > 0)
        bucket_s = gaps[len(gaps) // 2] if gaps else 1.0
    bucket_s = max(float(bucket_s), 1e-9)
    buckets: Dict[int, Dict[str, Any]] = {}
    gauge_n: Dict[int, Dict[str, int]] = {}
    for p in sorted(pts, key=lambda p: p["ts"]):
        b = int(p["ts"] // bucket_s)
        out = buckets.get(b)
        if out is None:
            out = buckets[b] = {"ts": round((b + 1) * bucket_s, 3),
                                "counters": {}, "gauges": {},
                                "quantiles": {}}
            gauge_n[b] = {}
        for k, v in (p.get("counters") or {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in (p.get("gauges") or {}).items():
            n = gauge_n[b].get(k, 0)
            prev = out["gauges"].get(k, 0.0)
            out["gauges"][k] = (prev * n + v) / (n + 1)
            gauge_n[b][k] = n + 1
        for k, d in (p.get("quantiles") or {}).items():
            cur = out["quantiles"].get(k)
            if cur is None:
                out["quantiles"][k] = dict(d)
            else:
                m = LogQuantileDigest.from_dict(cur)
                m.merge(LogQuantileDigest.from_dict(d))
                out["quantiles"][k] = m.to_dict()
    merged = [buckets[b] for b in sorted(buckets)]
    return {"label": "merged", "capacity": max(len(merged), 2),
            "points": merged}


class HistorySampler:
    """ONE daemon thread sampling every registered history per tick,
    then running the tick callbacks (the alert engine registers its
    evaluate here). Histories are weakly held — a server that goes
    away takes its history with it. Callbacks are CONTAINED: a crash
    is counted and warned, never propagated into the sampler loop."""

    def __init__(self, clock: Callable[[], float] = time.time):
        self._clock = clock
        self._histories: "weakref.WeakSet[MetricHistory]" = \
            weakref.WeakSet()
        self._callbacks: List[Tuple[str, Callable[[float], Any]]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self, history: MetricHistory) -> MetricHistory:
        with self._lock:
            self._histories.add(history)
        return history

    def add_callback(self, name: str,
                     fn: Callable[[float], Any]) -> None:
        with self._lock:
            self._callbacks = ([(n, f) for n, f in self._callbacks
                                if n != name] + [(name, fn)])

    def remove_callback(self, name: str) -> None:
        with self._lock:
            self._callbacks = [(n, f) for n, f in self._callbacks
                               if n != name]

    def tick(self, now: Optional[float] = None) -> int:
        """Sample every live history, then run callbacks. Returns the
        number of histories sampled (tests drive this directly with
        planted ``now``)."""
        ts = float(self._clock() if now is None else now)
        with self._lock:
            hs = list(self._histories)
            cbs = list(self._callbacks)
        n = 0
        for h in hs:
            try:
                h.sample(ts)
                n += 1
            except Exception as e:  # noqa: BLE001 - sampler must survive
                monitor.add("history/sample_errors", 1)
                log.warning("history: sample failed for %r: %r",
                            h.label, e)
        for name, fn in cbs:
            try:
                fn(ts)
            except Exception as e:  # noqa: BLE001 - contained by contract
                monitor.add("history/callback_errors", 1)
                log.warning("history: tick callback %s failed "
                            "(retried next tick): %r", name, e)
        monitor.GLOBAL.set_gauge("history/registries", float(len(hs)))
        return n

    def start(self, interval_s: float) -> bool:
        """Idempotent; non-positive interval = no thread (ticks can
        still be driven by hand)."""
        if interval_s <= 0:
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop.clear()

            def loop() -> None:
                while not self._stop.wait(interval_s):
                    self.tick()

            self._thread = threading.Thread(
                target=loop, name="history-sampler", daemon=True)
            self._thread.start()
        return True

    def stop(self) -> None:
        t = self._thread
        self._stop.set()
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()


GLOBAL_SAMPLER = HistorySampler()

# registry object -> its history, weakly keyed so instance registries
# (and their histories) die with their servers.
_HISTORIES: "weakref.WeakKeyDictionary[monitor.Monitor, MetricHistory]" \
    = weakref.WeakKeyDictionary()
_HIST_LOCK = threading.Lock()


def history_for(registry: Optional[monitor.Monitor] = None, *,
                label: str = "", create: bool = True
                ) -> Optional[MetricHistory]:
    """The (one) history ring over ``registry`` (default: the
    process-global registry), created on first ask and registered with
    the global sampler. Cheap when the sampler never starts — an idle
    ring object per server."""
    reg = monitor.GLOBAL if registry is None else registry
    with _HIST_LOCK:
        h = _HISTORIES.get(reg)
        if h is None and create:
            h = _HISTORIES[reg] = MetricHistory(reg, label=label)
            GLOBAL_SAMPLER.register(h)
        return h


def enabled() -> bool:
    return GLOBAL_SAMPLER.running


def init_from_flags() -> bool:
    """Arm the sampler when FLAGS_history_interval_s > 0 (or when the
    alert engine is on, with a 5s fallback cadence — alerts without
    history would never see a window). Idempotent; returns armed."""
    interval = float(flags.flag("history_interval_s"))
    if interval <= 0 and flags.flag("alerts_enable"):
        interval = 5.0
    if interval <= 0:
        return GLOBAL_SAMPLER.running
    history_for(label="global")
    return GLOBAL_SAMPLER.start(interval)
