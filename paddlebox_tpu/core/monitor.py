"""Process-global metric registry: counters, gauges, histograms.

Role of ``paddle/fluid/platform/monitor.h`` (``platform::Monitor`` /
``StatRegistry`` named int64 stats) grown into a full registry: int
counters (the original API, unchanged), FLOAT gauges (so rate/ratio call
sites don't silently truncate through the int counter path), and
fixed-bucket histograms (step/dispatch latency distributions).

Thread-safe and cheap to bump from the data pipeline, trainer, and RPC
threads. A labeled ``snapshot_all()`` returns one structured view; the
JSONL exporter appends snapshot lines to ``FLAGS_metrics_path`` — one
per pass report plus a periodic background flush thread
(``FLAGS_metrics_flush_interval_s``). Telemetry is default-off: with no
metrics path configured nothing is written and the flush thread never
starts.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

Number = Union[int, float]

# Default latency buckets (ms): exponential-ish 1ms..30s — wide enough
# for both a CPU smoke step and an axon-tunnel dispatch stall.
DEFAULT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                   1000.0, 2000.0, 5000.0, 10000.0, 30000.0)


class Histogram:
    """Fixed-bucket histogram: counts per bucket + running sum/min/max.

    Buckets are upper bounds; values above the last bound land in the
    implicit +inf bucket. Percentiles are estimated from bucket counts
    by tools/trace_report.py — the registry itself stores only O(len
    (buckets)) state no matter how many observations arrive."""

    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"histogram buckets must be strictly "
                             f"increasing: {buckets}")
        self.counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if v < self.min else self.min
        self.max = v if v > self.max else self.max

    def to_dict(self) -> Dict[str, Any]:
        return {"buckets": list(self.buckets),
                "counts": list(self.counts),
                "count": self.count,
                "sum": round(self.sum, 6),
                "min": self.min if self.count else None,
                "max": self.max if self.count else None}


class Monitor:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Number] = {}        # counters (add/set)
        self._gauges: Dict[str, float] = {}        # float set-last-wins
        self._hists: Dict[str, Histogram] = {}
        self._flush_thread: Optional[threading.Thread] = None
        self._flush_stop = threading.Event()
        self._flush_path: Optional[str] = None

    # -- counters (original StatRegistry API, unchanged) -------------------

    def add(self, name: str, delta: Number = 1) -> None:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + delta

    def set(self, name: str, value: Number) -> None:
        with self._lock:
            self._stats[name] = value

    def get(self, name: str) -> Number:
        with self._lock:
            return self._stats.get(name, 0)

    # -- gauges ------------------------------------------------------------

    def set_gauge(self, name: str, value: float) -> None:
        """Float gauge: last-write-wins (rates, ratios, ms figures —
        values the int counter path would truncate)."""
        with self._lock:
            self._gauges[name] = float(value)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    # -- histograms ---------------------------------------------------------

    def define_histogram(self, name: str,
                         buckets: Sequence[float] = DEFAULT_BUCKETS
                         ) -> None:
        """Pre-declare a histogram's buckets (idempotent for identical
        buckets; re-defining with different ones raises — silently
        changing bucket bounds mid-run would corrupt the series)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                self._hists[name] = Histogram(buckets)
            elif h.buckets != tuple(float(b) for b in buckets):
                raise ValueError(
                    f"histogram {name!r} already defined with buckets "
                    f"{h.buckets}")

    def observe(self, name: str, value: float,
                buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(buckets)
            h.observe(value)

    # -- snapshots -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Number]:
        """Flat counters+gauges view (the original API shape — existing
        call sites and tests keep working)."""
        with self._lock:
            out: Dict[str, Number] = dict(self._stats)
            out.update(self._gauges)
            return out

    def snapshot_all(self, labels: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
        """One labeled structured snapshot — the JSONL export record."""
        with self._lock:
            return {
                "ts": time.time(),
                "labels": dict(labels or {}),
                "counters": dict(self._stats),
                "gauges": dict(self._gauges),
                "histograms": {n: h.to_dict()
                               for n, h in self._hists.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._gauges.clear()
            self._hists.clear()

    # -- JSONL exporter -------------------------------------------------------

    def flush_jsonl(self, path: Optional[str] = None,
                    labels: Optional[Dict[str, Any]] = None
                    ) -> Optional[str]:
        """Append one snapshot line to ``path`` (default: the configured
        ``FLAGS_metrics_path``). No-op (returns None) when neither is
        set — callers sprinkle this freely without gating."""
        if path is None:
            path = self._flush_path
            if path is None:
                from paddlebox_tpu.core import flags
                path = flags.flag("metrics_path") or None
        if not path:
            return None
        line = json.dumps(self.snapshot_all(labels), default=str)
        with open(path, "a") as f:
            f.write(line + "\n")
        return path

    def start_flush_thread(self, path: str,
                           interval_s: float = 30.0) -> bool:
        """Periodic background JSONL flusher (daemon). Idempotent; a
        non-positive interval means 'no thread' (pass-report flushes
        still append)."""
        with self._lock:
            self._flush_path = path
            if interval_s <= 0 or (self._flush_thread is not None
                                   and self._flush_thread.is_alive()):
                return self._flush_thread is not None
            self._flush_stop.clear()

            def loop():
                while not self._flush_stop.wait(interval_s):
                    try:
                        self.flush_jsonl(path)
                    except OSError:
                        pass

            self._flush_thread = threading.Thread(
                target=loop, name="metrics-flush", daemon=True)
            self._flush_thread.start()
            return True

    def stop_flush_thread(self) -> None:
        """Stop the flusher AND disarm the configured path (tests and
        shutdown paths use this to fully de-configure the exporter)."""
        t = self._flush_thread
        self._flush_stop.set()
        if t is not None:
            t.join(timeout=5.0)
        self._flush_thread = None
        self._flush_path = None

    def init_from_flags(self) -> bool:
        """Idempotent flag-driven setup: a non-empty FLAGS_metrics_path
        arms the exporter (and its flush thread). Returns armed."""
        from paddlebox_tpu.core import flags
        path = flags.flag("metrics_path")
        if not path:
            return self._flush_path is not None
        self.start_flush_thread(
            path, float(flags.flag("metrics_flush_interval_s")))
        return True


GLOBAL = Monitor()

add = GLOBAL.add
set_stat = GLOBAL.set
get = GLOBAL.get
snapshot = GLOBAL.snapshot
snapshot_all = GLOBAL.snapshot_all
reset = GLOBAL.reset
set_gauge = GLOBAL.set_gauge
get_gauge = GLOBAL.get_gauge
observe = GLOBAL.observe
define_histogram = GLOBAL.define_histogram
flush_jsonl = GLOBAL.flush_jsonl
start_flush_thread = GLOBAL.start_flush_thread
stop_flush_thread = GLOBAL.stop_flush_thread
init_from_flags = GLOBAL.init_from_flags
