"""Process-global metric registry: counters, gauges, histograms.

Role of ``paddle/fluid/platform/monitor.h`` (``platform::Monitor`` /
``StatRegistry`` named int64 stats) grown into a full registry: int
counters (the original API, unchanged), FLOAT gauges (so rate/ratio call
sites don't silently truncate through the int counter path), and
fixed-bucket histograms (step/dispatch latency distributions).

Thread-safe and cheap to bump from the data pipeline, trainer, and RPC
threads. A labeled ``snapshot_all()`` returns one structured view; the
JSONL exporter appends snapshot lines to ``FLAGS_metrics_path`` — one
per pass report plus a periodic background flush thread
(``FLAGS_metrics_flush_interval_s``). Telemetry is default-off: with no
metrics path configured nothing is written and the flush thread never
starts.
"""

from __future__ import annotations

import atexit
import bisect
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from paddlebox_tpu.core.quantiles import LogQuantileDigest, merge_digests

Number = Union[int, float]

# Default latency buckets (ms): exponential-ish 1ms..30s — wide enough
# for both a CPU smoke step and an axon-tunnel dispatch stall.
DEFAULT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                   1000.0, 2000.0, 5000.0, 10000.0, 30000.0)


class Histogram:
    """Fixed-bucket histogram: counts per bucket + running sum/min/max.

    Buckets are upper bounds; values above the last bound land in the
    implicit +inf bucket. Percentiles are estimated from bucket counts
    by tools/trace_report.py — the registry itself stores only O(len
    (buckets)) state no matter how many observations arrive."""

    __slots__ = ("buckets", "counts", "count", "sum", "min", "max")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(float(b) for b in buckets)
        if list(self.buckets) != sorted(set(self.buckets)):
            raise ValueError(f"histogram buckets must be strictly "
                             f"increasing: {buckets}")
        self.counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if v < self.min else self.min
        self.max = v if v > self.max else self.max

    def to_dict(self) -> Dict[str, Any]:
        return {"buckets": list(self.buckets),
                "counts": list(self.counts),
                "count": self.count,
                "sum": round(self.sum, 6),
                "min": self.min if self.count else None,
                "max": self.max if self.count else None}


class Monitor:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, Number] = {}        # counters (add/set)
        self._gauges: Dict[str, float] = {}        # float set-last-wins
        self._hists: Dict[str, Histogram] = {}
        self._digests: Dict[str, LogQuantileDigest] = {}
        self._flush_thread: Optional[threading.Thread] = None
        self._flush_stop = threading.Event()
        self._flush_path: Optional[str] = None
        self._atexit_registered = False

    # -- counters (original StatRegistry API, unchanged) -------------------

    def add(self, name: str, delta: Number = 1) -> None:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + delta

    def set(self, name: str, value: Number) -> None:
        with self._lock:
            self._stats[name] = value

    def get(self, name: str) -> Number:
        with self._lock:
            return self._stats.get(name, 0)

    # -- gauges ------------------------------------------------------------

    def set_gauge(self, name: str, value: float) -> None:
        """Float gauge: last-write-wins (rates, ratios, ms figures —
        values the int counter path would truncate)."""
        with self._lock:
            self._gauges[name] = float(value)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    # -- histograms ---------------------------------------------------------

    def define_histogram(self, name: str,
                         buckets: Sequence[float] = DEFAULT_BUCKETS
                         ) -> None:
        """Pre-declare a histogram's buckets (idempotent for identical
        buckets; re-defining with different ones raises — silently
        changing bucket bounds mid-run would corrupt the series)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                self._hists[name] = Histogram(buckets)
            elif h.buckets != tuple(float(b) for b in buckets):
                raise ValueError(
                    f"histogram {name!r} already defined with buckets "
                    f"{h.buckets}")

    def observe(self, name: str, value: float,
                buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(buckets)
            h.observe(value)

    # -- streaming quantile digests ------------------------------------------

    def observe_quantile(self, name: str, value: float,
                         rel_error: float = 0.01) -> None:
        """Feed the named log-bucketed quantile sketch (created on first
        observe). Unlike the fixed-bucket histogram, the digest needs no
        pre-chosen bounds and merges across ranks — the p50/p90/p99/p999
        source for the pass report and the serving SLO layer."""
        with self._lock:
            d = self._digests.get(name)
            if d is None:
                d = self._digests[name] = LogQuantileDigest(rel_error)
            d.observe(value)

    def quantile_digest(self, name: str
                        ) -> Optional[LogQuantileDigest]:
        """A COPY of the named digest (safe to keep as a window base for
        :meth:`LogQuantileDigest.delta`); None when never observed."""
        with self._lock:
            d = self._digests.get(name)
            return d.copy() if d is not None else None

    # -- snapshots -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Number]:
        """Flat counters+gauges view (the original API shape — existing
        call sites and tests keep working)."""
        with self._lock:
            out: Dict[str, Number] = dict(self._stats)
            out.update(self._gauges)
            return out

    def snapshot_all(self, labels: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
        """One labeled structured snapshot — the JSONL export record."""
        with self._lock:
            return {
                "ts": time.time(),
                "labels": dict(labels or {}),
                "counters": dict(self._stats),
                "gauges": dict(self._gauges),
                "histograms": {n: h.to_dict()
                               for n, h in self._hists.items()},
                "quantiles": {n: d.to_dict()
                              for n, d in self._digests.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()
            self._gauges.clear()
            self._hists.clear()
            self._digests.clear()

    # -- JSONL exporter -------------------------------------------------------

    def flush_jsonl(self, path: Optional[str] = None,
                    labels: Optional[Dict[str, Any]] = None
                    ) -> Optional[str]:
        """Append one snapshot line to ``path`` (default: the configured
        ``FLAGS_metrics_path``). No-op (returns None) when neither is
        set — callers sprinkle this freely without gating."""
        if path is None:
            path = self._flush_path
            if path is None:
                from paddlebox_tpu.core import flags
                path = flags.flag("metrics_path") or None
        if not path:
            return None
        line = json.dumps(self.snapshot_all(labels), default=str)
        with open(path, "a") as f:
            f.write(line + "\n")
        return path

    def _atexit_flush(self) -> None:
        """Final flush at interpreter exit: short-lived runs (tools,
        crash drills) must not lose their last window just because no
        pass report or flush tick landed before exit. Idempotent with
        the periodic thread — it appends one more labeled snapshot, and
        a de-configured exporter (stop_flush_thread ran) makes it a
        no-op."""
        try:
            self.flush_jsonl(self._flush_path,
                             labels={"event": "final_flush"})
        except OSError:
            pass

    def _register_atexit(self) -> None:
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self._atexit_flush)

    def start_flush_thread(self, path: str,
                           interval_s: float = 30.0) -> bool:
        """Periodic background JSONL flusher (daemon). Idempotent; a
        non-positive interval means 'no thread' (pass-report flushes
        still append). Arming the exporter also registers the one
        atexit final flush."""
        self._register_atexit()
        with self._lock:
            self._flush_path = path
            if interval_s <= 0 or (self._flush_thread is not None
                                   and self._flush_thread.is_alive()):
                return self._flush_thread is not None
            self._flush_stop.clear()

            def loop():
                while not self._flush_stop.wait(interval_s):
                    try:
                        self.flush_jsonl(path)
                    except OSError:
                        pass

            self._flush_thread = threading.Thread(
                target=loop, name="metrics-flush", daemon=True)
            self._flush_thread.start()
            return True

    def stop_flush_thread(self) -> None:
        """Stop the flusher AND disarm the configured path (tests and
        shutdown paths use this to fully de-configure the exporter)."""
        t = self._flush_thread
        self._flush_stop.set()
        if t is not None:
            t.join(timeout=5.0)
        self._flush_thread = None
        self._flush_path = None

    def init_from_flags(self) -> bool:
        """Idempotent flag-driven setup: a non-empty FLAGS_metrics_path
        arms the exporter (and its flush thread). Returns armed."""
        from paddlebox_tpu.core import flags
        path = flags.flag("metrics_path")
        if not path:
            return self._flush_path is not None
        self.start_flush_thread(
            path, float(flags.flag("metrics_flush_interval_s")))
        return True


# -- cluster-level aggregation ------------------------------------------------

def merge_snapshots(snaps: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank ``snapshot_all()`` dicts into ONE cluster-level
    snapshot (prep for multi-host: each rank keeps its own registry; a
    collector folds them so the operator reads one report, not N).

    Merge semantics per section:
    - ``counters``: summed (they are totals — bytes, passes, retries).
    - ``gauges``: arithmetic mean across the ranks that reported the
      name, plus ``<name>__max`` for skew-sensitive reads (a mean hides
      the one stalled rank; the max names it).
    - ``histograms``: bucket-wise count addition (identical bucket
      bounds required — mixed bounds raise, same as define_histogram).
    - ``quantiles``: digest merge (the whole point of the log-bucketed
      sketch — associative bucket addition, no accuracy loss).
    """
    if not snaps:
        return {"ts": time.time(), "ranks": 0, "labels": {},
                "counters": {}, "gauges": {}, "histograms": {},
                "quantiles": {}}
    counters: Dict[str, Number] = {}
    for s in snaps:
        for k, v in (s.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
    gauge_vals: Dict[str, List[float]] = {}
    for s in snaps:
        for k, v in (s.get("gauges") or {}).items():
            gauge_vals.setdefault(k, []).append(float(v))
    gauges: Dict[str, float] = {}
    for k, vs in gauge_vals.items():
        gauges[k] = sum(vs) / len(vs)
        if len(vs) > 1:
            gauges[k + "__max"] = max(vs)
    hists: Dict[str, Dict[str, Any]] = {}
    for s in snaps:
        for k, h in (s.get("histograms") or {}).items():
            cur = hists.get(k)
            if cur is None:
                hists[k] = {**h, "counts": list(h["counts"])}
                continue
            if list(cur["buckets"]) != list(h["buckets"]):
                raise ValueError(
                    f"histogram {k!r} has mismatched buckets across "
                    f"ranks — cannot merge")
            cur["counts"] = [a + b for a, b in zip(cur["counts"],
                                                   h["counts"])]
            cur["count"] += h["count"]
            cur["sum"] = round(cur["sum"] + h["sum"], 6)
            mins = [m for m in (cur["min"], h["min"]) if m is not None]
            maxs = [m for m in (cur["max"], h["max"]) if m is not None]
            cur["min"] = min(mins) if mins else None
            cur["max"] = max(maxs) if maxs else None
    digs: Dict[str, List[LogQuantileDigest]] = {}
    for s in snaps:
        for k, d in (s.get("quantiles") or {}).items():
            digs.setdefault(k, []).append(LogQuantileDigest.from_dict(d))
    quantiles = {k: merge_digests(ds).to_dict()
                 for k, ds in digs.items()}
    return {"ts": max(float(s.get("ts", 0.0)) for s in snaps),
            "ranks": len(snaps),
            "labels": dict(snaps[0].get("labels") or {}),
            "counters": counters, "gauges": gauges,
            "histograms": hists, "quantiles": quantiles}


def collect_cluster_snapshot(store, *, labels: Optional[Dict[str, Any]]
                             = None, key: str = "metrics_snapshot",
                             timeout: float = 60.0,
                             snapshot: Optional[Dict[str, Any]] = None,
                             registry: Optional["Monitor"] = None
                             ) -> Dict[str, Any]:
    """All-gather every rank's registry snapshot through a FileStore
    (``distributed.transport.FileStore`` — or anything with its
    ``all_gather(name, bytes, timeout)`` contract) and return the ONE
    merged cluster-level snapshot on every rank. Symmetric: all ranks
    must call it (it is a rendezvous). Rank 0 typically writes the
    result to the metrics JSONL with a ``{"event": "cluster_report"}``
    label."""
    reg = registry if registry is not None else GLOBAL
    mine = snapshot if snapshot is not None else reg.snapshot_all(labels)
    blobs = store.all_gather(key, json.dumps(mine, default=str).encode(),
                             timeout=timeout)
    return merge_snapshots([json.loads(b) for b in blobs])


GLOBAL = Monitor()

add = GLOBAL.add
set_stat = GLOBAL.set
get = GLOBAL.get
snapshot = GLOBAL.snapshot
snapshot_all = GLOBAL.snapshot_all
reset = GLOBAL.reset
set_gauge = GLOBAL.set_gauge
get_gauge = GLOBAL.get_gauge
observe = GLOBAL.observe
observe_quantile = GLOBAL.observe_quantile
quantile_digest = GLOBAL.quantile_digest
define_histogram = GLOBAL.define_histogram
flush_jsonl = GLOBAL.flush_jsonl
start_flush_thread = GLOBAL.start_flush_thread
stop_flush_thread = GLOBAL.stop_flush_thread
init_from_flags = GLOBAL.init_from_flags
