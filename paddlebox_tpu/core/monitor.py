"""Named global counters.

Role of ``paddle/fluid/platform/monitor.h`` (``platform::Monitor`` /
``StatRegistry`` named int64 stats, e.g. GPU memory counters). Thread-safe,
process-global, cheap to bump from the data pipeline and trainer threads.
"""

from __future__ import annotations

import threading
from typing import Dict


class Monitor:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stats: Dict[str, int] = {}

    def add(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + delta

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._stats[name] = value

    def get(self, name: str) -> int:
        with self._lock:
            return self._stats.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


GLOBAL = Monitor()

add = GLOBAL.add
set_stat = GLOBAL.set
get = GLOBAL.get
snapshot = GLOBAL.snapshot
reset = GLOBAL.reset
