"""Streaming quantile sketch: a mergeable log-bucketed digest.

Role of the quantile layer the fixed-bucket ``monitor.Histogram`` cannot
play: the histogram's bounds must be chosen up front (1ms..30s latency
buckets), so a value range it was not designed for — queue depths,
key counts, sub-millisecond RPC latencies — degrades to "everything in
one bucket". This digest is DDSketch-shaped (log-spaced buckets with a
configurable RELATIVE error): bucket ``i`` covers
``(gamma^(i-1), gamma^i]`` with ``gamma = (1+a)/(1-a)``, so any quantile
estimate is within ``a`` (default 1%) of the true value, for ANY value
range, with O(log(max/min)/a) memory and O(1) inserts.

Mergeability is the point: two digests with the same ``rel_error`` merge
by adding bucket counts (associative + commutative), so per-rank
sketches combine into one cluster-level digest
(``monitor.merge_snapshots``), and a cumulative digest supports per-pass
windows by COUNT SUBTRACTION (:meth:`delta`) — the trainer keeps one
digest per metric and reports each pass's p50/p90/p99/p999 from the
window delta, no per-pass re-allocation.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional, Sequence

# The quantile points every report surfaces (SLO vocabulary).
DEFAULT_QS = (0.5, 0.9, 0.99, 0.999)


def _q_name(q: float) -> str:
    """0.5 -> 'p50', 0.999 -> 'p999' (the SLO field-name convention)."""
    pct = q * 100.0
    if abs(pct - round(pct)) < 1e-9:
        return f"p{int(round(pct))}"
    return "p" + f"{pct:g}".replace(".", "")


class LogQuantileDigest:
    """Log-bucketed quantile sketch with a bounded relative error.

    Handles the full real line: positive values land in log buckets,
    negative values in a mirrored set, zeros in their own counter — so
    "unbounded-range" metrics (deltas, temperature-style gauges) sketch
    correctly, not just latencies.
    """

    __slots__ = ("rel_error", "_gamma", "_log_gamma", "counts",
                 "neg_counts", "zero_count", "count", "sum", "min", "max")

    def __init__(self, rel_error: float = 0.01):
        if not 0.0 < rel_error < 1.0:
            raise ValueError(f"rel_error must be in (0, 1): {rel_error}")
        self.rel_error = float(rel_error)
        self._gamma = (1.0 + rel_error) / (1.0 - rel_error)
        self._log_gamma = math.log(self._gamma)
        self.counts: Dict[int, int] = {}
        self.neg_counts: Dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- inserts -----------------------------------------------------------

    def _bucket(self, mag: float) -> int:
        return int(math.ceil(math.log(mag) / self._log_gamma))

    def _bucket_value(self, i: int) -> float:
        # Midpoint estimate 2*gamma^i/(gamma+1): the worst-case relative
        # error over the bucket's range equals rel_error exactly.
        return 2.0 * self._gamma ** i / (self._gamma + 1.0)

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = v if v < self.min else self.min
        self.max = v if v > self.max else self.max
        if v > 0.0:
            i = self._bucket(v)
            self.counts[i] = self.counts.get(i, 0) + 1
        elif v < 0.0:
            i = self._bucket(-v)
            self.neg_counts[i] = self.neg_counts.get(i, 0) + 1
        else:
            self.zero_count += 1

    # -- queries -----------------------------------------------------------

    def _ascending(self):
        """Yield (estimate, count) in ascending value order: negatives
        from most- to least-negative, zeros, positives ascending."""
        for i in sorted(self.neg_counts, reverse=True):
            yield -self._bucket_value(i), self.neg_counts[i]
        if self.zero_count:
            yield 0.0, self.zero_count
        for i in sorted(self.counts):
            yield self._bucket_value(i), self.counts[i]

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate; None on an empty digest.
        Guaranteed within ``rel_error`` (relative) of the true value."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1]: {q}")
        rank = q * (self.count - 1)
        cum = 0
        est = None
        for est, c in self._ascending():
            cum += c
            if cum > rank:
                return est
        return est  # numerical edge: q == 1.0

    def quantiles(self, qs: Sequence[float] = DEFAULT_QS
                  ) -> Dict[str, Optional[float]]:
        return {_q_name(q): self.quantile(q) for q in qs}

    # -- merge / window ----------------------------------------------------

    def _check_compatible(self, other: "LogQuantileDigest") -> None:
        if abs(other.rel_error - self.rel_error) > 1e-12:
            raise ValueError(
                f"cannot combine digests with rel_error "
                f"{self.rel_error} vs {other.rel_error}")

    def merge(self, other: "LogQuantileDigest") -> "LogQuantileDigest":
        """In-place merge (bucket-count addition — associative and
        commutative, the cluster-aggregation property). Returns self."""
        self._check_compatible(other)
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + c
        for i, c in other.neg_counts.items():
            self.neg_counts[i] = self.neg_counts.get(i, 0) + c
        self.zero_count += other.zero_count
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def copy(self) -> "LogQuantileDigest":
        d = LogQuantileDigest(self.rel_error)
        d.counts = dict(self.counts)
        d.neg_counts = dict(self.neg_counts)
        d.zero_count = self.zero_count
        d.count = self.count
        d.sum = self.sum
        d.min = self.min
        d.max = self.max
        return d

    def delta(self, base: Optional["LogQuantileDigest"]
              ) -> "LogQuantileDigest":
        """Window digest: the observations recorded since ``base`` (a
        prior :meth:`copy` of this same digest). Count subtraction —
        exact because inserts only ever add. The window's true min/max
        are not recoverable from bucket counts; the delta reports its
        quantile(0)/quantile(1) estimates instead (within rel_error)."""
        if base is None:
            return self.copy()
        self._check_compatible(base)
        d = LogQuantileDigest(self.rel_error)
        for i, c in self.counts.items():
            n = c - base.counts.get(i, 0)
            if n > 0:
                d.counts[i] = n
        for i, c in self.neg_counts.items():
            n = c - base.neg_counts.get(i, 0)
            if n > 0:
                d.neg_counts[i] = n
        d.zero_count = max(0, self.zero_count - base.zero_count)
        d.count = max(0, self.count - base.count)
        d.sum = self.sum - base.sum
        if d.count:
            d.min = d.quantile(0.0)
            d.max = d.quantile(1.0)
        return d

    # -- serialization -----------------------------------------------------

    def to_dict(self, qs: Sequence[float] = DEFAULT_QS) -> Dict:
        """JSON-safe snapshot: the merge state (bucket counts) PLUS the
        derived quantile estimates, so a consumer that only wants p99
        never needs to rebuild the digest."""
        out = {
            "rel_error": self.rel_error,
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "zero_count": self.zero_count,
            "buckets": {str(i): c for i, c in self.counts.items()},
            "neg_buckets": {str(i): c for i, c in self.neg_counts.items()},
        }
        out.update(self.quantiles(qs))
        return out

    @classmethod
    def from_dict(cls, d: Dict) -> "LogQuantileDigest":
        out = cls(float(d.get("rel_error", 0.01)))
        out.counts = {int(i): int(c)
                      for i, c in (d.get("buckets") or {}).items()}
        out.neg_counts = {int(i): int(c)
                          for i, c in (d.get("neg_buckets") or {}).items()}
        out.zero_count = int(d.get("zero_count", 0))
        out.count = int(d.get("count", 0))
        out.sum = float(d.get("sum", 0.0))
        out.min = d.get("min")
        out.max = d.get("max")
        if out.min is None:
            out.min = math.inf
        if out.max is None:
            out.max = -math.inf
        return out


def merge_digests(digests: Iterable[LogQuantileDigest]
                  ) -> Optional[LogQuantileDigest]:
    """Fold any number of compatible digests into a fresh one (None for
    an empty iterable) — the per-rank collector's reduce step."""
    out: Optional[LogQuantileDigest] = None
    for d in digests:
        if out is None:
            out = d.copy()
        else:
            out.merge(d)
    return out
