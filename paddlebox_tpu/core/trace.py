"""Thread-safe span tracer buffering Chrome-trace events.

Role of the per-stage ``platform::Timer`` blocks the reference prints via
``PrintSyncTimer`` (``fleet/box_wrapper.h:395-420``) and its nvprof range
annotations — re-expressed as a process-global tracer whose spans land in
a bounded ring buffer and export to a ``chrome://tracing`` / Perfetto
loadable JSON file (``FLAGS_trace_path``).

Design constraints (the CTR hot loop runs through here):

- **Zero hot-loop cost when disabled.** ``span()`` checks ONE cached bool
  and returns a shared ``nullcontext`` — no flag-registry lock, no
  allocation. Enabling is explicit (``enable()`` or ``init_from_flags()``
  reading ``FLAGS_trace_path``), never inferred per event.
- **Host-side only.** Spans wrap dispatch/fetch boundaries and host
  stages; nothing here may add ops or syncs to a jitted program.
- **Bounded.** Events live in a ring (``FLAGS_trace_ring_events``); a
  multi-hour run cannot OOM the host, and ``snapshot()`` hands the tail
  to crash/stall dumps (bench.py's watchdog forensics).

Distributed tracing (OBSERVABILITY.md "Distributed tracing"): a
compact TRACE CONTEXT — ``{tid, sid, origin}`` = trace id, sending
span id, origin host:pid — rides the framed RPC header
(``distributed/rpc.py``), so every server-side span across the fleet
records the trace id of the request that caused it. Context is
thread-local (``use_context``); span/trace ids come from a process
counter salted with the pid (no wall clock, no randomness — the replay
closure stays pure). Each trace file carries a WALL-CLOCK ANCHOR
(``otherData.wall_anchor_ns`` = the unix ns at ring ts 0) plus the
per-connection clock offsets measured by the RPC handshake
(``note_peer_offset``), which is what lets ``tools/trace_report.py
--merge`` stitch N per-process rings onto ONE global timeline with
cross-process flow arrows.

Usage::

    from paddlebox_tpu.core import trace
    trace.enable("/tmp/run.trace.json")
    with trace.span("pull", k=4):
        ...
    trace.export()           # or automatic at process exit
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from paddlebox_tpu.core import flags


def _json_safe(v: Any) -> Any:
    """Clamp span args to JSON scalars — a jax array or object captured
    into an event must not make the whole export unserializable."""
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    return repr(v)


class _NullSpan:
    """Shared no-op context for the disabled path (allocation-free)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()
NULL_SPAN = _NULL_SPAN   # public alias (callers pre-picking a span)

# -- distributed trace context ------------------------------------------------

# Per-thread active context: {"tid": trace id, "sid": this hop's span id,
# "origin": "host:pid" of the trace root, optional "parent": the sending
# span id}. Set by the RPC server loop for the handler's duration, by
# fan-out helpers that carry a caller's context into worker threads, and
# by the serving micro-batcher for the batch it coalesced.
_CTX = threading.local()

# Monotonic span-id source. next() on itertools.count is atomic under
# the GIL; ids are salted with the pid so two processes never collide.
_SPAN_IDS = itertools.count(1)


def _new_id() -> str:
    return f"{os.getpid():x}.{next(_SPAN_IDS):x}"


def current_context() -> Optional[Dict[str, str]]:
    """The calling thread's active trace context (None when no traced
    request is in scope — including always when tracing is off, since
    only traced RPCs install one)."""
    return getattr(_CTX, "ctx", None)


class _CtxScope:
    """Push/pop one context on the calling thread (re-entrant; restores
    whatever was active on exit, including None)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[Dict[str, str]]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_CTX, "ctx", None)
        _CTX.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _CTX.ctx = self._prev
        return False


def use_context(ctx: Optional[Dict[str, str]]):
    """``with trace.use_context(ctx): ...`` — activate a captured
    context on this thread (fan-out worker threads, the micro-batcher
    dispatcher). ``None`` is legal and deactivates for the scope."""
    return _CtxScope(ctx)


def wire_context() -> Optional[Dict[str, str]]:
    """The context an outgoing RPC should carry, or None when tracing
    is off (the one-cached-bool discipline: a disabled process attaches
    nothing and pays one attribute check). A fresh root is minted when
    no context is active — the client edge is where a trace starts."""
    if not GLOBAL._enabled:
        return None
    cur = getattr(_CTX, "ctx", None)
    sid = _new_id()
    if cur is None:
        return {"tid": _new_id(), "sid": sid,
                "origin": f"{GLOBAL.host}:{GLOBAL._pid}"}
    return {"tid": cur["tid"], "sid": sid,
            "origin": cur.get("origin", "")}


def server_context(wire_ctx: Dict[str, Any]) -> Dict[str, str]:
    """The server-side child of a context received off the wire: same
    trace id, a fresh local span id, ``parent`` = the client's span id
    (what the merge tool draws the cross-process flow arrow from)."""
    return {"tid": str(wire_ctx.get("tid", "")),
            "sid": _new_id(),
            "parent": str(wire_ctx.get("sid", "")),
            "origin": str(wire_ctx.get("origin", ""))}


class _Span:
    """One live span: records a Chrome 'X' (complete) event on exit —
    including exit-via-exception, with the exception recorded in the
    event args so a crash dump names the failing stage."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, etype, evalue, tb):
        t1 = time.perf_counter_ns()
        args = self.args
        if etype is not None:
            args = dict(args or {})
            args["error"] = f"{etype.__name__}: {evalue!r}"
        self._tracer._record("X", self.name, self._t0, args,
                             dur_ns=t1 - self._t0)
        return False


class Tracer:
    """Process-global span tracer with a bounded event ring."""

    def __init__(self, capacity: int = 65536):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, int(capacity)))
        self._enabled = False          # the ONE hot-path check
        self._path: Optional[str] = None
        self._epoch_ns = time.perf_counter_ns()
        # Wall-clock anchor: the unix ns corresponding to ring ts 0.
        # Captured back-to-back with the perf epoch so cross-process
        # merge (trace_report --merge) can place this ring on a global
        # timeline. Constructed once per process, outside any replay
        # closure.
        self._wall_anchor_ns = time.time_ns()
        self._pid = os.getpid()
        try:
            self.host = os.uname().nodename
        except (AttributeError, OSError):  # pragma: no cover - non-posix
            self.host = "localhost"
        # endpoint -> {"offset_ms", "rtt_ms"} from the RPC clock
        # handshake (rpc.FramedRPCConn): how far each peer's wall clock
        # sits from ours, embedded in the export for merge refinement.
        self._peer_offsets: Dict[str, Dict[str, float]] = {}
        self._atexit_registered = False
        self._dropped = 0

    # -- lifecycle --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, path: Optional[str] = None,
               ring_events: Optional[int] = None) -> None:
        """Turn tracing on; ``path`` (if given) is where ``export()`` and
        the process-exit hook write the Chrome trace JSON."""
        with self._lock:
            if ring_events and ring_events != self._events.maxlen:
                self._events = deque(self._events,
                                     maxlen=max(1, int(ring_events)))
            if path:
                self._path = path
            self._enabled = True
            if self._path and not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self._export_at_exit)

    def disable(self) -> None:
        self._enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def init_from_flags(self) -> bool:
        """Idempotent flag-driven enable: a non-empty ``FLAGS_trace_path``
        turns tracing on (called at pass/bench/service entry points, so
        env-set flags work without code changes). Returns enabled."""
        if not self._enabled:
            path = flags.flag("trace_path")
            if path:
                self.enable(path, int(flags.flag("trace_ring_events")))
        return self._enabled

    # -- recording --------------------------------------------------------

    def _record(self, ph: str, name: str, t_ns: int,
                args: Optional[Dict[str, Any]], dur_ns: int = 0) -> None:
        if not self._enabled:
            return  # span opened just as tracing was disabled
        th = threading.current_thread()
        ev: Dict[str, Any] = {
            "name": name, "ph": ph, "pid": self._pid,
            "tid": th.ident or 0,
            "ts": (t_ns - self._epoch_ns) / 1e3,   # Chrome wants us
        }
        if ph == "X":
            ev["dur"] = dur_ns / 1e3
        ctx = getattr(_CTX, "ctx", None)
        if ctx is not None:
            # Every span recorded under a traced request carries its
            # caller's trace id — the cross-process correlation key.
            args = dict(args or {})
            args.setdefault("trace", ctx["tid"])
        if args:
            ev["args"] = {k: _json_safe(v) for k, v in args.items()}
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(ev)

    def span(self, name: str, **args: Any):
        """``with trace.span("pull", k=4): ...`` — a null context when
        disabled, a recorded Chrome complete-event otherwise."""
        if not self._enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, **args: Any) -> None:
        """Point-in-time marker (phase transitions, watchdog ticks)."""
        if not self._enabled:
            return
        self._record("i", name, time.perf_counter_ns(), args)

    def counter(self, name: str, **values: float) -> None:
        """Chrome counter event — graphs a named value over time."""
        if not self._enabled:
            return
        self._record("C", name, time.perf_counter_ns(), values)

    # -- output -----------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """The current ring contents, oldest first — the crash/stall dump
        surface (bench watchdog, ``stall_forensics``)."""
        with self._lock:
            return list(self._events)

    def trace_object(self) -> Dict[str, Any]:
        """The full Chrome-trace JSON object (thread-name metadata +
        events) — what ``export`` serializes."""
        events = self.snapshot()
        meta = []
        seen = set()
        for th in threading.enumerate():
            if th.ident is None or th.ident in seen:
                continue
            seen.add(th.ident)
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self._pid, "tid": th.ident,
                         "args": {"name": th.name}})
        with self._lock:
            peer_offsets = {ep: dict(v)
                            for ep, v in self._peer_offsets.items()}
        return {"traceEvents": meta + events,
                "displayTimeUnit": "ms",
                "otherData": {
                    # graftlint: allow-lock(approximate stat; torn read ok)
                    "dropped_events": self._dropped,
                    # The merge anchors: unix ns of ring ts 0, this
                    # process's identity, and measured peer clock
                    # offsets (trace_report --merge).
                    "wall_anchor_ns": int(self._wall_anchor_ns),
                    "host": self.host,
                    "pid": int(self._pid),
                    "peer_offsets_ms": peer_offsets}}

    def note_peer_offset(self, endpoint: str, offset_ms: float,
                         rtt_ms: float = 0.0) -> None:
        """Record one clock-handshake result (rpc.FramedRPCConn calls
        this per connect while tracing is on)."""
        with self._lock:
            self._peer_offsets[endpoint] = {
                "offset_ms": round(float(offset_ms), 3),
                "rtt_ms": round(float(rtt_ms), 3)}

    def export(self, path: Optional[str] = None) -> str:
        """Write the Perfetto/chrome://tracing-loadable JSON file.
        Returns the path written."""
        path = path or self._path
        if not path:
            raise ValueError("no trace path: pass one or enable(path=...)")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.trace_object(), f, default=str)
        os.replace(tmp, path)
        return path

    def _export_at_exit(self) -> None:
        if self._enabled and self._path:
            try:
                self.export()
            except OSError:
                pass


GLOBAL = Tracer()

enable = GLOBAL.enable
disable = GLOBAL.disable
clear = GLOBAL.clear
enabled = lambda: GLOBAL.enabled  # noqa: E731
init_from_flags = GLOBAL.init_from_flags
span = GLOBAL.span
instant = GLOBAL.instant
counter = GLOBAL.counter
snapshot = GLOBAL.snapshot
export = GLOBAL.export
note_peer_offset = GLOBAL.note_peer_offset

# Extra stall-forensics sections contributed by other modules (the rpc
# layer registers its in-flight call table here — trace cannot import
# rpc without a cycle). Each provider must be cheap and non-raising.
_FORENSICS_PROVIDERS: Dict[str, Callable[[], Any]] = {}


def register_forensics_provider(name: str, fn: Callable[[], Any]) -> None:
    _FORENSICS_PROVIDERS[name] = fn


def stall_forensics(max_events: int = 256) -> Dict[str, Any]:
    """Post-mortem payload for a hung run: every thread's Python stack
    (faulthandler), the trace ring tail, and every registered provider
    section (e.g. ``inflight_rpcs`` — the in-flight RPC table, so a
    hang names the REMOTE it is stuck on, not just local frames).
    bench.py's watchdog embeds this in the failure JSON so an r05-style
    'no progress in phase device-probe' stall names the blocked frame,
    not just the phase."""
    import faulthandler
    import tempfile
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            stacks = f.read().splitlines()
    except Exception as e:  # noqa: BLE001 - forensics must never raise
        stacks = [f"<faulthandler failed: {e!r}>"]
    out: Dict[str, Any] = {"thread_stacks": stacks,
                           "trace_tail": GLOBAL.snapshot()[-max_events:]}
    for name, fn in _FORENSICS_PROVIDERS.items():
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 - forensics must never raise
            out[name] = f"<provider failed: {e!r}>"
    return out
