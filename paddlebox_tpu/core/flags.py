"""Typed, env-settable flag registry.

Role of the reference's gflags config core (``paddle/fluid/platform/flags.cc``:
95 exported ``FLAGS_*`` flags, PaddleBox block at ``flags.cc:956-1007``) and the
python ``get_flags``/``set_flags`` API
(``python/paddle/fluid/framework.py`` get_flags/set_flags).

Flags are declared with :func:`define_flag`, may be overridden by environment
variables named ``FLAGS_<name>`` (checked at first read), and are readable /
settable at runtime via :func:`get_flags` / :func:`set_flags`.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Union


class FlagError(Exception):
    pass


def _parse_bool(s: str) -> bool:
    v = s.strip().lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    raise FlagError(f"cannot parse {s!r} as bool")


def _parse_int(s: str) -> int:
    s = s.strip()
    try:
        # Decimal first so zero-padded values ("08") parse; fall back to
        # base-0 for hex/octal/binary literals ("0x10").
        return int(s, 10)
    except ValueError:
        return int(s, 0)


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: _parse_int,
    float: float,
    str: lambda s: s,
}


def _parse(ftype: type, raw: str, name: str) -> Any:
    try:
        return _PARSERS[ftype](raw)
    except (ValueError, FlagError) as e:
        raise FlagError(
            f"cannot parse {raw!r} as {ftype.__name__} for flag {name!r}: {e}"
        ) from None


@dataclasses.dataclass
class _Flag:
    name: str
    type: type
    default: Any
    help: str
    value: Any = None
    # Whether an explicit set_flags / env override has happened.
    explicit: bool = False
    env_checked: bool = False


class FlagRegistry:
    """Process-global registry of typed flags with env overrides."""

    def __init__(self, env_prefix: str = "FLAGS_"):
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.RLock()
        self._env_prefix = env_prefix

    def define(self, name: str, default: Any, help: str = "",
               type: Optional[type] = None) -> None:
        with self._lock:
            if name in self._flags:
                raise FlagError(f"flag {name!r} already defined")
            ftype = type if type is not None else builtins_type(default)
            if ftype not in _PARSERS:
                raise FlagError(f"unsupported flag type {ftype} for {name!r}")
            self._flags[name] = _Flag(name=name, type=ftype, default=default,
                                      value=default, help=help)

    def _resolve_env(self, f: _Flag) -> None:
        if f.env_checked:
            return
        env_name = self._env_prefix + f.name
        raw = os.environ.get(env_name)
        if raw is not None and not f.explicit:
            # Parse before marking checked: a malformed env value raises
            # FlagError on every read rather than silently degrading to the
            # default after the first failure.
            f.value = _parse(f.type, raw, f.name)
            f.explicit = True
        f.env_checked = True

    def get(self, name: str) -> Any:
        with self._lock:
            f = self._require(name)
            self._resolve_env(f)
            return f.value

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            f = self._require(name)
            if isinstance(value, str) and f.type is not str:
                value = _parse(f.type, value, name)
            if not isinstance(value, f.type) and f.type is float and isinstance(value, int):
                value = float(value)
            if not isinstance(value, f.type):
                raise FlagError(
                    f"flag {name!r} expects {f.type.__name__}, got "
                    f"{type(value).__name__}")
            f.value = value
            f.explicit = True
            f.env_checked = True

    def _require(self, name: str) -> _Flag:
        if name not in self._flags:
            raise FlagError(f"unknown flag {name!r}")
        return self._flags[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._flags)

    def describe(self, name: str) -> str:
        with self._lock:
            f = self._require(name)
            return f.help

    def defaults(self) -> Dict[str, Any]:
        """name -> declared default (not the live value)."""
        with self._lock:
            return {n: f.default for n, f in self._flags.items()}

    def validate_all(self) -> List[str]:
        """Every default must round-trip through its own env parser —
        ``_parse(type, str(default)) == default`` — so a bad default
        fails statically (graftlint's flag-hygiene pass calls this at
        review time) instead of at the first env override. Returns a
        list of error strings; empty = all defaults sound."""
        errors: List[str] = []
        with self._lock:
            for f in self._flags.values():
                if not isinstance(f.default, f.type) or (
                        f.type is not bool
                        and isinstance(f.default, bool)):
                    errors.append(
                        f"flag {f.name!r}: default {f.default!r} is "
                        f"{type(f.default).__name__}, declared "
                        f"{f.type.__name__}")
                    continue
                try:
                    rt = _parse(f.type, str(f.default), f.name)
                except FlagError as e:
                    errors.append(
                        f"flag {f.name!r}: default {f.default!r} does "
                        f"not parse under its env parser: {e}")
                    continue
                if rt != f.default:
                    errors.append(
                        f"flag {f.name!r}: default {f.default!r} "
                        f"round-trips to {rt!r} — an env override of "
                        "the documented default would change behavior")
        return errors


def builtins_type(v: Any) -> type:
    if isinstance(v, bool):
        return bool
    if isinstance(v, int):
        return int
    if isinstance(v, float):
        return float
    if isinstance(v, str):
        return str
    raise FlagError(f"cannot infer flag type from {v!r}")


GLOBAL = FlagRegistry()


def define_flag(name: str, default: Any, help: str = "",
                type: Optional[type] = None) -> None:
    GLOBAL.define(name, default, help, type)


def get_flags(names: Union[str, Sequence[str]]) -> Dict[str, Any]:
    """Read one or many flags; mirrors paddle's ``get_flags`` signature."""
    if isinstance(names, str):
        names = [names]
    return {n: GLOBAL.get(n) for n in names}


def set_flags(values: Dict[str, Any]) -> None:
    """Set many flags; mirrors paddle's ``set_flags`` signature."""
    for k, v in values.items():
        GLOBAL.set(k, v)


def validate_all() -> List[str]:
    """Round-trip every registered default through its env parser (see
    :meth:`FlagRegistry.validate_all`). Called by graftlint's
    flag-hygiene pass and tests/test_core.py."""
    return GLOBAL.validate_all()


def pallas_kernels_enabled() -> bool:
    """True when auto-selection may pick a Pallas kernel: TPU backend
    AND the enable_pallas_kernels master switch. One predicate for every
    kernel gate (lookup scatter, flash attention, seqpool-CVM)."""
    import jax
    return jax.default_backend() == "tpu" and bool(
        flag("enable_pallas_kernels"))


def enable_compilation_cache() -> str:
    """Point jax's persistent compilation cache at the ONE shared
    location (env default — an operator override wins). Must run before
    jax initializes a backend; this module imports no jax, so callers
    (bench.py, the dryrun child env) can use it pre-import. Returns the
    directory."""
    d = os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/jax_comp_cache"))
    return d


def flag(name: str) -> Any:
    """Scalar read shorthand used on hot paths."""
    return GLOBAL.get(name)


# ---------------------------------------------------------------------------
# Built-in flags. These mirror the *roles* of the reference's PaddleBox flag
# block (``platform/flags.cc:956-1007``) re-expressed for the TPU runtime.
# ---------------------------------------------------------------------------

define_flag("v", 0, "global VLOG verbosity level (role of glog FLAGS_v)")
define_flag("check_nan_inf", False,
            "scan train-step outputs for NaN/Inf and abort the pass "
            "(role of FLAGS_check_nan_inf + nan_inf_utils_detail)")
define_flag("enable_pallas_kernels", True,
            "use Pallas TPU kernels for hot ops where available; "
            "fall back to pure-XLA lowering when False (or on CPU tests)")
define_flag("embedding_shard_slack", 1.3,
            "over-allocation factor for per-shard bucket capacity in the "
            "sparse pull/push all-to-all (static-shape padding headroom)")
define_flag("embedding_dedup", True,
            "merge duplicate ids BEFORE the pull/push all-to-all: only the "
            "first occurrence of each id consumes a bucket cell and "
            "duplicate grads sum into that cell pre-exchange (role of "
            "dedup_keys_and_fillidx + dynamic_merge_grad, heter_comm.h:69,"
            "192); hot keys can no longer overflow a shard bucket")
define_flag("embedding_auto_capacity", False,
            "size the pull/push bucket capacity from the MEASURED "
            "per-shard unique-id maximum of each pass's first batch "
            "(x shard slack, pow2-bucketed so steady-state passes reuse "
            "the compiled step) instead of the n-based binomial bound — "
            "removes the unique_frac guesswork on duplicate-heavy data; "
            "a later batch exceeding the measured headroom degrades to "
            "counted drops, surfaced by lookup_overflow")
define_flag("embedding_unique_frac", 1.0,
            "expected unique fraction of per-device ids, used to size the "
            "per-shard bucket capacity when embedding_dedup is on (1.0 = "
            "assume all unique, always safe; CTR batches typically dedup "
            "2-4x, so 0.5 halves the all-to-all bytes). Overflowing ids "
            "degrade to counted drops, never corruption")
define_flag("trainer_prefetch_depth", 2,
            "bounded queue depth for the train-pass host-map producer "
            "thread (batches packed ahead of the device)")
define_flag("trainer_steps_per_dispatch", 1,
            "fuse K train/eval steps into ONE scanned XLA dispatch "
            "(lax.scan megastep): the pass loop pays one host dispatch "
            "and at most one host sync per K steps instead of per step "
            "— the amortization that matters when the host link is "
            "high-latency (the axon tunnel pays ~ms per dispatch). "
            "1 = per-step dispatch (legacy behavior); "
            "dense_sync_mode='async' (host dense table needs per-step "
            "pull/push) and FLAGS_profile_trainer (per-step timing) "
            "force 1 with a logged note")
define_flag("embedding_exchange_dtype", "f32",
            "wire dtype of the sparse pull-reply and push-gradient "
            "all_to_all payloads: 'f32' (exact, default), 'bf16' "
            "(halves the ICI exchange bytes on top of dedup — "
            "EQuARX-style reduced-precision exchange; accumulation and "
            "the table stay f32), or 'int8' (quarters them: symmetric "
            "per-block quantization with f32 scales riding a second "
            "small all_to_all — block width embedding_quant_block; "
            "grads still merge sender-side in f32 and widen back "
            "before the owner-side accumulate). Row/request exchanges "
            "stay int32 either way")
define_flag("pass_table_pow2_rows", 1,
            "round each pass table's rows-per-shard up to a power of two "
            "so consecutive passes with different key counts reuse the "
            "compiled train step (1 recompile per size DOUBLING instead "
            "of every pass; costs <=2x table HBM in the worst case)")
define_flag("padbox_max_shuffle_wait_count", 16,
            "max concurrent sends per rank in the cross-node shuffle "
            "exchange (flow-control window — role of "
            "FLAGS_padbox_max_shuffle_wait_count; transport.py)")
define_flag("xbox_quant_bits", 0,
            "xbox serving-export embedding quantization: 0 = float32, "
            "8/16 = symmetric per-row int8/int16 with an f32 scale "
            "(role of the reference's quantized pull values, "
            "fused_seqpool_cvm_op.cu:247 quant_ratio — applied at the "
            "export boundary; w and the serving math stay float)")
define_flag("flash_block_q", 512,
            "flash-attention q-tile rows (Pallas kernel); tuned per "
            "hardware by tools/tune_flash_blocks.py — override via "
            "FLAGS_flash_block_q without touching call sites")
define_flag("flash_block_k", 512,
            "flash-attention k-tile columns (see flash_block_q)")
define_flag("sparse_scatter_kernel", "auto",
            "push-side scatter-accumulate backend: 'auto' (Pallas sorted "
            "kernel on TPU, XLA scatter elsewhere), 'pallas', 'interpret' "
            "(Pallas interpreter — tests), or 'xla'")
define_flag("sparse_gather_kernel", "auto",
            "pull-side table-row gather backend: 'auto' (Pallas sorted-"
            "stream kernel on TPU, XLA gather elsewhere), 'pallas', "
            "'interpret' (Pallas interpreter — tests), or 'xla'; the "
            "kernel shares one argsort per width group with the push "
            "scatter (embedding/lookup.py compute_bucketing)")
define_flag("pass_split_build", True,
            "device-tier split-key early build: gather the next pass's "
            "NOT-shared rows (and insert its unseen keys) from the "
            "resident store WHILE the active pass trains — only the "
            "shared-key remainder waits for the write-back (role of the "
            "double-buffered build threads, ps_gpu_wrapper.cc:907, "
            "extended to the HBM tier). False = the r04 serial build "
            "(the whole gather waits on end_pass)")
define_flag("pass_boundary_fuse", "auto",
            "compile the pass boundary — previous pass's end_pass "
            "scatter + next pass's shared-remainder gather — into ONE "
            "jitted device program: 'auto'/'on' fuse whenever a split "
            "early build is ready at end_pass (one PJRT dispatch "
            "crosses the host link per boundary instead of two — the "
            "ms-class axon tunnel pays per dispatch), 'off' keeps the "
            "two-dispatch boundary (scatter, then merge in the builder)")
define_flag("keymap_lookup_threads", 0,
            "worker threads sharding the per-batch feasign->row keymap "
            "lookup in the NUMPY fallback (searchsorted releases the "
            "GIL, so threads genuinely parallelize the ~426K-id batch "
            "map); 0 = auto (min(4, cores/2) for batches >= 64K ids, "
            "single-threaded below). The native keymap parallelizes "
            "internally and ignores this")
define_flag("trainer_map_ahead", True,
            "run the host keymap lookup of batch i+1 on a dedicated "
            "worker while the prefetch producer packs + transfers "
            "batch i — takes the CopyKeys host map off the prefetch "
            "critical path entirely (it was already off the DEVICE "
            "path via the producer thread). False = map inline in the "
            "producer (r07 behavior)")
define_flag("ingest_workers", 0,
            "worker PROCESSES for dataset load: file blocks parse into "
            "ColumnarChunk CSR arrays in child processes (native C++ "
            "parser, or the vectorized numpy bulk parse when no native "
            "lib) and hand off through zero-copy shared-memory frames — "
            "the GIL-bound thread-reader path cannot use more than one "
            "core for the python parse. 0 (default) = the in-process "
            "thread reader; ignored when an instance-scoped parser_fn "
            "is set (closures don't cross process boundaries)")
define_flag("ingest_file_retries", 1,
            "times a file whose ingest worker DIED mid-parse (SIGKILL/"
            "OOM) is requeued onto a fresh worker before the load fails; "
            "chunks commit only at file completion, so a retry never "
            "duplicates rows. Worker-raised errors (bad data, failing "
            "pipe_command) are never retried — they would fail again")
define_flag("ingest_key_runs", True,
            "dedup each loaded chunk's keys into per-slot sorted runs "
            "DURING ingest and serve pass_keys() as a linear k-way "
            "merge of those runs (the sorted-run store build feed) "
            "instead of one end-of-load sort over every id. False = the "
            "r02 behavior (np.unique at feed time); results are "
            "bit-identical either way")
define_flag("wuauc_spill_records", 4_000_000,
            "per-user-AUC raw records held in RAM before spilling to "
            "uid-hash bucket files on disk (bounds eval-pass host memory; "
            "role of the WuAucMetricMsg shuffle/sort spill)")
define_flag("auc_num_buckets", 1 << 20,
            "prediction histogram buckets for exact distributed AUC "
            "(role of BasicAucCalculator _table size, metrics.cc:33)")
define_flag("profile_trainer", False,
            "per-op/per-stage timing in the trainer hot loop "
            "(role of TrainFilesWithProfiler)")
define_flag("trace_path", "",
            "write a chrome://tracing / Perfetto-loadable span trace to "
            "this path (empty = tracing off, the default; spans wrap "
            "host stage/dispatch/fetch boundaries only — never ops "
            "inside the jitted step). Exported at process exit and on "
            "core.trace.export()")
define_flag("trace_ring_events", 65536,
            "bounded ring-buffer capacity of the span tracer (oldest "
            "events drop first; bounds host memory on multi-hour runs "
            "and sizes the stall-forensics tail)")
define_flag("metrics_path", "",
            "append metric-registry snapshots (counters / gauges / "
            "histograms) as JSON lines to this path (empty = exporter "
            "off, the default). One line per pass report plus the "
            "periodic flush thread")
define_flag("metrics_flush_interval_s", 30.0,
            "period of the metrics JSONL background flush thread "
            "(<= 0 disables the thread; pass reports still append)")
define_flag("fault_spec", "",
            "deterministic fault-injection spec: ';'-separated "
            "'<site>[:hit=N][:times=M]:<raise=Exc|delay_ms=X|kill[=SIG]>'"
            " clauses (empty = injection off, the default — faultpoints "
            "are one cached-bool no-ops). See core/faults.py and "
            "ROBUSTNESS.md")
define_flag("pass_max_retries", 2,
            "max pass-level retries after a TRANSIENT train_pass failure "
            "(IO/connection/timeout/stall): each retry cancels pending "
            "builds, rolls the sparse store + dense state back to the "
            "last published record, and replays the pass — bit-identical "
            "to an unfailed run. Fatal errors (bad data, NaN loss, code "
            "bugs) never retry. 0 disables the self-healing loop")
define_flag("pass_retry_backoff_s", 0.5,
            "base of the capped exponential backoff between pass retries "
            "(sleep = base * 2^(attempt-1), capped by "
            "pass_retry_backoff_max_s)")
define_flag("pass_retry_backoff_max_s", 30.0,
            "cap on the pass-retry backoff sleep")
define_flag("stall_timeout_s", 0.0,
            "abort the current pass when the training heartbeat "
            "(per-block dispatch progress) stalls for this many seconds: "
            "stall forensics (all-thread stacks + trace ring tail) land "
            "in the log and StallError is raised in the training thread "
            "so the pass retries through the normal rollback machinery. "
            "<= 0 disables (default)")
define_flag("rpc_max_retries", 3,
            "max reconnect-and-retry attempts for IDEMPOTENT "
            "FramedRPCConn methods after a connection failure "
            "(pull/stats-class reads — the caller declares which methods "
            "are idempotent); non-idempotent methods never retry (the "
            "request may have executed)")
define_flag("rpc_retry_backoff_s", 0.05,
            "base of the capped exponential backoff between RPC retries "
            "(sleep = base * 2^(attempt-1), capped at 2s)")
define_flag("serving_slo_p99_ms", 0.0,
            "serving predict-latency SLO target in ms: every predict RPC "
            "whose server-side latency exceeds it bumps the "
            "slo/violations counter, and handle_stats reports the "
            "p50/p90/p99/p999 latency quantiles against it so the "
            "operator reads margin, not just breaches. <= 0 disables "
            "(default) — quantiles are still recorded")
define_flag("serving_batch_window_ms", 2.0,
            "server-side ragged micro-batching window: concurrent "
            "predict RPCs enqueue parsed rows and a dispatcher thread "
            "drains everything waiting every this-many ms (or earlier "
            "at serving_batch_max_rows) into ONE packed device forward "
            "— the request-coalescing that turns N per-RPC dispatches "
            "into one ragged dispatch. 0 = dispatch as soon as the "
            "queue is non-empty (still coalesces whatever arrived "
            "together); < 0 = batching off, every RPC packs and "
            "dispatches inline (the pre-r14 path)")
define_flag("serving_batch_max_rows", 4096,
            "dispatch a serving micro-batch early once this many rows "
            "are waiting (bounds the packed batch's device shape and "
            "the head-of-line wait under burst load); also the "
            "per-request row ceiling when it exceeds the feed batch "
            "size")
define_flag("serving_hbm_rows", 0,
            "serving-table hot-tier capacity in rows: a model with more "
            "xbox rows than this serves through the hierarchical cache "
            "(hot rows in HBM, warm in a host-RAM CLOCK cache, cold on "
            "the ssd tier) with misses batch-promoted toward HBM by "
            "access frequency off the predict critical path. 0 "
            "(default) = whole table device-resident, no tiering")
define_flag("serving_host_cache_rows", 0,
            "warm host-RAM tier capacity (rows) of the tiered serving "
            "table; rows evicted from it spill to the ssd/disk tier. "
            "0 = unbounded host RAM (disk tier never used)")
define_flag("serving_cache_dir", "",
            "directory backing the tiered serving table's cold tier "
            "(DiskShards buckets); empty = a per-predictor temp dir")
define_flag("serving_publisher_poll_s", 1.0,
            "donefile poll interval of the serving publisher thread "
            "(serving/publisher.py): how often a replica checks the "
            "training day loop's donefile for freshly published "
            "per-pass deltas to hot-swap via apply_update")
define_flag("serving_rps_window_s", 30.0,
            "sliding window for the serving throughput_rps gauge/stat "
            "(computed from LogQuantileDigest.delta() counts over "
            "rotating window snapshots — an idle replica decays to 0 "
            "instead of reporting lifetime-average rate)")
define_flag("fleet_vnodes", 64,
            "virtual nodes per replica on the fleet router's consistent-"
            "hash ring (serving/router.py): more vnodes = smoother key "
            "spread and smaller remap on join/leave, at O(vnodes * "
            "replicas) ring memory")
define_flag("fleet_health_interval_s", 0.5,
            "fleet router health-check cadence: the health thread polls "
            "every replica's stats RPC this often, drives the SLO "
            "admission window, and adopts elastic membership changes "
            "(join/leave) between polls")
define_flag("fleet_health_fails", 2,
            "consecutive health-check failures before the fleet router "
            "ejects a replica from the ring (a routed predict that hits "
            "a dead connection re-routes immediately and counts one "
            "strike — ejection never waits for a full predict to fail "
            "this many times)")
define_flag("fleet_spillover_inflight", 8,
            "per-replica in-flight predict ceiling for hash-affinity "
            "routing: past it the router spills the request to the "
            "least-loaded healthy replica (cache affinity yields to "
            "load under key skew); a replica whose SLO admission "
            "tripped sheds its overflow to the degraded path instead")
define_flag("fleet_slo_window_s", 5.0,
            "SLO admission window of the fleet router: per-replica "
            "slo/violations deltas are read per health poll and summed "
            "over this window; tripping fleet_slo_trip within it moves "
            "the replica to DEGRADED admission, and one clean window "
            "restores it")
define_flag("fleet_slo_trip", 3,
            "slo/violations within one fleet_slo_window_s that trips a "
            "replica into DEGRADED admission (its overflow beyond "
            "fleet_spillover_inflight is served by the degraded "
            "HBM-hot-rows-only path, flagged degraded=true, instead of "
            "queueing)")
define_flag("embedding_quant_block", 128,
            "values per scale block of the int8 exchange wires: both "
            "the single-host all_to_all payloads "
            "(embedding_exchange_dtype=int8) and the cross-host shard "
            "pull/push (multihost_wire_dtype=int8) carry one f32 "
            "absmax/127 scale per `block` consecutive payload values "
            "(EQuARX-style per-block quantization; a payload row "
            "narrower than the block degrades to one per-row scale)")
define_flag("multihost_wire_dtype", "f32",
            "emb payload dtype of the cross-host shard pull/push DCN "
            "wire (multihost/shard_service.py): 'f32' (exact, default "
            "— the 2-host drill pins bit-parity with single-host), "
            "'f16', or 'int8' (per-block scales via "
            "embedding_quant_block; receivers widen to f32 before "
            "anything accumulates or persists). Optimizer state, "
            "w/show/click, and reshard row moves always travel f32")
define_flag("filestore_chunk_bytes", 1 << 24,
            "FileStore set() payloads above this many bytes split into "
            "numbered chunk files behind an atomic manifest (get() "
            "reassembles transparently) — a multi-MB rank-table or "
            "gathered cluster snapshot can never exceed one framed "
            "message or one atomic-rename window. <= 0 disables "
            "chunking")
define_flag("stream_pass_events", 0,
            "streaming ingest (stream/source.py): close an incremental "
            "pass once this many events (log lines) have accumulated "
            "across pending files — the count half of the sub-day pass "
            "carve. 0 = no count bound (passes close on the time "
            "window, a day change, or an explicit flush)")
define_flag("stream_pass_window_s", 60.0,
            "streaming ingest: close the open incremental pass once its "
            "OLDEST pending event (file mtime) is this many seconds old "
            "even if stream_pass_events has not been reached — the "
            "freshness bound that keeps a trickle of traffic from "
            "sitting unconsumed. <= 0 disables the time trigger")
define_flag("stream_poll_s", 1.0,
            "sleep between streaming source polls in "
            "StreamRunner.run() when a poll carved nothing (the idle "
            "cadence of the files-as-stream tailer; tests and bench "
            "drive poll_once() directly and never sleep)")
define_flag("table_decay_rate", 0.0,
            "show/click decay applied by every store variant's "
            "shrink() at the day boundary (role of the reference's "
            "show_click_decay_rate in ShrinkTable). 0 (default) = use "
            "the TableConfig.show_click_decay the model was built with; "
            "> 0 overrides it fleet-wide without rebuilding configs")
define_flag("table_ttl_days", 0,
            "feature TTL (role of delete_after_unseen_days): a row "
            "whose unseen_days counter — bumped by every shrink(), "
            "reset to 0 by any training write-back of that key — "
            "EXCEEDS this many days is evicted at the day-boundary "
            "shrink, bounding store growth under infinite traffic. "
            "0 disables TTL eviction (default)")
define_flag("table_min_show", 0.0,
            "floor on the min_show eviction threshold applied by "
            "shrink() (role of the reference's delete_threshold): the "
            "effective threshold is max(caller's min_show, this flag), "
            "so the lifecycle can be turned on fleet-wide without "
            "touching DayRunner call sites. 0 = no floor (default)")
define_flag("multihost_replicas", 1,
            "replication factor of the multi-host shard tier: each key "
            "range keeps 1 primary + (R-1) backup copies on DISTINCT "
            "hosts (ring placement — slot i's backups are the next "
            "hosts). Writes apply on the primary and forward "
            "synchronously to backups (a briefly-disconnected backup "
            "catches up from the primary's sequence-numbered delta "
            "journal instead of a full range copy); pure reads fail "
            "over to any live replica. 1 (default) = no replication — "
            "bit-identical to the pre-replication tier")
define_flag("multihost_journal_entries", 256,
            "per-range cap on the primary's delta-journal length "
            "(entries, each one push/apply/shrink mutation): a backup "
            "whose lag exceeds the journal window catches up with a "
            "full range snapshot instead of deltas — the bound that "
            "keeps journal memory and catch-up work finite. <= 0 "
            "disables journaling (every catch-up is a full copy)")
define_flag("multihost_overlap_exchange", True,
            "run the multi-host boundary exchange on a background "
            "worker (multihost/store.py): end_pass pushes and the "
            "split-build early pulls overlap the next pass's training "
            "instead of serializing with the boundary; only the "
            "shared-key remainder (plus the rows the pending pass "
            "needs back — the priority slice of the push) waits. "
            "Pushes are full-row overwrites keyed by the cached owner "
            "plan, so overlap ordering cannot change results. False = "
            "every pull/push synchronous in the caller (the "
            "pre-overlap wire, bit-identical either way)")
define_flag("dense_allreduce_dtype", "f32",
            "wire dtype of the dense-grad cross-replica sync "
            "(parallel/collective.py quantized_psum): 'f32' (exact "
            "lax.psum, default — bit-parity pinned), 'bf16' (halve "
            "the wire, stochastic-free cast), or 'int8' (EQuARX-style "
            "per-block absmax quantize -> scatter -> f32 "
            "dequant-accumulate -> gather; per-block scales via "
            "embedding_quant_block). Under a hierarchical ici+dcn "
            "mesh only the DCN hop narrows; the ICI hop stays f32")
define_flag("dense_zero", "off",
            "ZeRO-1/2 placement of the trainer's dense optimizer state "
            "(parallel/zero.py over the data-parallel axis): 'off' "
            "(default) replicates it on every device (the pre-ZeRO "
            "layout); 'shard' places each state leaf with zero_shardings "
            "and the step updates only the local param shard before an "
            "all-gather — f32 math is bit-identical to replicated while "
            "per-device state HBM drops to ~1/dp; 'offload' routes the "
            "update through OffloadedOptimizer so the state lives in "
            "host (pinned_host) memory between steps — HBM holds ~zero "
            "optimizer bytes at the cost of host-link traffic per step "
            "(requires dense_sync_mode='step'). 'shard' degrades to "
            "'off' under dense_sync_mode='kstep': k-step state is "
            "worker-local (intentionally divergent), so there is no "
            "redundant replica to shard away")
define_flag("dense_zero_min_size", 2048,
            "smallest dense leaf (elements) that FLAGS_dense_zero "
            "shards/offloads; smaller leaves stay replicated in HBM "
            "(gather latency and per-leaf transfer overhead would "
            "dominate their few bytes). Lower it to 0 to shard "
            "everything — what the parity tests do on toy models")
define_flag("table_slot_placement", "fused",
            "column layout of DeviceFeatureStore's persistent HBM "
            "table: 'fused' (default) keeps one [rows, D+3+Ke+Kw] "
            "array (the pre-split layout); 'split' carves the "
            "emb_state/w_state optimizer-slot columns into a sibling "
            "[rows, Ke+Kw] array so the hot array is exactly (D+3)*4 "
            "bytes/row — serving-tier capacity bounded by value bytes; "
            "'host' additionally pins the slot array to host memory "
            "(pinned_host via zero_shardings memory_kind) with "
            "transient HBM crossings around the pass-boundary "
            "push/pull. All three serve bit-identical payloads and "
            "write the same checkpoint/wire format — a checkpoint "
            "saved under one placement loads under any other")
define_flag("reshard_chunk_rows", 65536,
            "row window of the bounded-memory reshard/repair COPY walk "
            "(multihost/reshard.py + replica snapshots): pull_range / "
            "replica_snapshot move at most this many rows per RPC, "
            "pipelined two windows in flight (pull chunk k+1 while "
            "chunk k applies), each chunk an idempotent full-row "
            "overwrite so kill -9 drills carry over unchanged. <= 0 = "
            "whole-range single-shot moves (the pre-chunking wire)")
define_flag("stream_tail_bytes", False,
            "streaming ingest: tail-consume log files still being "
            "APPENDED — the source tracks a durable per-file byte "
            "offset, carves complete-line byte ranges "
            "('path@@start-end' manifest entries) instead of waiting "
            "for the whole segment to be atomically renamed, and "
            "resumes mid-file after kill -9 with no event lost or "
            "duplicated. False (default) = whole-segment mode "
            "(files must appear via write-tmp-then-rename)")
define_flag("quality_collect", False,
            "model-quality & data-health observatory (core/quality.py): "
            "per-slot input health collected on the ingest chunk path, "
            "per-pass COPC/calibration tracking rebinned from the AUC "
            "histogram, drift alarms (quality/alarms/<kind>) and ONE "
            "quality_report line beside each pass_report. Host-side "
            "only — the jitted step is unchanged (test_quality pins "
            "it). False (default) = collection off; the pass report's "
            "headline copc/bucket_error fields are free and always on")
define_flag("quality_sample_rate", 0.0,
            "serving-side sampled calibration: fraction of rid-carrying "
            "predict RPCs whose predictions are logged for a late "
            "label join (deterministic crc32-of-rid selection, no "
            "RNG). 0 (default) disables serving quality sampling")
define_flag("quality_join_window_s", 300.0,
            "bounded pending window of the serving prediction+label "
            "join: a sampled request whose labels have not arrived "
            "within this many seconds expires COUNTED "
            "(quality/label_join_expired), never crashes the join")
define_flag("quality_join_pending", 65536,
            "max sampled requests held pending a label join; beyond it "
            "the oldest entries expire counted (bounds serving host "
            "memory under a label-feed outage)")
define_flag("quality_min_events", 256,
            "joined label rows per serving calibration window: every "
            "this-many joined rows the window's COPC/calibration error "
            "is evaluated against the drift baseline")
define_flag("quality_baseline_passes", 8,
            "previous-N-pass window behind each quality drift baseline "
            "(the EWMA updates over it; alarms compare the new pass "
            "against the baseline built from prior passes only)")
define_flag("quality_warmup_passes", 3,
            "observed passes of a metric before its drift alarms may "
            "fire — early training legitimately moves calibration, and "
            "a baseline of one pass is noise")
define_flag("quality_copc_tol", 0.25,
            "relative COPC (actual ctr / predicted ctr) deviation from "
            "the EWMA baseline that raises quality/alarms/copc — the "
            "within-one-pass calibration-drift trip wire")
define_flag("quality_copc_band", 0.0,
            "absolute |COPC - 1| band that raises quality/alarms/copc "
            "immediately, no baseline needed (a calibrated CTR model "
            "targets COPC 1.0). 0 (default) = band check off — early "
            "training sits far from 1 by construction")
define_flag("quality_calibration_tol", 0.5,
            "relative RISE of the bucket calibration error over its "
            "EWMA baseline (and past a 0.01 absolute floor) that "
            "raises quality/alarms/calibration")
define_flag("quality_coverage_drop", 0.5,
            "relative DROP of a slot's example coverage vs its EWMA "
            "baseline (and past a 0.01 absolute floor) that raises "
            "quality/alarms/slot_dark — the slot-went-dark trip wire")
define_flag("quality_churn_max", 0.0,
            "pass-over-pass key churn (fraction of a slot's keys unseen "
            "last pass) above which quality/alarms/churn raises; "
            "suppressed for the first pass after a day rollover (the "
            "per-day key window slides by design). 0 (default) = off")
define_flag("rpc_mux", True,
            "negotiate the multiplexed v2 wire on connect (one "
            "wire_caps probe per connect): frames carry an in-flight "
            "request id so ONE socket serves N outstanding calls "
            "(call_async/futures) and the per-replica conn pools "
            "collapse to one mux'd conn. A peer that does not answer "
            "the probe keeps the blocking v1 protocol — mixed-version "
            "clusters interoperate per-connection. False = always "
            "speak v1 (the pre-r21 one-RTT-per-call plane)")
define_flag("rpc_worker_threads", 4,
            "bounded worker-pool size of the event-loop FramedRPCServer: "
            "device-touching/blocking handlers (pull, push, predict) "
            "dispatch to at most this many worker threads per server "
            "while cheap handlers (stats, clock_probe, metrics_snapshot, "
            "contains) run inline on the poller thread")
define_flag("rpc_sg_min_bytes", 4096,
            "ndarray payload bytes above which a v2 frame switches to "
            "the zero-copy scatter/gather encoding: arrays ride as "
            "64B-aligned trailing segments sent via sendmsg (no "
            "payload-sized join copy) and are received into the "
            "frame's preallocated buffer (decoded as views, no "
            "intermediate copy). < 0 disables SG frames (mux frames "
            "still carry request ids)")
define_flag("rpc_shm", False,
            "co-located-process shortcut for SG array frames: when "
            "both peers sit on the loopback interface, array segments "
            "ride a one-shot shared-memory block (name on the wire, "
            "receiver attaches/unlinks) instead of the socket. "
            "Off by default — a receiver that dies between frame and "
            "attach leaks the segment until sweep_orphans")
define_flag("rpc_shm_min_bytes", 65536,
            "ndarray payload bytes above which an shm-eligible frame "
            "(FLAGS_rpc_shm, loopback peer) actually uses the shared-"
            "memory path; smaller payloads stay on the socket where "
            "the segment setup cost would dominate")
define_flag("multihost_coalesce_window_ms", 0.0,
            "shard-server coalescing window for concurrent pull/"
            "pull_serving requests: requests for the same slot arriving "
            "within the window merge into ONE union-key store lookup "
            "(the serving micro-batcher pattern applied to the shard "
            "tier; results scatter back per request, bit-identical to "
            "serial). 0 (default) = opportunistic — no added latency, "
            "merge only what queued while the previous lookup ran; "
            "< 0 disables coalescing entirely")
define_flag("rpc_retry_deadline_s", 30.0,
            "overall wall-clock deadline across an idempotent call's "
            "retries: when exceeded the last connection error raises "
            "even if attempts remain (a PS blip should cost ms, not "
            "minutes of blind retry)")
define_flag("history_interval_s", 0.0,
            "metric-history sampler cadence (core/timeseries.py): every "
            "interval one bounded ring point is taken per registered "
            "registry (counter deltas, gauge last-values, digest window "
            "deltas) — the trend source for burn-rate alerts, fleet_top "
            "sparklines and incident bundles. 0 (default) = sampler off; "
            "alerts_enable arms a 5s fallback cadence")
define_flag("history_points", 360,
            "metric-history ring retention in points per registry "
            "(core/timeseries.py): oldest points fall off — 360 points "
            "at a 10s cadence is one hour of trend per process")
define_flag("alerts_enable", False,
            "arm the declarative SLO alert engine (core/alerts.py): the "
            "default rule pack evaluates multi-window burn rates off the "
            "metric history every sampler tick; active alerts surface "
            "via the alerts_active RPC, alert/<name> counters and one "
            "alert_report log line")
define_flag("alerts_fast_window_s", 60.0,
            "fast burn-rate window (core/alerts.py): a rule whose fast-"
            "window value breaches goes PENDING; fast AND slow breach "
            "goes FIRING — the fast window catches the step change")
define_flag("alerts_slow_window_s", 300.0,
            "slow burn-rate window (core/alerts.py): confirms a fast-"
            "window breach is sustained before FIRING, and must come "
            "back clean before an alert RESOLVES")
define_flag("alerts_clear_windows", 2,
            "hysteresis (core/alerts.py): consecutive clean evaluations "
            "(fast AND slow windows healthy) before a FIRING alert "
            "transitions to RESOLVED — one noisy good sample must not "
            "flap a page")
define_flag("alerts_violations_per_s", 0.0,
            "SLO error-budget burn threshold for the slo/violations "
            "counter (core/alerts.py default rule pack): sustained "
            "violations-per-second at or above this rate in both burn "
            "windows pages. 0 (default) disables the rule")
define_flag("alerts_replica_lag", 0.0,
            "page threshold for the multihost/replica_lag_p99 gauge "
            "(journal entries a replica trails the primary); 0 "
            "(default) disables the rule")
define_flag("alerts_freshness_p99_ms", 0.0,
            "warn threshold for the stream/event_to_servable_ms window "
            "p99 (event-to-servable freshness SLO); 0 (default) "
            "disables the rule")
define_flag("alerts_overlap_floor", 0.0,
            "warn floor for pass/train_boundary_exchange_overlap_frac: "
            "a sustained drop below the floor means the PR-17 boundary-"
            "exchange overlap stopped hiding DCN time; 0 (default) "
            "disables the rule")
define_flag("incident_dir", "",
            "directory for incident flight-recorder bundles "
            "(core/incident.py): a FIRING page alert, watchdog stall, "
            "replica eject or STALE_PRIMARY burst writes one atomically-"
            "renamed JSON bundle (history window, trace tail, rpc "
            "tables, active alerts, last reports). Empty (default) = "
            "recorder off")
define_flag("incident_min_interval_s", 60.0,
            "incident capture rate limit (core/incident.py): at most "
            "one bundle per interval per process — a flapping alert "
            "must not turn the flight recorder into a disk-filling "
            "loop; suppressed captures count incident/rate_limited")
define_flag("autopilot_poll_s", 0.5,
            "fleet autopilot control-loop cadence "
            "(serving/autopilot.py): each tick reads the merged fleet "
            "stats + active alerts and may emit at most one scale "
            "action and one canary transition")
define_flag("autopilot_cooldown_s", 5.0,
            "hysteresis guard between consecutive autopilot scale "
            "actions (out, in, or shard repair): inside the cooldown "
            "the loop observes but never acts — a flapping sensor "
            "produces at most one action per window. Persisted in the "
            "controller state file, so a restarted controller honors "
            "the window instead of double-applying")
define_flag("autopilot_min_replicas", 1,
            "scale-in floor: the autopilot never drains the fleet "
            "below this many healthy replicas")
define_flag("autopilot_max_replicas", 8,
            "scale-out ceiling: the autopilot never spawns past this "
            "many healthy replicas, whatever the burn rate says")
define_flag("autopilot_scale_in_fill", 0.1,
            "scale-in trigger: merged batch_fill_frac below this with "
            "zero SLO-violation delta and p99 under half the SLO means "
            "the fleet is over-provisioned — drain the least-loaded "
            "replica (subject to the cooldown and the floor)")
define_flag("autopilot_canary_replicas", 1,
            "canary subset size: a new donefile BASE lands on this "
            "many replicas first (clamped so at least one incumbent "
            "keeps serving the old model for the COPC comparison)")
define_flag("autopilot_canary_min_labels", 64,
            "joined label rows each side (canary and incumbent) of "
            "the quality comparison needs before the controller "
            "renders a promote/rollback verdict")
define_flag("autopilot_canary_copc_margin", 0.2,
            "rollback objective: the canary's |COPC - 1| may exceed "
            "the incumbent's by at most this margin; past it the base "
            "is judged calibration-poisoned and rolled back")
define_flag("autopilot_canary_timeout_s", 60.0,
            "fail-closed canary deadline: a canary that cannot gather "
            "enough joined labels for a verdict within this window is "
            "rolled back (objective 'timeout'), never promoted on "
            "missing evidence. <= 0 disables the deadline")
