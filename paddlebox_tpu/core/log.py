"""Leveled logging with VLOG semantics.

Role of glog + ``VLOG(n)`` used throughout the reference C++ core. Verbosity
is controlled by the ``v`` flag (env ``FLAGS_v``), matching how the reference
reads ``GLOG_v``.
"""

from __future__ import annotations

import logging
import sys

from paddlebox_tpu.core import flags

_logger = logging.getLogger("paddlebox_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(levelname).1s %(asctime)s.%(msecs)03d %(name)s] %(message)s",
        datefmt="%m%d %H:%M:%S"))
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)
    _logger.propagate = False


def vlog(level: int, msg: str, *args) -> None:
    """Log ``msg`` when the global verbosity flag is >= ``level``."""
    if flags.flag("v") >= level:
        _logger.info(msg, *args)


def info(msg: str, *args) -> None:
    _logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    _logger.warning(msg, *args)


def error(msg: str, *args) -> None:
    _logger.error(msg, *args)


def get_logger() -> logging.Logger:
    return _logger
