"""Pipeline occupancy + critical-path attribution.

The pass report's stage timers (core/report.py) say how long each stage
RAN; they cannot say who was blocked on whom — the question BENCH_r02's
15.6%-of-device-only number actually poses. This module records, per
pipeline stage, the three wall-time states that answer it:

- **busy** — the stage was doing its own work;
- **blocked_up** — waiting on its upstream (starved for input);
- **blocked_down** — waiting on its downstream (output queue full);

plus sampled queue depths (log-bucketed digests — core/quantiles.py),
and computes a per-window ``bottleneck`` verdict: the bounding stage,
the device idle fraction, and the host critical-path share. The stages
wired today (all HOST-side — nothing here touches the jitted step):

| stage      | where                                                   |
|---|---|
| ``reader`` | prefetch producer waiting on the dataset iterator        |
| ``packer`` | batch assembly / K-stacking / H2D (+ put-wait = blocked_down) |
| ``keymap`` | the map-ahead host keymap worker (CopyKeys role)         |
| ``device`` | consumer: dispatch enqueue + blocking fetches = busy; queue get-wait = blocked_up (the device-starved signal) |
| ``boundary`` | pass build (busy) vs time parked on the active pass (blocked_up) — fed from ``PassEngine.boundary_ms`` deltas |
| ``day_load`` | day-loop dataset load (usually hidden under the previous pass) |

Process-global like the metric registry; per-pass attribution windows
come from :meth:`PipelineStats.snapshot` + :meth:`window` deltas, so
multiple sequential passes (and trainers) share one recorder.

Verdict semantics (classic pipeline analysis — the stage running
closest to 100% utilization bounds throughput):

- ``stage``: the stage with the highest busy share of the window.
- ``device_idle_frac``: the consumer's blocked_up share — the fraction
  of the pass the device had no new block to chew on (host-visible
  starvation; an async dispatch queue means true device idle can only
  be lower).
- ``host_critical_share``: ``1 - device busy share`` — the fraction of
  the pass wall NOT attributable to device dispatch/drain, i.e. what a
  host-side fix could reclaim.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

from paddlebox_tpu.core.quantiles import LogQuantileDigest

# Relative error of the queue-depth digests (depths are small ints; 2%
# keeps the bucket count tiny).
_QUEUE_REL_ERROR = 0.02

KINDS = ("busy", "blocked_up", "blocked_down")


class _Stage:
    __slots__ = ("busy_s", "blocked_up_s", "blocked_down_s", "count")

    def __init__(self) -> None:
        self.busy_s = 0.0
        self.blocked_up_s = 0.0
        self.blocked_down_s = 0.0
        self.count = 0


class PipelineStats:
    """Thread-safe per-stage occupancy recorder with queue-depth
    digests. All methods are cheap (two perf_counter calls + one lock
    per scope) — they run per BATCH, never per device op."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._stages: Dict[str, _Stage] = {}
        self._queues: Dict[str, LogQuantileDigest] = {}

    # -- recording ---------------------------------------------------------

    def add(self, stage: str, kind: str, seconds: float) -> None:
        """Credit an externally-measured interval (the TimerGroup
        ``add_elapsed`` idiom — used by tests and by callers that
        already timed the interval)."""
        if kind not in KINDS:
            raise ValueError(f"unknown occupancy kind {kind!r}")
        with self._lock:
            st = self._stages.get(stage)
            if st is None:
                st = self._stages[stage] = _Stage()
            setattr(st, kind + "_s", getattr(st, kind + "_s") + seconds)
            st.count += 1

    @contextmanager
    def _scope(self, stage: str, kind: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(stage, kind, time.perf_counter() - t0)

    def busy(self, stage: str):
        return self._scope(stage, "busy")

    def blocked_up(self, stage: str):
        return self._scope(stage, "blocked_up")

    def blocked_down(self, stage: str):
        return self._scope(stage, "blocked_down")

    def sample_queue(self, name: str, depth: int) -> None:
        with self._lock:
            d = self._queues.get(name)
            if d is None:
                d = self._queues[name] = LogQuantileDigest(
                    _QUEUE_REL_ERROR)
            d.observe(float(depth))

    # -- windows -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Cumulative state — the base for a per-pass :meth:`window`."""
        with self._lock:
            return {
                "stages": {n: {"busy_s": s.busy_s,
                               "blocked_up_s": s.blocked_up_s,
                               "blocked_down_s": s.blocked_down_s,
                               "count": s.count}
                           for n, s in self._stages.items()},
                "queues": {n: d.copy() for n, d in self._queues.items()},
            }

    def window(self, base: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
        """Delta since ``base`` (a prior snapshot): per-stage ms in each
        state + per-queue window digests. Stages with zero activity in
        the window are dropped."""
        now = self.snapshot()
        base = base or {"stages": {}, "queues": {}}
        stages: Dict[str, Dict[str, float]] = {}
        for n, s in now["stages"].items():
            b = base["stages"].get(n, {})
            d = {"busy_ms": (s["busy_s"] - b.get("busy_s", 0.0)) * 1e3,
                 "blocked_up_ms": (s["blocked_up_s"]
                                   - b.get("blocked_up_s", 0.0)) * 1e3,
                 "blocked_down_ms": (s["blocked_down_s"]
                                     - b.get("blocked_down_s", 0.0))
                 * 1e3,
                 "count": s["count"] - b.get("count", 0)}
            if d["count"] > 0 or any(d[k] > 1e-6 for k in
                                     ("busy_ms", "blocked_up_ms",
                                      "blocked_down_ms")):
                stages[n] = {k: (round(v, 3) if k != "count" else v)
                             for k, v in d.items()}
        queues = {}
        for n, d in now["queues"].items():
            w = d.delta(base["queues"].get(n))
            if w.count:
                queues[n] = w
        return {"stages": stages, "queues": queues}


def bottleneck_verdict(window: Dict[str, Any], wall_ms: float,
                       device_stage: str = "device") -> Dict[str, Any]:
    """Compute the bounding-stage verdict from a :meth:`window` delta.

    Pure and deterministic — tests feed synthetic windows. Returns a
    JSON-safe dict: ``stage`` (bounding stage — highest busy share),
    ``device_idle_frac``, ``host_critical_share``, per-stage
    busy/blocked shares, and queue-depth percentiles."""
    stages = window.get("stages") or {}
    out: Dict[str, Any] = {"stage": None, "device_idle_frac": None,
                           "host_critical_share": None, "stages": {},
                           "queue_depth": {}}
    if wall_ms <= 0 or not stages:
        return out
    shares: Dict[str, Dict[str, float]] = {}
    for n, s in stages.items():
        shares[n] = {
            "busy_ms": round(s.get("busy_ms", 0.0), 3),
            "busy_frac": round(s.get("busy_ms", 0.0) / wall_ms, 4),
            "blocked_up_frac": round(
                s.get("blocked_up_ms", 0.0) / wall_ms, 4),
            "blocked_down_frac": round(
                s.get("blocked_down_ms", 0.0) / wall_ms, 4),
        }
    out["stages"] = shares
    out["stage"] = max(shares, key=lambda n: shares[n]["busy_frac"])
    dev = shares.get(device_stage)
    if dev is not None:
        out["device_idle_frac"] = dev["blocked_up_frac"]
        out["host_critical_share"] = round(
            min(1.0, max(0.0, 1.0 - dev["busy_frac"])), 4)
    for n, d in (window.get("queues") or {}).items():
        qs = d.quantiles((0.5, 0.9, 0.99))
        rnd = lambda v: round(v, 2) if v is not None else None  # noqa: E731
        out["queue_depth"][n] = {
            "p50": rnd(qs["p50"]), "p90": rnd(qs["p90"]),
            "p99": rnd(qs["p99"]), "max": rnd(d.max),
            "samples": d.count}
    return out


GLOBAL = PipelineStats()

add = GLOBAL.add
busy = GLOBAL.busy
blocked_up = GLOBAL.blocked_up
blocked_down = GLOBAL.blocked_down
sample_queue = GLOBAL.sample_queue
snapshot = GLOBAL.snapshot
window = GLOBAL.window
