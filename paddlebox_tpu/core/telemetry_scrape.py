"""One-scrape cluster telemetry: collect every server's registry.

The fleet (PRs 10-13) is N processes — FleetRouter, PredictServer
replicas, ShardServer hosts — each keeping its OWN metric registry
(instance Monitors, so in-process drills don't clobber each other).
This module is the collector half of the "one-scrape cluster" story
(OBSERVABILITY.md "Distributed tracing"): every framed service answers
a ``metrics_snapshot`` RPC (the ``FramedRPCServer`` base handler;
PredictServer / ShardServer / FleetRouter override it with their
instance registries and scrape-time derived gauges such as the
replication-lag pair), and :func:`scrape_cluster` folds the per-target
snapshots through :func:`monitor.merge_snapshots` into ONE cluster
snapshot plus a flat per-target summary table — what
``tools/fleet_top.py`` renders live and records to JSONL.

Pure client code: no jax, no server state, safe to run from an
operator laptop against a live cluster (trusted network, same stance
as the wire protocol itself).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from paddlebox_tpu.core import monitor
from paddlebox_tpu.core.quantiles import LogQuantileDigest


def _conn(endpoint: str, timeout: float):
    from paddlebox_tpu.distributed import rpc
    return rpc.FramedRPCConn(
        endpoint, timeout=timeout, service_name="scrape",
        idempotent=("metrics_snapshot", "metrics_history",
                    "alerts_active", "stats", "topology"))


def scrape_endpoint(endpoint: str, *, timeout: float = 10.0,
                    with_stats: bool = True,
                    with_alerts: bool = True) -> Dict[str, Any]:
    """One target's ``metrics_snapshot`` (labeled registry snapshot),
    with its ``stats`` reply attached under ``"stats"`` when the
    service answers one (best-effort — the snapshot is the contract,
    stats is gravy like the per-process rpc reconnect/retry totals)."""
    c = _conn(endpoint, timeout)
    try:
        snap = c.call("metrics_snapshot")
        if with_stats:
            try:
                snap["stats"] = c.call("stats")
            except (OSError, ConnectionError, RuntimeError):
                pass
        if with_alerts:
            # Best-effort like stats: the alert surface rides every
            # sweep (the acceptance contract: ONE scrape shows the
            # FIRING rule), but an old server without the handler
            # doesn't fail the scrape.
            try:
                snap["alerts"] = c.call("alerts_active")
            except (OSError, ConnectionError, RuntimeError):
                pass
        return snap
    finally:
        c.close()


def scrape_history(endpoint: str, *, timeout: float = 10.0,
                   window_s: Optional[float] = None,
                   last_n: Optional[int] = None) -> Dict[str, Any]:
    """One target's ``metrics_history`` ring (core/timeseries.py wire
    dict) — the trend surface behind fleet_top sparklines."""
    c = _conn(endpoint, timeout)
    try:
        req: Dict[str, Any] = {}
        if window_s is not None:
            req["window_s"] = float(window_s)
        if last_n is not None:
            req["last_n"] = int(last_n)
        return c.call("metrics_history", **req)
    finally:
        c.close()


def discover_router_targets(router_endpoint: str, *,
                            timeout: float = 10.0) -> Dict[str, str]:
    """label -> endpoint map from a FleetRouter's ``topology`` RPC:
    the router itself plus every non-ejected replica — so fleet_top
    follows join/leave without re-listing endpoints by hand."""
    c = _conn(router_endpoint, timeout)
    try:
        topo = c.call("topology")
    finally:
        c.close()
    out = {"router": router_endpoint}
    for r in topo.get("replicas", ()):
        if r.get("state") != "ejected" and r.get("endpoint"):
            out[f"replica:{r['id']}"] = str(r["endpoint"])
    return out


def _q(snap: Dict[str, Any], name: str, q: str = "p99"
       ) -> Optional[float]:
    d = (snap.get("quantiles") or {}).get(name)
    if not d:
        return None
    v = LogQuantileDigest.from_dict(d).quantiles().get(q)
    return round(v, 3) if isinstance(v, (int, float)) else None


def summarize_target(label: str, endpoint: str,
                     snap: Dict[str, Any]) -> Dict[str, Any]:
    """One flat row per target: the columns an operator watches —
    per-replica predict p99 + rps + SLO breaches, per-shard served
    volume + worst/p99 replication journal lag, router hop split, and
    the process's rpc reconnect/retry totals (off the stats ride-along)."""
    if "error" in snap and "counters" not in snap:
        return {"target": label, "endpoint": endpoint,
                "error": snap["error"]}
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    stats = snap.get("stats") or {}
    row: Dict[str, Any] = {"target": label, "endpoint": endpoint}
    p99 = _q(snap, "serving/predict_ms")
    if p99 is not None:
        row["predict_p99_ms"] = p99
    rps = gauges.get("serving/throughput_rps")
    if rps is None and isinstance(stats.get("throughput_rps"),
                                  (int, float)):
        rps = stats["throughput_rps"]
    if rps is not None:
        row["throughput_rps"] = round(float(rps), 1)
    if counters.get("slo/violations") or "slo/violations" in counters:
        row["slo_violations"] = int(counters.get("slo/violations", 0))
    for k, name in (("served_pull_keys", "multihost/served_pull_keys"),
                    ("served_push_keys", "multihost/served_push_keys")):
        if name in counters:
            row[k] = int(counters[name])
    for k, name in (("replica_lag_worst", "multihost/replica_lag_worst"),
                    ("replica_lag_p99", "multihost/replica_lag_p99"),
                    ("shard_rows", "multihost/shard_rows")):
        if name in gauges:
            row[k] = gauges[name]
    if "fleet/routed" in counters:
        row["routed"] = int(counters["fleet/routed"])
    for k, name in (("hop_route_p99_ms", "fleet/hop_route_ms"),
                    ("hop_wire_p99_ms", "fleet/hop_wire_ms"),
                    ("hop_server_p99_ms", "fleet/hop_server_ms")):
        v = _q(snap, name)
        if v is not None:
            row[k] = v
    for k in ("rpc_reconnects", "rpc_retries", "num_features", "keys"):
        if isinstance(stats.get(k), (int, float)):
            row[k] = int(stats[k])
    # RPC-plane health (PR 16 event-loop servers): poller-loop lag and
    # worker-queue depth say "is the one poller keeping up"; coalesced
    # pulls and mux fallbacks say the optimization planes are engaged.
    for k, name in (("rpc_poller_lag_ms", "rpc/poller_lag_ms"),
                    ("rpc_worker_queue", "rpc/worker_queue_depth")):
        v = gauges.get(name)
        if isinstance(v, (int, float)):
            row[k] = round(float(v), 3)
    for k, name in (("rpc_mux_fallbacks", "rpc/mux_fallbacks"),
                    ("coalesced_pulls", "multihost/coalesced_pulls")):
        if counters.get(name):
            row[k] = int(counters[name])
    # Model-quality pane (core/quality.py): COPC / calibration error
    # gauges plus the target's total quality alarms — "is the model
    # healthy" answered in the same row as "is the target healthy".
    for k, name in (("copc", "quality/copc"),
                    ("calibration_error", "quality/calibration_error")):
        v = gauges.get(name)
        if isinstance(v, (int, float)):
            row[k] = round(float(v), 4)
    qa = sum(int(v) for k, v in counters.items()
             if k.startswith("quality/alarms/"))
    if qa or any(k.startswith("quality/") for k in counters):
        row["quality_alarms"] = qa
    # SLO alert pane (core/alerts.py ride-along): firing count plus
    # the worst active rule name — one glance answers "is anything
    # paging on this target".
    al = snap.get("alerts")
    if isinstance(al, dict) and al.get("enabled"):
        row["alerts_firing"] = int(al.get("firing", 0))
        active = [a for a in al.get("alerts") or ()
                  if a.get("state") in ("firing", "pending")]
        if active:
            row["alert"] = (f"{active[0]['name']}"
                            f"[{active[0]['state']}]")
    return row


def scrape_cluster(targets: Dict[str, str], *, timeout: float = 10.0,
                   with_stats: bool = True, with_alerts: bool = True,
                   with_history: bool = False,
                   history_window_s: Optional[float] = None
                   ) -> Dict[str, Any]:
    """Scrape every target once and fold the answers: per-target
    snapshots + summary rows, the ONE merged cluster snapshot
    (counters summed, gauges mean+__max, digests merged — so the
    fleet-wide predict p99 and worst replication lag come out of a
    single read), and an error map for unreachable targets."""
    per: Dict[str, Dict[str, Any]] = {}
    errors: Dict[str, str] = {}
    for label, ep in targets.items():
        try:
            per[label] = scrape_endpoint(ep, timeout=timeout,
                                         with_stats=with_stats,
                                         with_alerts=with_alerts)
            if with_history:
                try:
                    per[label]["history"] = scrape_history(
                        ep, timeout=timeout,
                        window_s=history_window_s)
                except (OSError, ConnectionError, RuntimeError):
                    pass
        except (OSError, ConnectionError, RuntimeError) as e:
            errors[label] = repr(e)
    # merge_snapshots understands the snapshot_all sections only; the
    # stats ride-along must not leak in.
    merged = monitor.merge_snapshots(
        [{k: v for k, v in s.items()
          if k not in ("stats", "alerts", "history")}
         for s in per.values()])
    summary = [summarize_target(label, targets[label], snap)
               for label, snap in per.items()]
    cluster: Dict[str, Any] = {
        "scraped": len(per),
        "unreachable": len(errors),
        "fleet_predict_p99_ms": _q(merged, "serving/predict_ms"),
        "fleet_route_p99_ms": _q(merged, "fleet/route_ms"),
    }
    g = merged.get("gauges") or {}
    lag = g.get("multihost/replica_lag_worst__max",
                g.get("multihost/replica_lag_worst"))
    if lag is not None:
        cluster["replica_lag_worst"] = lag
    # Fleet-wide model health: quality alarms sum across every scraped
    # registry (counters section of the merged snapshot) plus the mean
    # COPC gauge — one scrape answers "is the model healthy" next to
    # the latency/lag systems columns above.
    qa = sum(int(v) for k, v in (merged.get("counters") or {}).items()
             if k.startswith("quality/alarms/"))
    if qa:
        cluster["quality_alarms"] = qa
    copc = g.get("quality/copc")
    if copc is not None:
        cluster["copc"] = round(float(copc), 4)
    # Fleet-wide alert roll-up: every FIRING/PENDING rule across the
    # scraped targets, deduped per (target, rule) — what fleet_top's
    # ALERTS pane and the acceptance drill read from ONE sweep.
    fleet_alerts: List[Dict[str, Any]] = []
    for label, snap in per.items():
        al = snap.get("alerts")
        if not (isinstance(al, dict) and al.get("enabled")):
            continue
        for a in al.get("alerts") or ():
            if a.get("state") in ("firing", "pending", "resolved"):
                fleet_alerts.append({"target": label, **a})
    if fleet_alerts:
        order = {"firing": 0, "pending": 1, "resolved": 2}
        fleet_alerts.sort(key=lambda a: (order.get(a["state"], 3),
                                         a.get("name", "")))
        cluster["alerts_firing"] = sum(
            1 for a in fleet_alerts if a["state"] == "firing")
    out: Dict[str, Any] = {
        "ts": time.time(), "targets": dict(targets),
        "per_target": per, "summary": summary,
        "errors": errors, "merged": merged, "cluster": cluster,
        "alerts": fleet_alerts}
    if with_history:
        hists = [s["history"] for s in per.values()
                 if isinstance(s.get("history"), dict)]
        if hists:
            from paddlebox_tpu.core import timeseries
            out["history"] = timeseries.merge_history(hists)
    return out


def record_jsonl(path: str, record: Dict[str, Any], *,
                 full: bool = False) -> None:
    """Append one scrape to a JSONL file (the fleet_top ``--record``
    sink). Default keeps the compact sections (summary + cluster +
    errors); ``full`` also writes the merged snapshot."""
    keep = ("ts", "targets", "summary", "cluster", "errors", "alerts")
    line = {k: record.get(k) for k in keep}
    if full:
        line["merged"] = record.get("merged")
    with open(path, "a") as f:
        f.write(json.dumps(line, default=str) + "\n")
