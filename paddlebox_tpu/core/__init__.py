"""Core runtime: flags, logging, monitors, timers, tracing, reports.

Role of the reference's platform layer (``paddle/fluid/platform/``):
gflags (``flags.cc``), glog VLOG, ``platform/monitor.h`` named counters
(grown into a counters/gauges/histograms registry with a JSONL
exporter), ``platform::Timer`` hot-path timers, plus the span tracer +
pass report that replace ad-hoc ``PrintSyncTimer`` prints (see
OBSERVABILITY.md).
"""

from paddlebox_tpu.core import flags
from paddlebox_tpu.core import log
from paddlebox_tpu.core import monitor
from paddlebox_tpu.core import report
from paddlebox_tpu.core import timers
from paddlebox_tpu.core import trace

__all__ = ["flags", "log", "monitor", "report", "timers", "trace"]
