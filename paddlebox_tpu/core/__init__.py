"""Core runtime: flags, logging, monitors, timers.

Role of the reference's platform layer (``paddle/fluid/platform/``):
gflags (``flags.cc``), glog VLOG, ``platform/monitor.h`` named counters,
``platform::Timer`` hot-path timers.
"""

from paddlebox_tpu.core import flags
from paddlebox_tpu.core import log
from paddlebox_tpu.core import monitor
from paddlebox_tpu.core import timers

__all__ = ["flags", "log", "monitor", "timers"]
