"""Model-quality & data-health observatory.

The systems plane (PRs 6/14) answers "is the fleet healthy"; this
module answers "is the MODEL healthy" on the same one-scrape telemetry
plane. CTR fleets die silently from data and calibration drift, not
crashes — the reference ships slot-level calibration machinery (PCOC in
``fused_seqpool_cvm_with_pcoc``, the bucket calibration error in its AUC
calculator) precisely because day-end AUC is too late. Three layers,
all host-side (nothing here ever enters a jitted program — the quality
jaxpr pins in tests/test_quality.py hold with everything on):

- :class:`SlotHealthCollector` — per-slot input health fed from the
  ingest chunk path (``data/`` columnar chunks): example coverage,
  ids/example quantiles, zero-key rate, label out-of-range rate,
  pass-over-pass key churn and access-skew top-share (the hot-set
  statistics "Dissecting Embedding Bag Performance in DLRM Inference"
  analyzes offline, live as gauges).
- calibration — streaming COPC (actual ctr / predicted ctr; 1.0 =
  calibrated, the inverse of the reference's PCOC) plus the registry's
  ``bucket_error_sweep`` calibration error, localized into log-spaced
  prediction buckets so an excursion NAMES the offending buckets.
  Accumulated per pass from the trainer's device AUC table (a host
  rebin of the existing ``[2, nb]`` histogram — zero device ops), and
  on served traffic via :class:`ServingQuality`'s sampled
  prediction+label join (labels arrive late through the stream tier's
  event log; join by sampled request id under a bounded pending
  window — expiry is counted, never crashed).
- drift alarms — :class:`DriftDetector` keeps a previous-N-pass window
  + EWMA baseline per metric; ``FLAGS_quality_*`` thresholds raise
  ``quality/alarms/<kind>`` counters and ONE structured
  ``quality_report {json}`` line beside ``pass_report``
  (:func:`core.report.emit_quality_report`), so a COPC excursion or a
  slot going dark is caught within one pass, not at day-end AUC.

Default-off (``FLAGS_quality_collect``), consistent with the rest of
the telemetry plane; the pass_report's headline ``copc`` /
``bucket_error`` fields are free and always on. Replay purity: nothing
on the training path reads the wall clock or randomness — the serving
joiner's clock is injectable and lives outside the replay closure.
"""

from __future__ import annotations

import math
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import flags, log, monitor, report

# Log-spaced prediction-bucket edges for COPC localization: CTR
# predictions live on a log scale (1e-4 tail traffic and 0.5 head
# traffic are both real), so linear buckets would put every alarm in
# one bin. 24 buckets over [1e-6, 1].
N_PRED_BUCKETS = 24
PRED_EDGES = np.concatenate(
    [[0.0], np.logspace(-6.0, 0.0, N_PRED_BUCKETS)])


def enabled() -> bool:
    """Master switch (``FLAGS_quality_collect``). Read at per-pass /
    per-dataset granularity — never per row."""
    return bool(flags.flag("quality_collect"))


# -- calibration --------------------------------------------------------------


def log_bucket_table(table: np.ndarray) -> List[Dict[str, float]]:
    """Rebin a linear ``[2, nb]`` neg/pos prediction histogram (the
    device AUC table / host calculator table) into the log-spaced
    prediction buckets. Per bucket: shows, clicks, the midpoint-
    approximated predicted ctr, and COPC = actual/predicted. The
    midpoint approximation is exact to one linear bucket's width
    (1/nb), far below any alarm threshold."""
    table = np.asarray(table, np.float64)
    neg, pos = table[0], table[1]
    nb = neg.shape[0]
    centers = (np.arange(nb, dtype=np.float64) + 0.5) / nb
    li = np.clip(np.searchsorted(PRED_EDGES[1:], centers, side="left"),
                 0, N_PRED_BUCKETS - 1)
    shows = np.bincount(li, weights=neg + pos, minlength=N_PRED_BUCKETS)
    clicks = np.bincount(li, weights=pos, minlength=N_PRED_BUCKETS)
    pred_sum = np.bincount(li, weights=(neg + pos) * centers,
                           minlength=N_PRED_BUCKETS)
    out: List[Dict[str, float]] = []
    for b in range(N_PRED_BUCKETS):
        if shows[b] <= 0:
            continue
        predicted = pred_sum[b] / shows[b]
        actual = clicks[b] / shows[b]
        out.append({
            "lo": round(float(PRED_EDGES[b]), 8),
            "hi": round(float(PRED_EDGES[b + 1]), 8),
            "count": float(shows[b]),
            "predicted_ctr": round(float(predicted), 6),
            "actual_ctr": round(float(actual), 6),
            "copc": (round(float(actual / predicted), 4)
                     if predicted > 0 else None),
        })
    return out


def offending_buckets(buckets: Sequence[Dict[str, float]], *,
                      tol: float, top: int = 8) -> List[Dict[str, float]]:
    """The buckets a COPC excursion should NAME: count-qualified
    (>= max(16, 0.2%) of the window) log buckets whose per-bucket COPC
    deviates from 1.0 by more than ``tol``, worst first."""
    total = sum(b["count"] for b in buckets)
    min_count = max(16.0, 0.002 * total)
    bad = [b for b in buckets
           if b["count"] >= min_count and b["copc"] is not None
           and abs(b["copc"] - 1.0) > tol]
    bad.sort(key=lambda b: -abs(b["copc"] - 1.0))
    return bad[:top]


def calibration_error_from_table(table: np.ndarray) -> float:
    """The registry's adaptive-span bucket calibration error, reused
    verbatim (metrics/registry.py ``bucket_error_sweep``)."""
    from paddlebox_tpu.metrics.registry import bucket_error_sweep
    return float(bucket_error_sweep(np.asarray(table, np.float64)))


# -- drift baselines ----------------------------------------------------------


class DriftDetector:
    """Windowed per-metric baseline: previous-N-pass value window plus
    an EWMA. A check compares the NEW value against the baseline built
    from prior passes only (the current value joins the window after
    the verdict), so an abrupt excursion alarms on the pass it lands
    in while gradual convergence never does. No alarms before
    ``warmup`` observations of a metric — early training legitimately
    moves calibration."""

    EWMA_ALPHA = 0.3

    def __init__(self):
        self._hist: Dict[str, deque] = {}
        self._ewma: Dict[str, float] = {}

    def baseline(self, name: str) -> Optional[float]:
        return self._ewma.get(name)

    def check(self, name: str, value: Optional[float], *, rel_tol: float,
              abs_floor: float = 0.0, direction: str = "both"
              ) -> Optional[Dict[str, Any]]:
        """Update the metric's window with ``value`` and return an alarm
        dict when it deviates from the pre-existing baseline by more
        than ``rel_tol`` (relative) AND ``abs_floor`` (absolute).
        ``direction``: 'both', 'up' (only a rise alarms — error-style
        metrics), or 'down' (only a drop — coverage-style)."""
        if value is None or not math.isfinite(value):
            return None
        window = max(2, int(flags.flag("quality_baseline_passes")))
        warmup = max(1, int(flags.flag("quality_warmup_passes")))
        hist = self._hist.get(name)
        if hist is None or hist.maxlen != window:
            hist = self._hist[name] = deque(hist or (), maxlen=window)
        base = self._ewma.get(name)
        alarm = None
        if base is not None and len(hist) >= warmup:
            dev = value - base
            dir_ok = (direction == "both"
                      or (direction == "up" and dev > 0)
                      or (direction == "down" and dev < 0))
            if (dir_ok and abs(dev) > rel_tol * max(abs(base), 1e-9)
                    and abs(dev) > abs_floor):
                alarm = {"metric": name, "value": round(value, 6),
                         "baseline": round(base, 6),
                         "window": len(hist)}
        hist.append(value)
        self._ewma[name] = (value if base is None
                            else self.EWMA_ALPHA * value
                            + (1.0 - self.EWMA_ALPHA) * base)
        return alarm

    def reset(self) -> None:
        self._hist.clear()
        self._ewma.clear()


# -- per-slot input health ----------------------------------------------------


class SlotHealthCollector:
    """Per-slot data-health accumulated from ingest-path columnar
    chunks (one collector per Dataset load window; the hook lives in
    ``Dataset._drain``). All numpy-vectorized per chunk — the heavy
    half (per-chunk key dedup) mirrors what ``ingest_key_runs`` already
    pays. Thread-safe: the preload thread feeds it."""

    MAX_LEN_BIN = 64          # ids/example histogram cap (clipped)
    TOP_SHARE_FRAC = 0.01     # "top share" = head 1% of keys

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: Dict[str, Dict[str, Any]] = {}
        self._rows = 0
        self._label_values = 0
        self._label_oob = 0

    def observe_chunk(self, chunk) -> None:
        n = int(chunk.num_rows)
        if n == 0:
            return
        lab = chunk.labels
        oob = int(np.count_nonzero(~np.isfinite(lab) | (lab < 0.0)
                                   | (lab > 1.0)))
        per_slot = []
        for s, ids in chunk.sparse_ids.items():
            lens = np.diff(chunk.sparse_offsets[s])
            hist = np.bincount(np.minimum(lens, self.MAX_LEN_BIN),
                               minlength=self.MAX_LEN_BIN + 1)
            uk, cnt = (np.unique(ids, return_counts=True) if ids.size
                       else (np.empty(0, np.uint64),
                             np.empty(0, np.int64)))
            per_slot.append((s, int(np.count_nonzero(lens > 0)),
                             int(ids.size),
                             int(np.count_nonzero(ids == 0)),
                             hist, uk, cnt))
        with self._lock:
            self._rows += n
            self._label_values += int(lab.size)
            self._label_oob += oob
            for s, with_slot, nids, zeros, hist, uk, cnt in per_slot:
                st = self._slots.get(s)
                if st is None:
                    st = self._slots[s] = {
                        "with_slot": 0, "ids": 0, "zeros": 0,
                        "len_hist": np.zeros(self.MAX_LEN_BIN + 1,
                                             np.int64),
                        "runs": []}
                st["with_slot"] += with_slot
                st["ids"] += nids
                st["zeros"] += zeros
                st["len_hist"] += hist
                if uk.size:
                    st["runs"].append((uk, cnt))

    @staticmethod
    def _hist_quantile(hist: np.ndarray, total: int, q: float) -> float:
        if total <= 0:
            return 0.0
        cum = np.cumsum(hist)
        return float(np.searchsorted(cum, q * total, side="left"))

    def finalize(self) -> Optional[Dict[str, Any]]:
        """One health snapshot of everything observed so far:
        per-slot coverage / ids-per-example quantiles / zero rate /
        access-skew top-share plus the merged unique key+count arrays
        (the churn comparand the tracker keeps pass-over-pass)."""
        with self._lock:
            rows = self._rows
            if rows == 0:
                return None
            slots = {s: dict(st) for s, st in self._slots.items()}
            label_values = self._label_values
            label_oob = self._label_oob
        out_slots: Dict[str, Dict[str, Any]] = {}
        keys_by_slot: Dict[str, np.ndarray] = {}
        for s, st in slots.items():
            if st["runs"]:
                all_k = np.concatenate([r[0] for r in st["runs"]])
                all_c = np.concatenate([r[1] for r in st["runs"]])
                uk, inv = np.unique(all_k, return_inverse=True)
                counts = np.bincount(inv, weights=all_c.astype(np.float64))
            else:
                uk = np.empty(0, np.uint64)
                counts = np.empty(0, np.float64)
            total = float(counts.sum())
            if uk.size:
                head = max(1, int(math.ceil(self.TOP_SHARE_FRAC
                                            * uk.size)))
                top = float(np.sort(counts)[::-1][:head].sum())
                top_share = top / total if total > 0 else 0.0
            else:
                top_share = 0.0
            out_slots[s] = {
                "coverage": round(st["with_slot"] / rows, 6),
                "ids_per_example_p50": self._hist_quantile(
                    st["len_hist"], rows, 0.5),
                "ids_per_example_p99": self._hist_quantile(
                    st["len_hist"], rows, 0.99),
                "zero_frac": round(st["zeros"] / max(st["ids"], 1), 6),
                "unique_keys": int(uk.size),
                "top_share": round(top_share, 4),
            }
            keys_by_slot[s] = uk
        return {"examples": rows,
                "label_oob_frac": round(label_oob / max(label_values, 1),
                                        6),
                "slots": out_slots,
                "_keys": keys_by_slot}


# -- the training-side tracker ------------------------------------------------


class QualityTracker:
    """Per-process model-quality state: per-pass calibration + slot
    health + drift alarms, emitted as ONE ``quality_report`` line and
    a set of ``quality/*`` registry gauges/counters beside each pass
    report. Driven by ``CTRTrainer.train_pass/eval_pass``; the stream
    and day runners stamp the pass context (day/pass_id) first."""

    def __init__(self):
        self._lock = threading.Lock()
        self._drift = DriftDetector()
        self._prev_keys: Dict[str, np.ndarray] = {}
        self._pass_idx = 0
        self._ctx: Optional[Dict[str, Any]] = None
        self._day_rollover = False
        self.last_report: Optional[Dict[str, Any]] = None

    def set_pass_context(self, day: str, pass_id: int, *,
                         events: Optional[int] = None,
                         files: Optional[int] = None,
                         override: bool = True) -> None:
        """Stamp the NEXT observe_pass with its day/pass identity (the
        stream runner adds manifest detail; the day runner only fills
        in when nothing richer is pending)."""
        if not enabled():
            return
        with self._lock:
            if self._ctx is not None and not override:
                return
            ctx: Dict[str, Any] = {"day": str(day),
                                   "pass_id": int(pass_id)}
            if events is not None:
                ctx["events"] = int(events)
            if files is not None:
                ctx["files"] = int(files)
            self._ctx = ctx

    def note_day_rollover(self) -> None:
        """A day boundary just closed: key churn on the NEXT pass is
        expected (the per-day key window slides), so the churn alarm is
        suppressed for that one pass."""
        with self._lock:
            self._day_rollover = True

    def reset(self) -> None:
        with self._lock:
            self._drift.reset()
            self._prev_keys = {}
            self._pass_idx = 0
            self._ctx = None
            self._day_rollover = False
            self.last_report = None

    # -- the per-pass observation -----------------------------------------

    def observe_pass(self, kind: str, *, stats: Dict[str, Any],
                     auc_table: Optional[np.ndarray] = None,
                     health: Optional[Dict[str, Any]] = None
                     ) -> Optional[Dict[str, Any]]:
        """Fold one finished pass into the quality plane. ``stats`` is
        the trainer's pass stats (carries copc / bucket_error /
        predicted_ctr / actual_ctr from the shared AUC sweep);
        ``auc_table`` the host copy of the ``[2, nb]`` histogram for
        bucket localization; ``health`` a SlotHealthCollector
        finalize(). Returns the quality summary (also in
        ``last_report``), or None when collection is off."""
        if not enabled():
            return None
        reg = monitor.GLOBAL
        copc_tol = float(flags.flag("quality_copc_tol"))
        copc_band = float(flags.flag("quality_copc_band"))
        with self._lock:
            self._pass_idx += 1
            summary: Dict[str, Any] = {"kind": kind,
                                       "pass": self._pass_idx}
            ctx, self._ctx = self._ctx, None
            if ctx:
                summary.update(ctx)
            rollover, self._day_rollover = self._day_rollover, False
            alarms: List[Dict[str, Any]] = []

            # -- calibration ----------------------------------------------
            copc = stats.get("copc")
            cal_err = stats.get("bucket_error")
            for k in ("copc", "predicted_ctr", "actual_ctr"):
                v = stats.get(k)
                if isinstance(v, (int, float)) and math.isfinite(v):
                    summary[k] = round(float(v), 6)
            if isinstance(cal_err, (int, float)):
                summary["calibration_error"] = round(float(cal_err), 6)
            if isinstance(copc, (int, float)) and math.isfinite(copc):
                reg.set_gauge("quality/copc", float(copc))
                reg.observe_quantile("quality/copc", float(copc))
                a = self._drift.check(f"{kind}:copc", float(copc),
                                      rel_tol=copc_tol)
                if a is None and copc_band > 0 \
                        and abs(float(copc) - 1.0) > copc_band:
                    a = {"metric": "copc", "value": round(float(copc), 6),
                         "baseline": 1.0, "band": copc_band}
                if a is not None:
                    a["kind"] = "copc"
                    alarms.append(a)
            if isinstance(cal_err, (int, float)) and math.isfinite(cal_err):
                reg.set_gauge("quality/calibration_error", float(cal_err))
                reg.observe_quantile("quality/calibration_error",
                                     float(cal_err))
                a = self._drift.check(
                    f"{kind}:calibration_error", float(cal_err),
                    rel_tol=float(flags.flag("quality_calibration_tol")),
                    abs_floor=0.01, direction="up")
                if a is not None:
                    a["kind"] = "calibration"
                    alarms.append(a)
            if auc_table is not None:
                buckets = log_bucket_table(auc_table)
                bad = offending_buckets(buckets,
                                        tol=max(copc_tol, 0.2))
                summary["prediction_buckets"] = len(buckets)
                if bad:
                    summary["offending_buckets"] = bad

            # -- per-slot input health ------------------------------------
            if health:
                churn_max = float(flags.flag("quality_churn_max"))
                cov_drop = float(flags.flag("quality_coverage_drop"))
                slot_out: Dict[str, Dict[str, Any]] = {}
                churns: List[float] = []
                top_shares: List[float] = []
                new_keys = health.get("_keys") or {}
                for s, h in health["slots"].items():
                    h = dict(h)
                    prev = self._prev_keys.get(s)
                    cur = new_keys.get(s)
                    churn = None
                    if prev is not None and cur is not None and cur.size:
                        shared = np.intersect1d(
                            prev, cur, assume_unique=True).size
                        churn = round(1.0 - shared / cur.size, 4)
                        h["key_churn"] = churn
                        churns.append(churn)
                    top_shares.append(h.get("top_share", 0.0))
                    slot_out[s] = h
                    reg.set_gauge(f"quality/slot_coverage/{s}",
                                  h["coverage"])
                    reg.set_gauge(f"quality/slot_zero_frac/{s}",
                                  h["zero_frac"])
                    reg.set_gauge(f"quality/slot_top_share/{s}",
                                  h["top_share"])
                    reg.set_gauge(f"quality/slot_ids_p50/{s}",
                                  h["ids_per_example_p50"])
                    reg.set_gauge(f"quality/slot_ids_p99/{s}",
                                  h["ids_per_example_p99"])
                    if churn is not None:
                        reg.set_gauge(f"quality/slot_churn/{s}", churn)
                    a = self._drift.check(
                        f"coverage/{s}", h["coverage"],
                        rel_tol=cov_drop, abs_floor=0.01,
                        direction="down")
                    if a is not None:
                        a["kind"] = "slot_dark"
                        a["slot"] = s
                        alarms.append(a)
                    if (churn is not None and churn_max > 0
                            and churn > churn_max and not rollover):
                        alarms.append({"kind": "churn", "slot": s,
                                       "metric": f"churn/{s}",
                                       "value": churn,
                                       "threshold": churn_max})
                self._prev_keys.update(new_keys)
                summary["slots"] = slot_out
                summary["examples"] = health.get("examples")
                lo = health.get("label_oob_frac")
                if lo:
                    summary["label_oob_frac"] = lo
                if churns:
                    reg.set_gauge("quality/key_churn",
                                  sum(churns) / len(churns))
                if top_shares:
                    reg.set_gauge("quality/skew_top_share",
                                  max(top_shares))

            # -- emit -----------------------------------------------------
            for a in alarms:
                reg.add(f"quality/alarms/{a['kind']}", 1)
                log.warning("quality alarm [%s] %s: value=%s baseline=%s",
                            a["kind"], a.get("metric", a.get("slot")),
                            a.get("value"), a.get("baseline"))
            if alarms:
                summary["alarms"] = alarms
            report.emit_quality_report(kind, summary)
            self.last_report = summary
            return summary


GLOBAL = QualityTracker()


# -- served-traffic calibration ----------------------------------------------


class ServingQuality:
    """Sampled prediction + late-label join on a serving replica.

    ``sample(rid, preds)`` logs a request's predictions under its
    request id when ``FLAGS_quality_sample_rate`` selects it (crc32
    hash of the rid — deterministic, no RNG); labels arrive late
    (through the stream tier's event log, or any label feed) via
    ``join(rid, labels)``. The pending map is bounded: entries older
    than ``FLAGS_quality_join_window_s`` (or past
    ``FLAGS_quality_join_pending``) expire COUNTED
    (``quality/label_join_expired``), never crash, and a join for an
    expired/unsampled rid is a counted miss. Joined pairs accumulate
    in a linear prediction histogram (the registry bucket-error math
    applies unchanged); every ``FLAGS_quality_min_events`` joined rows
    the window's COPC/calibration is evaluated against the drift
    baseline and alarms land in every attached registry (the replica's
    instance Monitor rides the ``metrics_snapshot`` scrape)."""

    def __init__(self, registries: Sequence[Any] = (), *,
                 clock: Callable[[], float] = time.time,
                 num_buckets: int = 1 << 12):
        self._lock = threading.Lock()
        self._regs = list(registries)
        self._clock = clock
        self._pending: "OrderedDict[str, Tuple[float, np.ndarray]]" = \
            OrderedDict()
        self._table = np.zeros((2, num_buckets), np.float64)
        self._pred_sum = 0.0
        self._label_sum = 0.0
        self._count = 0.0
        self._win_base = (self._table.copy(), 0.0, 0.0, 0.0)
        self._drift = DriftDetector()
        self.alarms = 0

    def _bump(self, name: str, delta: int = 1) -> None:
        monitor.add(name, delta)
        for r in self._regs:
            r.add(name, delta)

    def _set(self, name: str, value: float) -> None:
        monitor.set_gauge(name, value)
        for r in self._regs:
            r.set_gauge(name, value)

    @staticmethod
    def _selected(rid: str, rate: float) -> bool:
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        return (zlib.crc32(rid.encode()) % 1000000) < rate * 1000000

    def sample(self, rid: str, preds: np.ndarray) -> bool:
        """Record one request's predictions for a later label join.
        Returns whether the rid was sampled."""
        rate = float(flags.flag("quality_sample_rate"))
        if not self._selected(rid, rate):
            return False
        now = self._clock()
        preds = np.asarray(preds, np.float64).ravel().copy()
        cap = max(1, int(flags.flag("quality_join_pending")))
        with self._lock:
            self._expire_locked(now)
            while len(self._pending) >= cap:
                self._pending.popitem(last=False)
                self._bump("quality/label_join_expired", 1)
            self._pending[rid] = (now, preds)
        self._bump("quality/sampled_rows", int(preds.size))
        return True

    def _expire_locked(self, now: float) -> None:
        window = float(flags.flag("quality_join_window_s"))
        expired = 0
        while self._pending:
            rid, (ts, _p) = next(iter(self._pending.items()))
            if now - ts <= window:
                break
            self._pending.popitem(last=False)
            expired += 1
        if expired:
            self._bump("quality/label_join_expired", expired)

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def join(self, rid: str, labels: np.ndarray) -> bool:
        """Late labels for a sampled request. Returns whether the join
        landed (False = never sampled, or expired out of the window)."""
        labels = np.asarray(labels, np.float64).ravel()
        with self._lock:
            self._expire_locked(self._clock())
            ent = self._pending.pop(rid, None)
            if ent is None:
                evaluate = False
            else:
                _ts, preds = ent
                m = min(preds.size, labels.size)
                preds, lab = preds[:m], labels[:m]
                nb = self._table.shape[1]
                bucket = np.clip((preds * nb).astype(np.int64), 0, nb - 1)
                pos = (lab > 0.5).astype(np.int64)
                np.add.at(self._table, (pos, bucket), 1.0)
                self._pred_sum += float(preds.sum())
                self._label_sum += float(lab.sum())
                self._count += float(m)
                evaluate = (self._count - self._win_base[3]
                            >= max(1, int(flags.flag("quality_min_events"))))
        if ent is None:
            self._bump("quality/label_join_miss", 1)
            return False
        self._bump("quality/label_joined", int(m))
        if evaluate:
            self.evaluate()
        return True

    def evaluate(self, force: bool = False) -> List[Dict[str, Any]]:
        """Close the current joined-label window: COPC + calibration
        error over it, drift-check, alarm, and publish gauges. Called
        automatically every ``FLAGS_quality_min_events`` joined rows;
        ``force`` evaluates whatever the window holds."""
        with self._lock:
            base_table, base_pred, base_label, base_count = self._win_base
            win_count = self._count - base_count
            if win_count <= 0 and not force:
                return []
            win_table = self._table - base_table
            win_pred = self._pred_sum - base_pred
            win_label = self._label_sum - base_label
            self._win_base = (self._table.copy(), self._pred_sum,
                              self._label_sum, self._count)
            copc = win_label / win_pred if win_pred > 0 else None
            cal_err = calibration_error_from_table(win_table)
            alarms: List[Dict[str, Any]] = []
            band = float(flags.flag("quality_copc_band"))
            if copc is not None and math.isfinite(copc):
                self._set("quality/copc", float(copc))
                a = self._drift.check(
                    "serving_copc", float(copc),
                    rel_tol=float(flags.flag("quality_copc_tol")))
                if a is None and band > 0 and abs(copc - 1.0) > band:
                    a = {"metric": "serving_copc",
                         "value": round(float(copc), 6),
                         "baseline": 1.0, "band": band}
                if a is not None:
                    a["kind"] = "copc"
                    alarms.append(a)
            self._set("quality/calibration_error", float(cal_err))
            a = self._drift.check(
                "serving_calibration_error", float(cal_err),
                rel_tol=float(flags.flag("quality_calibration_tol")),
                abs_floor=0.01, direction="up")
            if a is not None:
                a["kind"] = "calibration"
                alarms.append(a)
            summary: Dict[str, Any] = {
                "kind": "serving", "events": int(win_count),
                "copc": (round(float(copc), 6)
                         if copc is not None else None),
                "calibration_error": round(float(cal_err), 6),
            }
            bad = offending_buckets(
                log_bucket_table(win_table),
                tol=max(float(flags.flag("quality_copc_tol")), 0.2))
            if bad:
                summary["offending_buckets"] = bad
        for a in alarms:
            self._bump(f"quality/alarms/{a['kind']}", 1)
            log.warning("serving quality alarm [%s]: value=%s "
                        "baseline=%s", a["kind"], a.get("value"),
                        a.get("baseline"))
        if alarms:
            summary["alarms"] = alarms
            self.alarms += len(alarms)
        report.emit_quality_report("serving", summary)
        return alarms
