"""Declarative SLO alerting over the metric history ring.

The PaddleBox production discipline was named ``Monitor`` stats that a
human watched; this module grows them into *objectives* a controller
can consume (ROADMAP item 1's autoscaler/canary interface). Each
:class:`SLORule` names one signal in the history ring
(core/timeseries.py) and is evaluated with **multi-window burn-rate**
semantics every sampler tick:

- breach in the FAST window only       → ``pending`` (might be a blip)
- breach in fast AND slow windows      → ``firing``  (sustained burn)
- fast and slow clean for
  ``FLAGS_alerts_clear_windows`` ticks → ``resolved`` (hysteresis —
  one good sample never flaps a page), decaying to ``ok`` when a new
  breach cycle starts.

The default rule pack covers the signals the fleet already emits —
merged predict p99 vs ``FLAGS_serving_slo_p99_ms``, ``slo/violations``
error-budget burn, replica journal lag, event-to-servable freshness,
``quality/alarms/*`` deltas and the boundary-exchange overlap floor —
each gated on its threshold flag so an unset objective is simply not
evaluated. Outputs are machine-readable three ways: the
``alerts_active`` RPC (every framed server answers it), ``alert/<name>``
counters on each firing transition, and one ``alert_report {json}``
log line beside pass_report.

Containment contract (ROBUSTNESS.md ``alerts/evaluate``): the
evaluator runs on the sampler thread behind a faultpoint; a crash is
counted (``alerts/evaluate_errors``), warned, and retried next tick —
it can never take down a serving or training thread.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from paddlebox_tpu.core import faults, flags, log, monitor, timeseries, trace

STATES = ("ok", "pending", "firing", "resolved")
KINDS = ("quantile", "rate", "gauge", "delta")
SEVERITIES = ("page", "warn")
DIRECTIONS = ("above", "below")


@dataclasses.dataclass
class SLORule:
    """One objective over one history signal.

    ``threshold_flag`` (read at every evaluation, so an operator can
    retune a live fleet) overrides ``threshold`` when set; a resolved
    threshold of 0 with ``gate_on_threshold`` means the objective is
    unset and the rule is skipped entirely.
    """

    name: str
    metric: str
    kind: str = "quantile"          # quantile | rate | gauge | delta
    q: str = "p99"                  # quantile kind: which quantile
    threshold: float = 0.0
    threshold_flag: str = ""
    direction: str = "above"        # breach when value above/below
    burn: float = 1.0               # rate kind: burn-rate multiplier
    severity: str = "page"
    fast_window_s: float = 0.0      # 0 = FLAGS_alerts_fast_window_s
    slow_window_s: float = 0.0      # 0 = FLAGS_alerts_slow_window_s
    gate_on_threshold: bool = True  # skip rule while threshold <= 0

    def validate(self) -> List[str]:
        errs = []
        if not self.name:
            errs.append("rule with empty name")
        if not self.metric:
            errs.append(f"{self.name}: empty metric")
        if self.kind not in KINDS:
            errs.append(f"{self.name}: unknown kind {self.kind!r}")
        if self.direction not in DIRECTIONS:
            errs.append(f"{self.name}: unknown direction "
                        f"{self.direction!r}")
        if self.severity not in SEVERITIES:
            errs.append(f"{self.name}: unknown severity "
                        f"{self.severity!r}")
        if self.burn <= 0:
            errs.append(f"{self.name}: burn must be > 0")
        if (self.fast_window_s and self.slow_window_s
                and self.fast_window_s >= self.slow_window_s):
            errs.append(f"{self.name}: fast window must be shorter "
                        "than slow window")
        return errs

    # -- evaluation helpers ------------------------------------------------

    def resolved_threshold(self) -> float:
        if self.threshold_flag:
            v = flags.flag(self.threshold_flag)
            if isinstance(v, (int, float)) and float(v) > 0:
                return float(v)
            return 0.0 if self.gate_on_threshold else self.threshold
        return self.threshold

    def value(self, history: timeseries.MetricHistory,
              window_s: float) -> Optional[float]:
        if self.kind == "quantile":
            wq = history.window_quantiles(self.metric, window_s)
            v = wq.get(self.q)
            return float(v) if isinstance(v, (int, float)) else None
        if self.kind == "rate":
            return history.rate(self.metric, window_s)
        if self.kind == "delta":
            prefix = self.metric.endswith("*")
            name = self.metric[:-1] if prefix else self.metric
            return history.delta(name, window_s, prefix=prefix)
        v = history.latest(self.metric)  # gauge
        return float(v) if isinstance(v, (int, float)) else None

    def breached(self, value: Optional[float],
                 threshold: float) -> bool:
        if value is None:
            return False
        bar = threshold * self.burn if self.kind == "rate" else threshold
        if self.direction == "below":
            return value < bar
        # "delta" objectives with threshold 0 mean "any event is a
        # breach" (quality alarm bursts) — strict > keeps 0 clean.
        return value > bar


def default_rule_pack() -> List[SLORule]:
    """The objectives the fleet already has signals for. Every rule is
    threshold-flag gated: set the flag, get the objective —
    FLAGS_serving_slo_p99_ms (predict p99), FLAGS_alerts_violations_per_s
    (SLO-violation burn), FLAGS_alerts_replica_lag (fleet step lag),
    FLAGS_alerts_freshness_p99_ms (event→servable p99), and
    FLAGS_alerts_overlap_floor (boundary exchange overlap floor)."""
    return [
        SLORule(name="serving_predict_p99",
                metric="serving/predict_ms", kind="quantile", q="p99",
                threshold_flag="serving_slo_p99_ms", severity="page"),
        SLORule(name="slo_violation_burn",
                metric="slo/violations", kind="rate",
                threshold_flag="alerts_violations_per_s",
                severity="page"),
        SLORule(name="replica_lag_p99",
                metric="multihost/replica_lag_p99", kind="gauge",
                threshold_flag="alerts_replica_lag", severity="page"),
        SLORule(name="stream_freshness_p99",
                metric="stream/event_to_servable_ms", kind="quantile",
                q="p99", threshold_flag="alerts_freshness_p99_ms",
                severity="warn"),
        SLORule(name="quality_alarm_burst",
                metric="quality/alarms/*", kind="delta", threshold=0.0,
                severity="warn", gate_on_threshold=False),
        SLORule(name="boundary_overlap_floor",
                metric="pass/train_boundary_exchange_overlap_frac",
                kind="gauge", direction="below",
                threshold_flag="alerts_overlap_floor", severity="warn"),
    ]


def validate_rules(rules: List[SLORule]) -> List[str]:
    errs: List[str] = []
    seen: Dict[str, int] = {}
    for r in rules:
        errs.extend(r.validate())
        seen[r.name] = seen.get(r.name, 0) + 1
    errs.extend(f"duplicate rule name {n!r}" for n, c in seen.items()
                if c > 1)
    return errs


@dataclasses.dataclass
class AlertState:
    rule: SLORule
    state: str = "ok"
    since: float = 0.0          # ts of the last state transition
    clean_evals: int = 0
    fired: int = 0              # firing transitions over lifetime
    value_fast: Optional[float] = None
    value_slow: Optional[float] = None
    threshold: float = 0.0

    def summary(self) -> Dict[str, Any]:
        r = self.rule
        return {"name": r.name, "state": self.state,
                "severity": r.severity, "metric": r.metric,
                "kind": r.kind, "direction": r.direction,
                "value_fast": self.value_fast,
                "value_slow": self.value_slow,
                "threshold": self.threshold, "since": self.since,
                "fired": self.fired}


class AlertEngine:
    """Evaluates a rule pack against ONE history every tick and runs
    the PENDING→FIRING→RESOLVED machine per rule. Registered as a
    sampler callback by :func:`init_from_flags`; tests drive
    ``evaluate(now=...)`` directly on planted histories."""

    def __init__(self, history: Optional[timeseries.MetricHistory] = None,
                 rules: Optional[List[SLORule]] = None, *,
                 clock: Callable[[], float] = time.time,
                 on_page: Optional[Callable[[Dict[str, Any]], Any]] = None):
        self._history = history
        self._rules = list(default_rule_pack() if rules is None
                           else rules)
        errs = validate_rules(self._rules)
        if errs:
            raise ValueError("invalid alert rule pack: "
                             + "; ".join(errs))
        self._alerts = {r.name: AlertState(r) for r in self._rules}
        self._clock = clock
        self._lock = threading.Lock()
        self._on_page = on_page

    @property
    def rules(self) -> List[SLORule]:
        return list(self._rules)

    def _resolve_history(self) -> Optional[timeseries.MetricHistory]:
        if self._history is not None:
            return self._history
        return timeseries.history_for(create=False)

    # -- evaluation --------------------------------------------------------

    def evaluate_safe(self, now: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
        """The sampler-callback entry: contained per the ROBUSTNESS.md
        ``alerts/evaluate`` row — count, warn, retry next tick."""
        try:
            return self.evaluate(now)
        except Exception as e:  # noqa: BLE001 - containment contract
            monitor.add("alerts/evaluate_errors", 1)
            log.warning("alerts: evaluation failed (retried next "
                        "tick): %r", e)
            return []

    def evaluate(self, now: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
        """One pass over every rule; returns the transitions
        ``[{name, from, to, ...summary}]`` that happened."""
        faults.faultpoint("alerts/evaluate")
        ts = float(self._clock() if now is None else now)
        history = self._resolve_history()
        if history is None or len(history) < 2:
            return []
        fast_d = float(flags.flag("alerts_fast_window_s"))
        slow_d = float(flags.flag("alerts_slow_window_s"))
        clear_n = max(int(flags.flag("alerts_clear_windows")), 1)
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            monitor.add("alerts/evaluations", 1)
            for rule in self._rules:
                st = self._alerts[rule.name]
                threshold = rule.resolved_threshold()
                if rule.gate_on_threshold and threshold <= 0:
                    continue
                fast = rule.fast_window_s or fast_d
                slow = rule.slow_window_s or slow_d
                vf = rule.value(history, fast)
                vs = rule.value(history, slow)
                bf = rule.breached(vf, threshold)
                bs = rule.breached(vs, threshold)
                st.value_fast = vf
                st.value_slow = vs
                st.threshold = threshold
                new = self._step(st, bf, bs, clear_n)
                if new != st.state:
                    old, st.state, st.since = st.state, new, ts
                    if new == "firing":
                        st.fired += 1
                    transitions.append({"from": old, "to": new,
                                        **st.summary()})
            firing = sum(1 for a in self._alerts.values()
                         if a.state == "firing")
            pending = sum(1 for a in self._alerts.values()
                          if a.state == "pending")
        monitor.GLOBAL.set_gauge("alerts/firing", float(firing))
        monitor.GLOBAL.set_gauge("alerts/pending", float(pending))
        for t in transitions:
            self._publish(t)
        return transitions

    @staticmethod
    def _step(st: AlertState, bf: bool, bs: bool, clear_n: int) -> str:
        state = st.state
        if state in ("ok", "resolved", "pending"):
            st.clean_evals = 0
            if bf and bs:
                return "firing"
            if bf:
                return "pending"
            return "ok" if state == "pending" else state
        # firing: hysteresis — both windows clean for clear_n
        # consecutive evaluations before resolving.
        if not bf and not bs:
            st.clean_evals += 1
            if st.clean_evals >= clear_n:
                return "resolved"
        else:
            st.clean_evals = 0
        return "firing"

    def _publish(self, t: Dict[str, Any]) -> None:
        line = json.dumps(t, default=str)
        if t["to"] == "firing":
            monitor.add(f"alert/{t['name']}", 1)
            log.warning("alert_report %s", line)
            trace.instant(f"alert/{t['name']}", state="firing",
                          severity=t["severity"])
            if t["severity"] == "page":
                if self._on_page is not None:
                    self._on_page(t)
                else:
                    from paddlebox_tpu.core import incident
                    incident.trigger(f"alert:{t['name']}",
                                     context={"alert": t})
        else:
            log.info("alert_report %s", line)
            trace.instant(f"alert/{t['name']}", state=t["to"])

    # -- queries -----------------------------------------------------------

    def active(self, include_ok: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            out = [a.summary() for a in self._alerts.values()
                   if include_ok or a.state != "ok"]
        order = {"firing": 0, "pending": 1, "resolved": 2, "ok": 3}
        out.sort(key=lambda a: (order[a["state"]], a["name"]))
        return out

    def state(self, name: str) -> str:
        with self._lock:
            return self._alerts[name].state

    def firing_count(self) -> int:
        with self._lock:
            return sum(1 for a in self._alerts.values()
                       if a.state == "firing")


# -- process-global engine ----------------------------------------------------

GLOBAL: Optional[AlertEngine] = None
_LOCK = threading.Lock()


def enabled() -> bool:
    return GLOBAL is not None


def active_alerts(include_ok: bool = False) -> List[Dict[str, Any]]:
    eng = GLOBAL
    return eng.active(include_ok) if eng is not None else []


def firing_count() -> int:
    eng = GLOBAL
    return eng.firing_count() if eng is not None else 0


def init_from_flags() -> bool:
    """Arm the process-global engine over the global history when
    FLAGS_alerts_enable is set: ensures the sampler runs and registers
    ``evaluate_safe`` as its tick callback. Idempotent."""
    global GLOBAL
    if not flags.flag("alerts_enable"):
        return GLOBAL is not None
    with _LOCK:
        if GLOBAL is None:
            GLOBAL = AlertEngine()
        timeseries.GLOBAL_SAMPLER.add_callback(
            "alerts", GLOBAL.evaluate_safe)
    timeseries.init_from_flags()
    return True


def shutdown() -> None:
    """Disarm (tests/bench): drop the global engine and its sampler
    callback; the sampler itself is left to its owner."""
    global GLOBAL
    with _LOCK:
        timeseries.GLOBAL_SAMPLER.remove_callback("alerts")
        GLOBAL = None
