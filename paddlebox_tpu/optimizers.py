"""Dense optimizer library (role of ``operators/optimizers/`` +
``python/paddle/optimizer``): sgd/momentum/adam/adamw/lars/lamb, built on
optax (the idiomatic JAX optimizer stack) with a string factory mirroring
the reference's optimizer selection, plus grad clipping and LR schedules.
"""

from __future__ import annotations

from typing import Optional

import optax


def make_optimizer(name: str, learning_rate, *, weight_decay: float = 0.0,
                   momentum: float = 0.9, b1: float = 0.9, b2: float = 0.999,
                   eps: float = 1e-8, clip_norm: Optional[float] = None,
                   ) -> optax.GradientTransformation:
    """Factory by name; lars/lamb cover the reference's large-batch ops
    (``operators/optimizers/lars_momentum_op``, ``lamb_op``)."""
    name = name.lower()
    if name == "sgd":
        tx = optax.sgd(learning_rate)
    elif name == "momentum":
        tx = optax.sgd(learning_rate, momentum=momentum)
    elif name == "adam":
        tx = optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    elif name == "adamw":
        tx = optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps,
                         weight_decay=weight_decay)
    elif name == "lars":
        tx = optax.lars(learning_rate, weight_decay=weight_decay,
                        momentum=momentum)
    elif name == "lamb":
        tx = optax.lamb(learning_rate, b1=b1, b2=b2, eps=eps,
                        weight_decay=weight_decay)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if clip_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
    return tx


def warmup_cosine(peak_lr: float, warmup_steps: int, total_steps: int,
                  end_lr: float = 0.0) -> optax.Schedule:
    """Standard BERT/GPT pretraining schedule (role of
    paddle.optimizer.lr.* schedules)."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak_lr, warmup_steps=warmup_steps,
        decay_steps=total_steps, end_value=end_lr)


def warmup_linear(peak_lr: float, warmup_steps: int, total_steps: int
                  ) -> optax.Schedule:
    return optax.join_schedules([
        optax.linear_schedule(0.0, peak_lr, warmup_steps),
        optax.linear_schedule(peak_lr, 0.0, total_steps - warmup_steps),
    ], [warmup_steps])
