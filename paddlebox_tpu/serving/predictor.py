"""Online-serving predictor over xbox model exports.

Role of the inference half of the reference stack for the CTR production
loop (SURVEY.md L12 — `paddle/fluid/inference/` is scoped to serving the
trained artifacts): the training side ships per-pass **xbox** exports
(``save_xbox_base_model``, fleet_util.py:774 — {key → emb, w} only, no
optimizer state) and the online service answers prediction requests from
them. Here: load the xbox npz (any store tier wrote it — host, sharded,
or device), build a device-resident serving table (fused [rows, D+1]
record + native key index), and run a jitted batch forward.

TPU-first: the serving lookup is the same pass-table machinery as
training — host key→row map (C++ hash, native/store.cc), one device
gather, jitted model forward in bf16 — so a model served here is
bit-compatible with what training evaluated.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.core import log, monitor
from paddlebox_tpu.native import store_py as native_store


def load_xbox_model(path: str, table: str = "embedding"
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(keys, emb [n, D], w [n]) from an xbox export directory — flat
    (`<table>.xbox.npz`) or sharded (`bucket-*/` / `part-*/`
    subdirectories are concatenated; all shards carry the same width).

    Dim-grouped exports (mixed-width models write `dim<D>/` subdirs with
    per-group table names `<base>_dim<D>`) hold INCOMPATIBLE widths —
    load each group separately:
    ``load_xbox_model(f"{path}/dim8", table=f"{table}_dim8")``.
    """
    flat = os.path.join(path, f"{table}.xbox.npz")
    if os.path.exists(flat):
        data = np.load(flat)
        return (data["keys"].astype(np.uint64), data["emb"], data["w"])
    dim_parts = sorted(d for d in os.listdir(path)
                       if os.path.isdir(os.path.join(path, d))
                       and d.startswith("dim"))
    if dim_parts:
        raise ValueError(
            f"{path} is a dim-grouped export ({dim_parts}) — groups have "
            f"different embedding widths; load each with "
            f"load_xbox_model(path/dim<D>, table='{table}_dim<D>')")
    parts = sorted(
        d for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d))
        and (d.startswith("bucket-") or d.startswith("part-")))
    if not parts:
        raise FileNotFoundError(f"no xbox export for {table!r} under {path}")
    ks, es, ws = [], [], []
    for d in parts:
        k, e, w = load_xbox_model(os.path.join(path, d), table)
        ks.append(k)
        es.append(e)
        ws.append(w)
    return np.concatenate(ks), np.concatenate(es), np.concatenate(ws)


class CTRPredictor:
    """Batch CTR inference over an xbox-exported sparse model + dense
    params (role of the inference engine serving a BoxPS-trained model).

    ``model`` is the same functional model the trainer used (DeepFM,
    WideDeep, ...); ``dense_params`` its trained dense pytree. Unknown
    feasigns serve zero embeddings (a feature the trainer never saw
    contributes nothing — the reference's serving tier does the same for
    evicted/unseen keys).
    """

    def __init__(self, model, feed_config, keys: np.ndarray,
                 emb: np.ndarray, w: np.ndarray, dense_params,
                 *, compute_dtype: str = "bfloat16",
                 data_norm_slot_dim: int = -1):
        self.model = model
        self.feed = feed_config
        # Must match the trainer's TrainerConfig.data_norm_slot_dim for
        # data_norm-trained models — the show-skip zeroing is part of
        # the forward.
        self._dn_slot_dim = int(data_norm_slot_dim)
        order = np.argsort(keys, kind="stable")
        self._index = native_store.KeyIndex()
        rows, n_new = self._index.upsert(
            np.ascontiguousarray(keys[order], np.uint64))
        if n_new != keys.shape[0]:
            raise ValueError("duplicate keys in xbox export")
        d = emb.shape[1]
        # Fused serving record [emb | w], one zero row appended for
        # unknown keys (row == n).
        fused = np.zeros((keys.shape[0] + 1, d + 1), np.float32)
        fused[:-1, :d] = emb[order]
        fused[:-1, d] = w[order]
        self._table = jnp.asarray(fused)
        self._dense_params = dense_params
        self._dim = d
        self._cdt = dict(float32=jnp.float32,
                         bfloat16=jnp.bfloat16)[compute_dtype]
        self._slot_names = [s.name for s in feed_config.sparse_slots]
        # Jitted forwards keyed by (caps, batch_size): the traced slicing
        # closes over them, so a batch with different shapes needs its
        # own trace — reusing the first would mis-slice silently.
        self._fwd_cache: Dict[tuple, object] = {}

    @classmethod
    def from_dirs(cls, model, feed_config, xbox_path: str,
                  dense_path: Optional[str] = None, *,
                  table: str = "embedding", dense_params=None,
                  dense_template=None, **kw) -> "CTRPredictor":
        """Load from a training run's artifacts: the xbox sparse export +
        a dense checkpoint (``checkpoint.dense.save_pytree`` format, with
        ``dense_template`` = a freshly-init'd param pytree)."""
        keys, emb, w = load_xbox_model(xbox_path, table)
        if dense_params is None:
            if dense_path is None or dense_template is None:
                raise ValueError(
                    "need dense_params, or dense_path + dense_template")
            from paddlebox_tpu.checkpoint.dense import load_pytree
            dense_params = load_pytree(dense_template, dense_path)
        return cls(model, feed_config, keys, emb, w, dense_params, **kw)

    def _build_fwd(self, caps: Dict[str, int], bs: int):
        model = self.model
        d = self._dim
        cdt = self._cdt
        names = self._slot_names

        def cast(t):
            return jax.tree.map(
                lambda x: x.astype(cdt)
                if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, t)

        dn_slot_dim = self._dn_slot_dim

        def fwd(table, params, rows, segments, dense_feats):
            # data_norm-trained models (TrainerConfig.data_norm):
            # normalize exactly as the trainer's forward does — the
            # SAME shared helper, f32 stats, before the compute cast —
            # or served probabilities diverge from training.
            from paddlebox_tpu.ops.data_norm import (
                normalize_dense_and_strip)
            params, dense_feats = normalize_dense_and_strip(
                params, dense_feats, slot_dim=dn_slot_dim)
            picked = table[rows]                      # [sum caps, D+1]
            off = 0
            emb: Dict[str, jax.Array] = {}
            w: Dict[str, jax.Array] = {}
            for nme in names:
                sl = slice(off, off + caps[nme])
                emb[nme] = cast(picked[sl, :d])
                w[nme] = cast(picked[sl, d])
                off += caps[nme]
            logits = model.apply(cast(params), emb, w, segments,
                                 batch_size=bs,
                                 dense_feats=cast(dense_feats))
            return jax.nn.sigmoid(logits.astype(jnp.float32))

        return jax.jit(fwd)

    def predict(self, batch) -> np.ndarray:
        """SlotBatch -> CTR probabilities [batch_size] (invalid/padding
        rows yield whatever the model does on zeros — mask with
        batch.valid if needed)."""
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        caps = {n: batch.ids[n].shape[0] for n in self._slot_names}
        bs = batch.batch_size
        key = (tuple(sorted(caps.items())), bs)
        fwd = self._fwd_cache.get(key)
        if fwd is None:
            fwd = self._fwd_cache[key] = self._build_fwd(caps, bs)
        all_ids = np.concatenate(
            [batch.ids[n] for n in self._slot_names])
        rows = self._index.lookup(all_ids)
        n_tab = self._table.shape[0] - 1
        rows = np.where(rows < 0, n_tab, rows).astype(np.int32)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in self._slot_names}
        monitor.add("serving/requests", bs)
        probs = fwd(self._table, self._dense_params,
                    jnp.asarray(rows), segs,
                    jnp.asarray(_concat_dense_host(batch)))
        return np.asarray(probs)
