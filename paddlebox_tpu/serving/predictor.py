"""Online-serving predictor over xbox model exports.

Role of the inference half of the reference stack for the CTR production
loop (SURVEY.md L12 — `paddle/fluid/inference/` is scoped to serving the
trained artifacts): the training side ships per-pass **xbox** exports
(``save_xbox_base_model``, fleet_util.py:774 — {key → emb, w} only, no
optimizer state) and the online service answers prediction requests from
them. Here: load the xbox npz (any store tier wrote it — host, sharded,
or device), build a device-resident serving table (fused [rows, D+1]
record + native key index), and run a jitted batch forward.

TPU-first: the serving lookup is the same pass-table machinery as
training — host key→row map (C++ hash, native/store.cc), one device
gather, jitted model forward in bf16 — so a model served here is
bit-compatible with what training evaluated.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.native import store_py as native_store
from paddlebox_tpu.ops.data_norm import normalize_dense_and_strip


def _load_export(path: str, table: str, kind: str
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Shared (keys, emb, w) loader for serving artifacts of ``kind``
    ('xbox' | 'delta'): flat ``<table>.<kind>.npz``, sharded
    (``bucket-*/`` / ``part-*/`` concatenated), dim-grouped roots
    rejected (per-group widths are incompatible). Quantized embeddings
    (FLAGS_xbox_quant_bits at save time: symmetric per-row intN * f32
    scale) dequantize to f32 transparently."""
    flat = os.path.join(path, f"{table}.{kind}.npz")
    if os.path.exists(flat):
        data = np.load(flat)
        if "emb_q" in data:
            emb = (data["emb_q"].astype(np.float32)
                   * data["emb_scale"][:, None])
        else:
            emb = data["emb"]
        return (data["keys"].astype(np.uint64), emb, data["w"])
    dim_parts = sorted(d for d in os.listdir(path)
                       if os.path.isdir(os.path.join(path, d))
                       and d.startswith("dim"))
    if dim_parts:
        raise ValueError(
            f"{path} is a dim-grouped export ({dim_parts}) — groups have "
            f"different embedding widths; load each group with "
            f"table='{table}_dim<D>' under path/dim<D>")
    parts = sorted(
        d for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d))
        and (d.startswith("bucket-") or d.startswith("part-")))
    if not parts:
        raise FileNotFoundError(
            f"no {kind} export for {table!r} under {path}")
    ks, es, ws = [], [], []
    for d in parts:
        k, e, w = _load_export(os.path.join(path, d), table, kind)
        ks.append(k)
        es.append(e)
        ws.append(w)
    return np.concatenate(ks), np.concatenate(es), np.concatenate(ws)


def load_xbox_model(path: str, table: str = "embedding"
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(keys, emb [n, D], w [n]) from an xbox export directory — see
    :func:`_load_export` for the layouts handled."""
    return _load_export(path, table, "xbox")


def load_delta_update(path: str, table: str = "embedding"
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(keys, emb, w) from a per-pass delta checkpoint — the serving
    fields only, for :meth:`CTRPredictor.apply_update`. Same layouts as
    :func:`load_xbox_model` (see :func:`_load_export`)."""
    return _load_export(path, table, "delta")


def load_serving_predictor(model, feed_config, path: str,
                           **kw) -> "CTRPredictor":
    """Stand a predictor up from a ``CTRTrainer.export_serving`` dir:
    meta.json names the table and whether the dense snapshot carries
    data_norm stats — the template is built to MATCH (a plain
    ``model.init`` template would silently drop those stats, and
    ``load_pytree`` ignores extra file keys, so the predictor would
    serve un-normalized probabilities with no error)."""
    import jax as _jax

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    template = dict(model.init(_jax.random.PRNGKey(0)))
    if meta.get("data_norm"):
        from paddlebox_tpu.ops.data_norm import data_norm_init
        template["data_norm"] = data_norm_init(int(meta["dense_dim"]))
    kw.setdefault("data_norm_slot_dim",
                  int(meta.get("data_norm_slot_dim", -1)))
    kw.setdefault("compute_dtype", meta.get("compute_dtype", "bfloat16"))
    if kw["compute_dtype"] not in ("bfloat16", "float32"):
        kw["compute_dtype"] = "bfloat16"
    return CTRPredictor.from_dirs(
        model, feed_config, os.path.join(path, "xbox"),
        os.path.join(path, "dense.npz"),
        table=str(meta.get("table", "embedding")),
        dense_template=template, **kw)


class CTRPredictor:
    """Batch CTR inference over an xbox-exported sparse model + dense
    params (role of the inference engine serving a BoxPS-trained model).

    ``model`` is the same functional model the trainer used (DeepFM,
    WideDeep, ...); ``dense_params`` its trained dense pytree. Unknown
    feasigns serve zero embeddings (a feature the trainer never saw
    contributes nothing — the reference's serving tier does the same for
    evicted/unseen keys).
    """

    def __init__(self, model, feed_config, keys: np.ndarray,
                 emb: np.ndarray, w: np.ndarray, dense_params,
                 *, compute_dtype: str = "bfloat16",
                 data_norm_slot_dim: int = -1):
        self.model = model
        self.feed = feed_config
        # Must match the trainer's TrainerConfig.data_norm_slot_dim for
        # data_norm-trained models — the show-skip zeroing is part of
        # the forward.
        self._dn_slot_dim = int(data_norm_slot_dim)
        order = np.argsort(keys, kind="stable")
        self._index = native_store.KeyIndex()
        rows, n_new = self._index.upsert(
            np.ascontiguousarray(keys[order], np.uint64))
        if n_new != keys.shape[0]:
            raise ValueError("duplicate keys in xbox export")
        d = emb.shape[1]
        # Fused serving record [emb | w], one zero row appended for
        # unknown keys (row == n).
        fused = np.zeros((keys.shape[0] + 1, d + 1), np.float32)
        fused[:-1, :d] = emb[order]
        fused[:-1, d] = w[order]
        self._table = jnp.asarray(fused)
        self._dense_params = dense_params
        self._dim = d
        self._cdt = dict(float32=jnp.float32,
                         bfloat16=jnp.bfloat16)[compute_dtype]
        self._slot_names = [s.name for s in feed_config.sparse_slots]
        # Jitted forwards keyed by (caps, batch_size): the traced slicing
        # closes over them, so a batch with different shapes needs its
        # own trace — reusing the first would mis-slice silently.
        self._fwd_cache: Dict[tuple, object] = {}
        # Serializes apply_update against predict's index lookup + state
        # snapshot: KeyIndex is not internally synchronized (a concurrent
        # upsert can rehash under a reader), and (table, index, dense)
        # must be swapped as one consistent version.
        self._lock = threading.Lock()

    @classmethod
    def from_dirs(cls, model, feed_config, xbox_path: str,
                  dense_path: Optional[str] = None, *,
                  table: str = "embedding", dense_params=None,
                  dense_template=None, **kw) -> "CTRPredictor":
        """Load from a training run's artifacts: the xbox sparse export +
        a dense checkpoint (``checkpoint.dense.save_pytree`` format, with
        ``dense_template`` = a freshly-init'd param pytree)."""
        keys, emb, w = load_xbox_model(xbox_path, table)
        if dense_params is None:
            if dense_path is None or dense_template is None:
                raise ValueError(
                    "need dense_params, or dense_path + dense_template")
            from paddlebox_tpu.checkpoint.dense import load_pytree
            dense_params, _step = load_pytree(dense_template, dense_path)
        return cls(model, feed_config, keys, emb, w, dense_params, **kw)

    def _build_fwd(self, caps: Dict[str, int], bs: int):
        model = self.model
        d = self._dim
        cdt = self._cdt
        names = self._slot_names

        def cast(t):
            return jax.tree.map(
                lambda x: x.astype(cdt)
                if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, t)

        dn_slot_dim = self._dn_slot_dim

        def fwd(table, params, rows, segments, dense_feats):
            # data_norm-trained models (TrainerConfig.data_norm):
            # normalize exactly as the trainer's forward does — the
            # SAME shared helper, f32 stats, before the compute cast —
            # or served probabilities diverge from training.
            params, dense_feats = normalize_dense_and_strip(
                params, dense_feats, slot_dim=dn_slot_dim)
            picked = table[rows]                      # [sum caps, D+1]
            off = 0
            emb: Dict[str, jax.Array] = {}
            w: Dict[str, jax.Array] = {}
            for nme in names:
                sl = slice(off, off + caps[nme])
                emb[nme] = cast(picked[sl, :d])
                w[nme] = cast(picked[sl, d])
                off += caps[nme]
            logits = model.apply(cast(params), emb, w, segments,
                                 batch_size=bs,
                                 dense_feats=cast(dense_feats))
            return jax.nn.sigmoid(logits.astype(jnp.float32))

        return jax.jit(fwd)

    def apply_update(self, keys: np.ndarray, emb: np.ndarray,
                     w: np.ndarray, *, dense_params=None) -> int:
        """Apply a per-pass update to the LIVE serving table — the
        reference's online patch-model flow (``README.md:48``
        "real-time model update": per-pass delta/xbox exports land on
        serving without a cold reload). Existing keys' rows are
        overwritten in place, new keys appended (the zero trash row for
        unknown feasigns stays last); optionally swap the dense params
        in the same call. Returns the number of new keys.

        Thread-safe against concurrent predict(): the (index, table,
        dense) triple swaps as one version under the predictor lock."""
        k = np.ascontiguousarray(keys, np.uint64)
        # The null feasign (0) never serves — KeyIndex maps it to row -1
        # and a -1 scatter would wrap onto the trash row, corrupting the
        # zeros every unknown key reads.
        nz = k != 0
        if not nz.all():
            k = k[nz]
            emb, w = np.asarray(emb)[nz], np.asarray(w)[nz]
        if k.shape[0] == 0:
            if dense_params is not None:
                with self._lock:
                    self._dense_params = dense_params
            return 0
        if emb.shape[1] != self._dim:
            raise ValueError(
                f"update width {emb.shape[1]} != serving table width "
                f"{self._dim}")
        # Keep the LAST occurrence of duplicate keys (a stream of
        # updates applies in order; scatter with dup indices would be
        # order-nondeterministic).
        _, last = np.unique(k[::-1], return_index=True)
        keep = np.sort(k.shape[0] - 1 - last)
        k = k[keep]
        vals = np.concatenate(
            [np.asarray(emb, np.float32)[keep],
             np.asarray(w, np.float32)[keep][:, None]], axis=1)
        with self._lock:
            n_old = self._table.shape[0] - 1
            # Read-only lookup FIRST: the fallible device allocations
            # (concat/scatter) must complete before the index mutates,
            # or an exception would leave index and table permanently
            # out of sync (every later update then mis-splices).
            looked = self._index.lookup(k)
            new_mask = looked < 0
            n_new = int(new_mask.sum())
            table = self._table
            if n_new:
                # upsert (below) assigns fresh rows [n_old, n_old+n_new)
                # in input order; splice them in — pre-filled with their
                # values — BEFORE the trash row.
                grow = vals[new_mask]
                table = jnp.concatenate(
                    [table[:-1], jnp.asarray(grow),
                     jnp.zeros((1, self._dim + 1), jnp.float32)])
            ex_rows, ex_vals = looked[~new_mask], vals[~new_mask]
            if ex_rows.size:
                # Scatter only the EXISTING keys' rows (fresh rows were
                # written via the splice — re-scattering them would pay
                # a second full-table materialization for nothing).
                table = table.at[jnp.asarray(ex_rows, jnp.int32)].set(
                    jnp.asarray(ex_vals))
            if n_new:
                rows, got_new = self._index.upsert(k)
                if got_new != n_new or not np.array_equal(
                        rows[new_mask],
                        n_old + np.arange(n_new)):
                    raise RuntimeError(
                        "serving index assignment diverged from the "
                        "spliced table layout")
            self._table = table
            if dense_params is not None:
                self._dense_params = dense_params
        monitor.add("serving/updated_keys", int(k.shape[0]))
        monitor.add("serving/new_keys", int(n_new))
        return int(n_new)

    def predict(self, batch) -> np.ndarray:
        """SlotBatch -> CTR probabilities [batch_size] (invalid/padding
        rows yield whatever the model does on zeros — mask with
        batch.valid if needed)."""
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        caps = {n: batch.ids[n].shape[0] for n in self._slot_names}
        bs = batch.batch_size
        key = (tuple(sorted(caps.items())), bs)
        fwd = self._fwd_cache.get(key)
        if fwd is None:
            fwd = self._fwd_cache[key] = self._build_fwd(caps, bs)
        all_ids = np.concatenate(
            [batch.ids[n] for n in self._slot_names])
        with self._lock:
            # One consistent model version per batch: lookup + table +
            # dense snapshot under the update lock (jax arrays are
            # immutable, so the compute below needs no lock).
            rows = self._index.lookup(all_ids)
            table, dense_params = self._table, self._dense_params
        n_tab = table.shape[0] - 1
        rows = np.where(rows < 0, n_tab, rows).astype(np.int32)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in self._slot_names}
        monitor.add("serving/requests", bs)
        probs = fwd(table, dense_params,
                    jnp.asarray(rows), segs,
                    jnp.asarray(_concat_dense_host(batch)))
        return np.asarray(probs)
