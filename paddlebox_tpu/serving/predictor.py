"""Online-serving predictor over xbox model exports.

Role of the inference half of the reference stack for the CTR production
loop (SURVEY.md L12 — `paddle/fluid/inference/` is scoped to serving the
trained artifacts): the training side ships per-pass **xbox** exports
(``save_xbox_base_model``, fleet_util.py:774 — {key → emb, w} only, no
optimizer state) and the online service answers prediction requests from
them. Here: load the xbox npz (any store tier wrote it — host, sharded,
or device), build a device-resident serving table (fused [rows, D+1]
record + native key index), and run a jitted batch forward.

TPU-first: the serving lookup is the same pass-table machinery as
training — host key→row map (C++ hash, native/store.cc), one device
gather, jitted model forward in bf16 — so a model served here is
bit-compatible with what training evaluated.

Two capacity regimes:

- **Flat** (default): the whole fused table lives in HBM, one gather
  per batch — the small-model fast path.
- **Tiered** (``FLAGS_serving_hbm_rows`` < table rows): the BoxPS
  memory hierarchy reproduced for inference — hot rows in a fixed-size
  HBM array (admitted by observed access frequency), warm rows in a
  host-RAM CLOCK cache (``embedding/cache.py``), cold rows in disk
  shards (``embedding/ssd_tier.py``). A predict resolves HBM misses
  from the lower tiers into a per-batch staging array fed to the SAME
  jitted forward; misses are batch-promoted HBM-ward off the predict
  critical path ("Dissecting Embedding Bag Performance in DLRM
  Inference": the gather path dominates, so the hot set must live in
  device memory and the warm set in RAM).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddlebox_tpu.core import faults, flags, log, monitor
from paddlebox_tpu.embedding.cache import HostRowCache
from paddlebox_tpu.embedding.ssd_tier import DiskShards
from paddlebox_tpu.native import store_py as native_store
from paddlebox_tpu.ops.data_norm import normalize_dense_and_strip


def _load_export(path: str, table: str, kind: str
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Shared (keys, emb, w) loader for serving artifacts of ``kind``
    ('xbox' | 'delta'): flat ``<table>.<kind>.npz``, sharded
    (``bucket-*/`` / ``part-*/`` concatenated), dim-grouped roots
    rejected (per-group widths are incompatible). Quantized embeddings
    (FLAGS_xbox_quant_bits at save time: symmetric per-row intN * f32
    scale) dequantize to f32 transparently."""
    flat = os.path.join(path, f"{table}.{kind}.npz")
    if os.path.exists(flat):
        data = np.load(flat)
        if "emb_q" in data:
            emb = (data["emb_q"].astype(np.float32)
                   * data["emb_scale"][:, None])
        else:
            emb = data["emb"]
        return (data["keys"].astype(np.uint64), emb, data["w"])
    dim_parts = sorted(d for d in os.listdir(path)
                       if os.path.isdir(os.path.join(path, d))
                       and d.startswith("dim"))
    if dim_parts:
        raise ValueError(
            f"{path} is a dim-grouped export ({dim_parts}) — groups have "
            f"different embedding widths; load each group with "
            f"table='{table}_dim<D>' under path/dim<D>")
    parts = sorted(
        d for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d))
        and (d.startswith("bucket-") or d.startswith("part-")))
    if not parts:
        raise FileNotFoundError(
            f"no {kind} export for {table!r} under {path}")
    ks, es, ws = [], [], []
    for d in parts:
        k, e, w = _load_export(os.path.join(path, d), table, kind)
        ks.append(k)
        es.append(e)
        ws.append(w)
    return np.concatenate(ks), np.concatenate(es), np.concatenate(ws)


def load_xbox_model(path: str, table: str = "embedding"
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(keys, emb [n, D], w [n]) from an xbox export directory — see
    :func:`_load_export` for the layouts handled."""
    return _load_export(path, table, "xbox")


def load_delta_update(path: str, table: str = "embedding"
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(keys, emb, w) from a per-pass delta checkpoint — the serving
    fields only, for :meth:`CTRPredictor.apply_update`. Same layouts as
    :func:`load_xbox_model` (see :func:`_load_export`)."""
    return _load_export(path, table, "delta")


def grouped_export_dims(path: str) -> List[int]:
    """Width groups of a dim-grouped export root (``dim8/``, ``dim32/``
    subdirs — the GroupedStore checkpoint layout); [] for flat."""
    if not os.path.isdir(path):
        return []
    dims = []
    for d in sorted(os.listdir(path)):
        if d.startswith("dim") and d[3:].isdigit() and \
                os.path.isdir(os.path.join(path, d)):
            dims.append(int(d[3:]))
    return sorted(dims)


def load_grouped_export(path: str, table: str = "embedding",
                        kind: str = "xbox"
                        ) -> Dict[int, Tuple[np.ndarray, np.ndarray,
                                             np.ndarray]]:
    """Per-width-group (keys, emb, w) from a dim-grouped export root:
    ``<path>/dim<D>/<table>_dim<D>.<kind>.npz`` per group (the
    GroupedEngine table naming). A group whose subdir lacks this kind
    (e.g. a delta that touched only one width) is skipped."""
    out: Dict[int, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
    for d in grouped_export_dims(path):
        sub = os.path.join(path, f"dim{d}")
        try:
            out[d] = _load_export(sub, f"{table}_dim{d}", kind)
        except FileNotFoundError:
            continue
    if not out:
        raise FileNotFoundError(
            f"no dim-grouped {kind} export for {table!r} under {path}")
    return out


def load_serving_predictor(model, feed_config, path: str,
                           **kw) -> "CTRPredictor":
    """Stand a predictor up from a ``CTRTrainer.export_serving`` dir:
    meta.json names the table and whether the dense snapshot carries
    data_norm stats — the template is built to MATCH (a plain
    ``model.init`` template would silently drop those stats, and
    ``load_pytree`` ignores extra file keys, so the predictor would
    serve un-normalized probabilities with no error)."""
    import jax as _jax

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    template = dict(model.init(_jax.random.PRNGKey(0)))
    if meta.get("data_norm"):
        from paddlebox_tpu.ops.data_norm import data_norm_init
        template["data_norm"] = data_norm_init(int(meta["dense_dim"]))
    kw.setdefault("data_norm_slot_dim",
                  int(meta.get("data_norm_slot_dim", -1)))
    kw.setdefault("compute_dtype", meta.get("compute_dtype", "bfloat16"))
    if kw["compute_dtype"] not in ("bfloat16", "float32"):
        kw["compute_dtype"] = "bfloat16"
    return CTRPredictor.from_dirs(
        model, feed_config, os.path.join(path, "xbox"),
        os.path.join(path, "dense.npz"),
        table=str(meta.get("table", "embedding")),
        dense_template=template, **kw)


def _pow2(n: int, floor: int = 8) -> int:
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


def _splice_scatter(table: jax.Array, grow: jax.Array,
                    ex_rows: jax.Array, ex_vals: jax.Array) -> jax.Array:
    """ONE fused device program for the delta hot-swap: splice appended
    rows in before the trash row AND overwrite existing rows' values in
    the same dispatch. Under jit, XLA fuses the scatter into the
    concatenated output buffer, so a delta pays one new-table
    allocation — the separate concat-then-scatter it replaces
    materialized the full table twice (and paused predicts for the
    extra multi-×-table-size allocation spike)."""
    width = table.shape[1]
    out = jnp.concatenate(
        [table[:-1], grow, jnp.zeros((1, width), table.dtype)])
    return out.at[ex_rows].set(ex_vals)


_splice_scatter_jit = jax.jit(_splice_scatter)


def _dedup_update(keys: np.ndarray, emb: np.ndarray, w: np.ndarray,
                  dim: int) -> Tuple[np.ndarray, np.ndarray]:
    """Shared update preprocessing: drop the null feasign (0 — KeyIndex
    maps it to row -1, and a -1 scatter would wrap onto the trash row),
    keep the LAST occurrence of duplicate keys (updates apply in
    order; dup-index scatter is order-nondeterministic), and fuse
    [emb | w]. Returns (keys, fused vals) — possibly empty."""
    k = np.ascontiguousarray(keys, np.uint64)
    nz = k != 0
    if not nz.all():
        k = k[nz]
        emb, w = np.asarray(emb)[nz], np.asarray(w)[nz]
    if k.shape[0] and emb.shape[1] != dim:
        raise ValueError(
            f"update width {emb.shape[1]} != serving table width {dim}")
    if k.shape[0] == 0:
        return k, np.zeros((0, dim + 1), np.float32)
    _, last = np.unique(k[::-1], return_index=True)
    keep = np.sort(k.shape[0] - 1 - last)
    k = k[keep]
    vals = np.concatenate(
        [np.asarray(emb, np.float32)[keep],
         np.asarray(w, np.float32)[keep][:, None]], axis=1)
    return k, vals


def _apply_flat_update(index, table: jax.Array, k: np.ndarray,
                       vals: np.ndarray) -> Tuple[jax.Array, int]:
    """Land a deduped update on one flat serving table + its KeyIndex
    (callers hold the owning predictor's lock): ONE fused splice+scatter
    dispatch, then the index upsert — read-only lookup FIRST so a
    failed device dispatch cannot leave index and table out of sync.
    Returns (new table, n_new)."""
    n_old = table.shape[0] - 1
    looked = index.lookup(k)
    new_mask = looked < 0
    n_new = int(new_mask.sum())
    grow = vals[new_mask]
    ex_rows = looked[~new_mask]
    ex_vals = vals[~new_mask]
    # One dispatch, one allocation: splice the appended rows in
    # (pre-filled with their values) and scatter the existing keys'
    # rows in the SAME fused program. No donation: a concurrent predict
    # may still hold the old table (it snapshots under the lock,
    # computes outside it) — the old version stays alive until its last
    # reader drops it.
    out = _splice_scatter_jit(
        table, jnp.asarray(grow, jnp.float32),
        jnp.asarray(ex_rows, jnp.int32),
        jnp.asarray(ex_vals, jnp.float32))
    if n_new:
        rows, got_new = index.upsert(k)
        if got_new != n_new or not np.array_equal(
                rows[new_mask], n_old + np.arange(n_new)):
            raise RuntimeError(
                "serving index assignment diverged from the spliced "
                "table layout")
    return out, n_new


class ServingTierStore:
    """The hierarchical serving table behind a tiered CTRPredictor.

    Tiers are EXCLUSIVE (a key lives in exactly one — the
    TieredFeatureStore invariant): hot keys map to rows of one
    fixed-capacity device array ``table`` ([hbm_cap + 1, width]; the
    last row is the zero trash row unknown/null feasigns read), warm
    keys live in a :class:`HostRowCache`, cold keys in
    :class:`DiskShards` (point-read via :meth:`DiskShards.read`;
    tier moves use the removing ``take``).

    NOT internally locked: every method runs under the owning
    predictor's lock — including :meth:`promote_locked`, which the
    promote worker calls with that lock held, keeping the per-request
    path free of promotion work.
    """

    FIELD = "v"
    # Promote once this many miss ACCESSES accumulate (not unique keys:
    # frequency is the admission signal, so hot misses trip it sooner).
    PROMOTE_EVERY = 2048

    def __init__(self, keys_sorted: np.ndarray, vals: np.ndarray,
                 hbm_cap: int, *, cache_rows: Optional[int] = None,
                 cache_dir: Optional[str] = None, backing=None):
        self.width = int(vals.shape[1])
        self.hbm_cap = int(hbm_cap)
        n = int(keys_sorted.shape[0])
        self.total_keys = n
        n_hot = min(self.hbm_cap, n)
        dev = np.zeros((self.hbm_cap + 1, self.width), np.float32)
        dev[:n_hot] = vals[:n_hot]
        self.table = jnp.asarray(dev)
        # Initial admission is arbitrary (first n_hot by key order) —
        # the frequency-driven promote cycle re-ranks it from live
        # traffic.
        self._hot_keys = keys_sorted[:n_hot].copy()      # sorted asc
        self._hot_rows = np.arange(n_hot, dtype=np.int32)
        self._free_rows = list(range(n_hot, self.hbm_cap))
        self._hits = np.zeros((self.hbm_cap,), np.int64)
        self._miss_counts: Dict[int, int] = {}
        self._miss_accesses = 0
        if cache_rows is None:
            cache_rows = int(flags.flag("serving_host_cache_rows"))
        # ``backing`` (a fleet ShardBackedStore, or anything with its
        # read()/num_features()/close() surface) replaces the private
        # disk tier with the SHARED shard tier: cold misses resolve by
        # pure-read RPC, warm evictions just drop (the backing row is
        # authoritative and re-readable), and local tiers are COPIES
        # that shadow the shared rows rather than exclusive owners.
        self.backing = backing
        self._own_dir = None
        if backing is not None:
            self.disk = None
        else:
            cdir = cache_dir or str(flags.flag("serving_cache_dir"))
            if not cdir:
                cdir = tempfile.mkdtemp(prefix="serving_cold_")
                self._own_dir = cdir
            self.disk = DiskShards(cdir, num_buckets=16)
        self.warm = HostRowCache(self.width, capacity=max(cache_rows, 0),
                                 on_evict=self._spill)
        if n > n_hot:
            self.warm.put_rows(keys_sorted[n_hot:], vals[n_hot:])

    def _spill(self, keys: np.ndarray, vals: np.ndarray) -> None:
        if self.disk is None:
            # Shard-backed: the shared tier still holds every row a
            # replica ever read — an evicted warm copy is just dropped.
            monitor.add("serving/cache_dropped", int(keys.shape[0]))
            return
        self.disk.write(keys, {self.FIELD: vals})
        monitor.add("serving/cache_spilled", int(keys.shape[0]))

    def local_keys_locked(self) -> int:
        """Rows materialized in this replica's local tiers (hot + warm;
        the shard-backed mode's num_keys surface — the shared tier's
        own count is the backing's num_features()). Caller holds the
        owning predictor's lock, like every other method here."""
        return int(self._hot_keys.shape[0]) + len(self.warm)

    def close(self) -> None:
        if self.backing is not None:
            self.backing.close()
            self.backing = None
        if self._own_dir:
            shutil.rmtree(self._own_dir, ignore_errors=True)
            self._own_dir = None

    # -- lookup ------------------------------------------------------------

    def lookup(self, ids: np.ndarray, *, resolve: bool = True
               ) -> Tuple[np.ndarray, np.ndarray, int]:
        """ids [n] uint64 → (rows [n] int32, staging values
        [stage, width], stage). Rows < hbm_cap+1 index ``table`` (the
        trash row for null/unknown); rows >= hbm_cap+1 index the
        staging array, filled from the warm/cold tiers for this batch.
        ``stage`` is pow2-bucketed so the jitted forward's trace count
        stays bounded; 0 = no misses (pure-HBM batch).

        ``resolve=False`` is the DEGRADED admission path: HBM hot rows
        only — misses read the zero trash row (the default-init row the
        predictor serves for unknown keys) with no warm/cold/backing
        work and no promotion accounting, so a shed request costs one
        searchsorted and one device gather."""
        ids = np.asarray(ids, np.uint64)
        # graftlint: allow-lock(caller-serialized: lookup runs under the predictor lock, same lock promote_locked mutates under)
        n_hot = self._hot_keys.shape[0]
        rows = np.full(ids.shape, self.hbm_cap, np.int32)
        if n_hot:
            pos = np.searchsorted(self._hot_keys, ids)
            pos_c = np.minimum(pos, n_hot - 1)
            hot_hit = (self._hot_keys[pos_c] == ids) & (ids != 0)
            # graftlint: allow-lock(caller-serialized: lookup runs under the predictor lock, same lock promote_locked mutates under)
            hit_rows = self._hot_rows[pos_c[hot_hit]]
            rows[hot_hit] = hit_rows
            np.add.at(self._hits, hit_rows, 1)
        else:
            hot_hit = np.zeros(ids.shape, bool)
        monitor.add("serving/cache_hbm_hits", int(hot_hit.sum()))
        miss_sel = ~hot_hit & (ids != 0)
        if not resolve:
            monitor.add("serving/degraded_rows", int(miss_sel.sum()))
            return rows, np.zeros((1, self.width), np.float32), 0
        if not miss_sel.any():
            return rows, np.zeros((1, self.width), np.float32), 0
        uniq, inv, cnt = np.unique(ids[miss_sel], return_inverse=True,
                                   return_counts=True)
        vals = np.zeros((uniq.shape[0], self.width), np.float32)
        wvals, whit = self.warm.get_rows(uniq)
        vals[whit] = wvals[whit]
        monitor.add("serving/cache_host_hits", int(cnt[whit].sum()))
        cold = ~whit
        if cold.any() and self.backing is not None:
            bfound, bvals = self.backing.read(uniq[cold])
            idx = np.flatnonzero(cold)
            vals[idx[bfound]] = bvals[bfound]
            monitor.add("serving/cache_backing_hits",
                        int(cnt[idx[bfound]].sum()))
            monitor.add("serving/cache_unknown",
                        int(cnt[idx[~bfound]].sum()))
        elif cold.any():
            cfound, cvals = self.disk.read(uniq[cold])
            idx = np.flatnonzero(cold)
            if cvals:
                vals[idx[cfound]] = cvals[self.FIELD][cfound]
            monitor.add("serving/cache_ssd_hits",
                        int(cnt[idx[cfound]].sum()))
            monitor.add("serving/cache_unknown",
                        int(cnt[idx[~cfound]].sum()))
        # Admission accounting: access FREQUENCY per missed key (the
        # cheap host-side counter the promote cycle ranks by).
        for k, c in zip(uniq, cnt):
            ki = int(k)
            # graftlint: allow-lock(caller-serialized: lookup runs under the predictor lock, same lock promote_locked mutates under)
            self._miss_counts[ki] = self._miss_counts.get(ki, 0) + int(c)
        # graftlint: allow-lock(caller-serialized: lookup runs under the predictor lock, same lock promote_locked mutates under)
        self._miss_accesses += int(cnt.sum())
        stage = _pow2(uniq.shape[0])
        miss_arr = np.zeros((stage, self.width), np.float32)
        miss_arr[:uniq.shape[0]] = vals
        rows[miss_sel] = (self.hbm_cap + 1 + inv).astype(np.int32)
        return rows, miss_arr, stage

    def promote_due(self) -> bool:
        return self._miss_accesses >= self.PROMOTE_EVERY

    # -- tier movement -----------------------------------------------------

    def _take_from_lower(self, keys: np.ndarray
                         ) -> Tuple[np.ndarray, np.ndarray]:
        """Remove ``keys`` from warm-then-cold, returning (found [n],
        vals [n, width]) — the promotion read (exclusive tiers: rows
        moving HBM-ward leave their old tier). Shard-backed mode reads
        COPIES from the shared tier instead of taking (replicas never
        mutate it; the local hot row shadows the backing row)."""
        found, vals = self.warm.pop_rows(keys)
        need = ~found
        if need.any() and self.backing is not None:
            order = np.argsort(keys[need], kind="stable")
            idx = np.flatnonzero(need)[order]
            bfound, bvals = self.backing.read(keys[idx])
            vals[idx[bfound]] = bvals[bfound]
            found[idx[bfound]] = True
        elif need.any():
            dk, dv = self.disk.take(keys[need])
            if dk.size:
                where = {int(k): i for i, k in enumerate(dk)}
                for i in np.flatnonzero(need):
                    j = where.get(int(keys[i]))
                    if j is not None:
                        vals[i] = dv[self.FIELD][j]
                        found[i] = True
        return found, vals

    def promote_locked(self) -> int:
        """One batched promotion cycle (runs under the predictor lock,
        OFF the request path): admit the most-frequent missed keys into
        HBM — free rows first, then displacing hot rows whose observed
        hit count is lower — with ONE device scatter for the whole
        batch; displaced rows move to the warm tier. Returns rows
        promoted."""
        faults.faultpoint("serving/cache_promote")
        self._miss_accesses = 0
        if not self._miss_counts:
            return 0
        cand = sorted(self._miss_counts.items(), key=lambda kv: -kv[1])
        self._miss_counts = {}
        k_max = max(64, self.hbm_cap // 16)   # bound one cycle's swap
        cand = cand[:k_max]
        ck = np.asarray([k for k, _ in cand], np.uint64)
        cc = np.asarray([c for _, c in cand], np.int64)
        found, cvals = self._take_from_lower(ck)
        ck, cc, cvals = ck[found], cc[found], cvals[found]
        if ck.size == 0:
            return 0
        target_rows: list = []
        admit: list = []
        n_free = min(len(self._free_rows), ck.size)
        for i in range(n_free):
            target_rows.append(self._free_rows.pop())
            admit.append(i)
        evict_entries: list = []
        if ck.size > n_free and self._hot_keys.size:
            order = np.argsort(self._hits[self._hot_rows],
                               kind="stable")
            for j, cand_i in enumerate(range(n_free, ck.size)):
                if j >= order.size:
                    break
                entry = int(order[j])
                row = int(self._hot_rows[entry])
                # Admission by frequency: only displace a hot row a
                # missed key out-ran since the last cycle.
                if int(cc[cand_i]) <= int(self._hits[row]):
                    break
                evict_entries.append(entry)
                target_rows.append(row)
                admit.append(cand_i)
        if not admit:
            # Nothing out-ranked the resident set: the fetched
            # candidates go back to the warm tier.
            self.warm.put_rows(ck, cvals)
            return 0
        admit_a = np.asarray(admit, np.int64)
        rows_a = np.asarray(target_rows, np.int32)
        keep_unadmitted = np.setdiff1d(np.arange(ck.size), admit_a)
        if keep_unadmitted.size:
            self.warm.put_rows(ck[keep_unadmitted],
                               cvals[keep_unadmitted])
        if evict_entries:
            ev = np.asarray(evict_entries, np.int64)
            ev_rows = self._hot_rows[ev]
            ev_vals = np.asarray(self.table[jnp.asarray(ev_rows)])
            self.warm.put_rows(self._hot_keys[ev], ev_vals)
            keep = np.ones(self._hot_keys.shape[0], bool)
            keep[ev] = False
            self._hot_keys = self._hot_keys[keep]
            self._hot_rows = self._hot_rows[keep]
        # ONE scatter admits the whole batch.
        self.table = self.table.at[jnp.asarray(rows_a)].set(
            jnp.asarray(cvals[admit_a]))
        new_keys = np.concatenate([self._hot_keys, ck[admit_a]])
        new_rows = np.concatenate([self._hot_rows,
                                   rows_a.astype(np.int32)])
        order = np.argsort(new_keys, kind="stable")
        self._hot_keys = new_keys[order]
        self._hot_rows = new_rows[order]
        # Fresh admits start with the frequency that earned the slot —
        # a zeroed counter would make them the next cycle's victims.
        self._hits[rows_a] = cc[admit_a]
        monitor.add("serving/cache_promoted", int(admit_a.size))
        return int(admit_a.size)

    # -- updates -----------------------------------------------------------

    def update(self, keys: np.ndarray, vals: np.ndarray) -> int:
        """Apply a delta to whichever tier holds each key (hot rows in
        one device scatter; the rest lands warm, with stale disk copies
        removed for exclusivity). New keys insert into the warm tier —
        admission to HBM stays frequency-driven. Returns new keys."""
        n_hot = self._hot_keys.shape[0]
        if n_hot:
            pos = np.searchsorted(self._hot_keys, keys)
            pos_c = np.minimum(pos, n_hot - 1)
            hot_hit = self._hot_keys[pos_c] == keys
            if hot_hit.any():
                rows = self._hot_rows[pos_c[hot_hit]]
                # graftlint: allow-lock(caller-serialized: update runs under the predictor lock, same lock promote_locked mutates under)
                self.table = self.table.at[jnp.asarray(rows)].set(
                    jnp.asarray(vals[hot_hit], jnp.float32))
        else:
            hot_hit = np.zeros(keys.shape, bool)
        rest = ~hot_hit
        n_new = 0
        if rest.any() and self.backing is not None:
            # Shared tier: a delta only needs to land on the rows THIS
            # replica has materialized (hot scatter above, warm
            # overwrite here). Everything else is bypassed — the
            # training side already pushed those rows into the shard
            # tier, and the next miss reads the fresh value. This is
            # what lets the publisher land a delta once per replica's
            # hot/warm set instead of once per full model copy.
            rk, rv = keys[rest], vals[rest]
            in_warm = self.warm.contains(rk)
            if in_warm.any():
                self.warm.put_rows(rk[in_warm], rv[in_warm])
            monitor.add("serving/delta_bypassed", int((~in_warm).sum()))
        elif rest.any():
            rk, rv = keys[rest], vals[rest]
            in_warm = self.warm.contains(rk)
            if (~in_warm).any():
                dk, _ = self.disk.take(rk[~in_warm])
                n_new = int((~in_warm).sum()) - int(dk.shape[0])
            self.warm.put_rows(rk, rv)
        self.total_keys += n_new
        return n_new


class CTRPredictor:
    """Batch CTR inference over an xbox-exported sparse model + dense
    params (role of the inference engine serving a BoxPS-trained model).

    ``model`` is the same functional model the trainer used (DeepFM,
    WideDeep, ...); ``dense_params`` its trained dense pytree. Unknown
    feasigns serve zero embeddings (a feature the trainer never saw
    contributes nothing — the reference's serving tier does the same for
    evicted/unseen keys).
    """

    def __init__(self, model, feed_config, keys: np.ndarray,
                 emb: np.ndarray, w: np.ndarray, dense_params,
                 *, compute_dtype: str = "bfloat16",
                 data_norm_slot_dim: int = -1,
                 hbm_rows: Optional[int] = None,
                 host_cache_rows: Optional[int] = None,
                 cache_dir: Optional[str] = None,
                 shard_backing=None):
        self.model = model
        self.feed = feed_config
        # Must match the trainer's TrainerConfig.data_norm_slot_dim for
        # data_norm-trained models — the show-skip zeroing is part of
        # the forward.
        self._dn_slot_dim = int(data_norm_slot_dim)
        d = emb.shape[1]
        self._dim = d
        order = np.argsort(keys, kind="stable")
        keys_sorted = np.ascontiguousarray(keys[order], np.uint64)
        if keys_sorted.size and (np.diff(keys_sorted) == 0).any():
            raise ValueError("duplicate keys in xbox export")
        if hbm_rows is None:
            hbm_rows = int(flags.flag("serving_hbm_rows"))
        if shard_backing is not None and hbm_rows <= 0:
            raise ValueError(
                "shard-backed serving is tiered by construction: pass "
                "hbm_rows > 0 (or set FLAGS_serving_hbm_rows)")
        if shard_backing is not None or 0 < hbm_rows < keys_sorted.shape[0]:
            fused_vals = np.concatenate(
                [np.asarray(emb, np.float32)[order],
                 np.asarray(w, np.float32)[order][:, None]], axis=1)
            self._tiers: Optional[ServingTierStore] = ServingTierStore(
                keys_sorted, fused_vals, hbm_rows,
                cache_rows=host_cache_rows, cache_dir=cache_dir,
                backing=shard_backing)
            self._table = self._tiers.table
            self._index = None
            log.vlog(0, "serving: tiered table — %d keys, %d HBM rows%s",
                     keys_sorted.shape[0], hbm_rows,
                     " (shard-backed)" if shard_backing is not None
                     else "")
        else:
            self._tiers = None
            self._index = native_store.KeyIndex()
            _rows, n_new = self._index.upsert(keys_sorted)
            if n_new != keys.shape[0]:
                raise ValueError("duplicate keys in xbox export")
            # Fused serving record [emb | w], one zero row appended for
            # unknown keys (row == n).
            fused = np.zeros((keys.shape[0] + 1, d + 1), np.float32)
            fused[:-1, :d] = emb[order]
            fused[:-1, d] = w[order]
            self._table = jnp.asarray(fused)
        self._dense_params = dense_params
        self._cdt = dict(float32=jnp.float32,
                         bfloat16=jnp.bfloat16)[compute_dtype]
        self._slot_names = [s.name for s in feed_config.sparse_slots]
        # Jitted forwards keyed by (caps, batch_size, staging rows): the
        # traced slicing closes over them. Callers that pack through
        # serving/batcher.py only ever present pow2-bucketed shapes, so
        # the cache stays O(log max_rows); a caller packing exact shapes
        # pays one trace per distinct shape (the pre-r14 behavior).
        self._fwd_cache: Dict[tuple, object] = {}
        # One fixed dummy staging array for stage-0 (flat / all-hot)
        # forwards: constant shape, so it never forces a retrace.
        self._zero_miss = jnp.zeros((1, d + 1), jnp.float32)
        # Serializes apply_update / tier promotion against predict's
        # index lookup + state snapshot: KeyIndex is not internally
        # synchronized (a concurrent upsert can rehash under a reader),
        # and (table, index/tiers, dense) must swap as one version.
        self._lock = threading.Lock()
        self._promote_stop = threading.Event()
        self._promote_wake = threading.Event()
        self._promote_thread: Optional[threading.Thread] = None
        if self._tiers is not None:
            # Promotion runs on its own thread so a predict only ever
            # pays the counter bump — the batched tier moves happen
            # between requests, under the same lock.
            self._promote_thread = threading.Thread(
                target=self._promote_loop, daemon=True,
                name="serving-promote")
            self._promote_thread.start()

    @classmethod
    def from_dirs(cls, model, feed_config, xbox_path: str,
                  dense_path: Optional[str] = None, *,
                  table: str = "embedding", dense_params=None,
                  dense_template=None, **kw) -> "CTRPredictor":
        """Load from a training run's artifacts: the xbox sparse export +
        a dense checkpoint (``checkpoint.dense.save_pytree`` format, with
        ``dense_template`` = a freshly-init'd param pytree). A
        dim-grouped export root (``dim8/``, ``dim32/`` — the dynamic-mf
        GroupedStore layout) builds a :class:`GroupedCTRPredictor`, so
        one replica serves mixed-width slots."""
        if dense_params is None:
            if dense_path is None or dense_template is None:
                raise ValueError(
                    "need dense_params, or dense_path + dense_template")
            from paddlebox_tpu.checkpoint.dense import load_pytree
            dense_params, _step = load_pytree(dense_template, dense_path)
        if grouped_export_dims(xbox_path):
            groups = load_grouped_export(xbox_path, table, "xbox")
            return GroupedCTRPredictor(model, feed_config, groups,
                                       dense_params, table=table, **kw)
        keys, emb, w = load_xbox_model(xbox_path, table)
        return cls(model, feed_config, keys, emb, w, dense_params, **kw)

    # -- tier promotion ----------------------------------------------------

    def _promote_loop(self) -> None:
        while not self._promote_stop.is_set():
            self._promote_wake.wait(timeout=0.5)
            self._promote_wake.clear()
            if self._promote_stop.is_set():
                return
            if self._tiers is not None and self._tiers.promote_due():
                self.promote_now()

    def promote_now(self) -> int:
        """Run one promotion cycle immediately (the promote worker's
        body; tests drive it directly for determinism)."""
        if self._tiers is None:
            return 0
        with self._lock:
            n = self._tiers.promote_locked()
            self._table = self._tiers.table
        return n

    def close(self) -> None:
        """Stop the promote worker and drop the cold-tier temp dir
        (no-op for flat predictors)."""
        self._promote_stop.set()
        self._promote_wake.set()
        if self._promote_thread is not None:
            self._promote_thread.join(timeout=5.0)
            self._promote_thread = None
        if self._tiers is not None:
            self._tiers.close()

    @property
    def num_keys(self) -> int:
        """Keys served (all tiers) — the stats-RPC surface. Shard-backed
        replicas report their LOCALLY materialized rows (hot + warm);
        the shared tier's own count is the backing's num_features()."""
        if self._tiers is not None:
            if self._tiers.backing is not None:
                with self._lock:
                    return int(self._tiers.local_keys_locked())
            return int(self._tiers.total_keys)
        # graftlint: allow-lock(benign snapshot: jax arrays are immutable — a stale ref still answers with a consistent shape)
        return int(self._table.shape[0] - 1)

    # -- forward -----------------------------------------------------------

    def _build_fwd(self, caps: Dict[str, int], bs: int, stage: int):
        model = self.model
        d = self._dim
        cdt = self._cdt
        names = self._slot_names

        def cast(t):
            return jax.tree.map(
                lambda x: x.astype(cdt)
                if hasattr(x, "dtype") and x.dtype == jnp.float32 else x, t)

        dn_slot_dim = self._dn_slot_dim

        def fwd(table, miss, params, rows, segments, dense_feats):
            # data_norm-trained models (TrainerConfig.data_norm):
            # normalize exactly as the trainer's forward does — the
            # SAME shared helper, f32 stats, before the compute cast —
            # or served probabilities diverge from training.
            params, dense_feats = normalize_dense_and_strip(
                params, dense_feats, slot_dim=dn_slot_dim)
            if stage:
                # Tiered batch: rows past the device table index the
                # per-batch staging array (warm/cold values) — one
                # gather from each source, row-wise select.
                n_dev = table.shape[0]
                dev_rows = jnp.minimum(rows, n_dev - 1)
                st_rows = jnp.clip(rows - n_dev, 0, stage - 1)
                picked = jnp.where((rows < n_dev)[:, None],
                                   table[dev_rows], miss[st_rows])
            else:
                picked = table[rows]              # [sum caps, D+1]
            off = 0
            emb: Dict[str, jax.Array] = {}
            w: Dict[str, jax.Array] = {}
            for nme in names:
                sl = slice(off, off + caps[nme])
                emb[nme] = cast(picked[sl, :d])
                w[nme] = cast(picked[sl, d])
                off += caps[nme]
            logits = model.apply(cast(params), emb, w, segments,
                                 batch_size=bs,
                                 dense_feats=cast(dense_feats))
            return jax.nn.sigmoid(logits.astype(jnp.float32))

        return jax.jit(fwd)

    # -- updates -----------------------------------------------------------

    def apply_update(self, keys: np.ndarray, emb: np.ndarray,
                     w: np.ndarray, *, dense_params=None) -> int:
        """Apply a per-pass update to the LIVE serving table — the
        reference's online patch-model flow (``README.md:48``
        "real-time model update": per-pass delta/xbox exports land on
        serving without a cold reload). Existing keys' rows are
        overwritten in place, new keys appended (the zero trash row for
        unknown feasigns stays last); optionally swap the dense params
        in the same call. Returns the number of new keys.

        Thread-safe against concurrent predict(): the (index, table,
        dense) triple swaps as one version under the predictor lock.
        The flat-table path lands as ONE fused jitted splice+scatter
        dispatch (:func:`_splice_scatter`); the tiered path routes each
        key to the tier that owns it."""
        k, vals = _dedup_update(keys, emb, w, self._dim)
        if k.shape[0] == 0:
            if dense_params is not None:
                with self._lock:
                    self._dense_params = dense_params
            return 0
        with self._lock:
            if self._tiers is not None:
                n_new = self._tiers.update(k, vals)
                self._table = self._tiers.table
            else:
                self._table, n_new = _apply_flat_update(
                    self._index, self._table, k, vals)
            if dense_params is not None:
                self._dense_params = dense_params
        monitor.add("serving/updated_keys", int(k.shape[0]))
        monitor.add("serving/new_keys", int(n_new))
        return int(n_new)

    def apply_update_export(self, path: str, table: str = "embedding",
                            kind: str = "delta") -> int:
        """Apply an on-disk update export of either layout (the surface
        the delta RPC and the donefile publisher share): flat/sharded
        roots go through :meth:`apply_update`; dim-grouped roots are
        rejected here and handled by :class:`GroupedCTRPredictor`'s
        override — so a fleet of mixed-dim replicas and flat replicas
        tails the same donefile."""
        keys, emb, w = _load_export(path, table, kind)
        return self.apply_update(keys, emb, w)

    # -- predict -----------------------------------------------------------

    def predict(self, batch, *, degraded: bool = False) -> np.ndarray:
        """SlotBatch -> CTR probabilities [batch_size] (invalid/padding
        rows yield whatever the model does on zeros — mask with
        batch.valid if needed). ``degraded=True`` is the fleet router's
        SLO-shed path: a tiered table serves HBM hot rows only (misses
        read the default zero row, no warm/cold/backing resolution) —
        cheaper and approximate, flagged degraded in the RPC reply."""
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        caps = {n: batch.ids[n].shape[0] for n in self._slot_names}
        bs = batch.batch_size
        all_ids = np.concatenate(
            [batch.ids[n] for n in self._slot_names])
        with self._lock:
            # One consistent model version per batch: lookup + table +
            # dense snapshot under the update lock (jax arrays are
            # immutable, so the compute below needs no lock).
            if self._tiers is not None:
                rows, miss_arr, stage = self._tiers.lookup(
                    all_ids, resolve=not degraded)
                table, dense_params = self._table, self._dense_params
                miss = jnp.asarray(miss_arr) if stage else self._zero_miss
                promote_due = self._tiers.promote_due()
            else:
                looked = self._index.lookup(all_ids)
                table, dense_params = self._table, self._dense_params
                n_tab = table.shape[0] - 1
                rows = np.where(looked < 0, n_tab,
                                looked).astype(np.int32)
                miss, stage = self._zero_miss, 0
                promote_due = False
        if promote_due:
            self._promote_wake.set()
        key = (tuple(sorted(caps.items())), bs, stage)
        fwd = self._fwd_cache.get(key)
        if fwd is None:
            fwd = self._fwd_cache[key] = self._build_fwd(caps, bs, stage)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in self._slot_names}
        monitor.add("serving/requests", int(batch.num_valid))
        probs = fwd(table, miss, dense_params,
                    jnp.asarray(rows), segs,
                    jnp.asarray(_concat_dense_host(batch)))
        return np.asarray(probs)


class _ServingGroup:
    """One width group of a grouped serving table: its fused flat table
    ([n+1, dim+1], zero trash row last) + key index + member slots."""

    __slots__ = ("dim", "slots", "index", "table")

    def __init__(self, dim: int, slots: Tuple[str, ...],
                 keys: np.ndarray, emb: np.ndarray, w: np.ndarray):
        self.dim = int(dim)
        self.slots = slots
        order = np.argsort(keys, kind="stable")
        keys_sorted = np.ascontiguousarray(keys[order], np.uint64)
        self.index = native_store.KeyIndex()
        _rows, n_new = self.index.upsert(keys_sorted)
        if n_new != keys.shape[0]:
            raise ValueError(
                f"duplicate keys in dim{dim} xbox export")
        fused = np.zeros((keys.shape[0] + 1, self.dim + 1), np.float32)
        fused[:-1, :self.dim] = np.asarray(emb, np.float32)[order]
        fused[:-1, self.dim] = np.asarray(w, np.float32)[order]
        self.table = jnp.asarray(fused)


class GroupedCTRPredictor(CTRPredictor):
    """Serving over a dim-grouped (dynamic-mf) export: one flat table
    PER WIDTH GROUP, slots routed to their group's table — the serving
    twin of :class:`~paddlebox_tpu.embedding.grouped.GroupedEngine`
    (mixed 8/32/64-wide slots in one model, every array static-shape).
    A feasign appearing in slots of two widths serves an independent
    row per group, the same contract training has.

    The same ``predict``/``apply_update_export``/stats surface as the
    flat predictor, so the micro-batcher, the predict service, the
    donefile publisher, and the fleet router all work unchanged —
    one fleet serves mixed-dim and single-dim replicas side by side.
    Tiering is not supported for grouped tables (flat per-group HBM
    tables only)."""

    def __init__(self, model, feed_config,
                 groups: Dict[int, Tuple[np.ndarray, np.ndarray,
                                         np.ndarray]],
                 dense_params, *, table: str = "embedding",
                 slot_dims: Optional[Dict[str, int]] = None,
                 compute_dtype: str = "bfloat16",
                 data_norm_slot_dim: int = -1,
                 hbm_rows: Optional[int] = None, **_ignored):
        if hbm_rows:
            raise ValueError(
                "grouped serving tables are flat-per-group; tiering "
                "(hbm_rows) is not supported")
        self.model = model
        self.feed = feed_config
        self.table_name = table
        self._dn_slot_dim = int(data_norm_slot_dim)
        self._slot_names = [s.name for s in feed_config.sparse_slots]
        if slot_dims is None:
            md = getattr(model, "emb_dim", None)
            if hasattr(md, "items"):
                slot_dims = {s: int(d) for s, d in md.items()}
            elif isinstance(md, int) and len(groups) == 1:
                slot_dims = {s: md for s in self._slot_names}
            else:
                raise ValueError(
                    "cannot derive per-slot widths: pass slot_dims= or "
                    "use a model whose emb_dim is a per-slot mapping")
        self._slot_dims = {s: int(slot_dims[s]) for s in self._slot_names}
        want = sorted(set(self._slot_dims.values()))
        have = sorted(groups)
        if want != have:
            raise ValueError(
                f"export width groups {have} != model slot widths {want}")
        self._groups: Dict[int, _ServingGroup] = {}
        for d in have:
            slots = tuple(s for s in self._slot_names
                          if self._slot_dims[s] == d)
            k, e, w = groups[d]
            if e.shape[1] != d:
                raise ValueError(
                    f"dim{d} export has width {e.shape[1]}")
            self._groups[d] = _ServingGroup(d, slots, k, e, w)
        self._dim = max(have)     # stats surface: the widest group
        self._dense_params = dense_params
        self._cdt = dict(float32=jnp.float32,
                         bfloat16=jnp.bfloat16)[compute_dtype]
        self._fwd_cache: Dict[tuple, object] = {}
        self._lock = threading.Lock()
        # No tiers/promote worker for grouped tables; the base close()
        # and predict() branches read these.
        self._tiers = None
        self._index = None
        self._promote_stop = threading.Event()
        self._promote_wake = threading.Event()
        self._promote_thread = None
        log.vlog(0, "serving: grouped table — dims %s, %d keys", have,
                 self.num_keys)

    @property
    def num_keys(self) -> int:
        return int(sum(g.table.shape[0] - 1
                       for g in self._groups.values()))

    @property
    def dims(self) -> List[int]:
        return sorted(self._groups)

    # -- forward -----------------------------------------------------------

    def _build_fwd_grouped(self, caps: Dict[str, int], bs: int):
        model = self.model
        cdt = self._cdt
        names = self._slot_names
        dims = self._slot_dims
        dim_order = self.dims
        dn_slot_dim = self._dn_slot_dim

        def cast(t):
            return jax.tree.map(
                lambda x: x.astype(cdt)
                if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
                t)

        def fwd(tables, params, rows, segments, dense_feats):
            params, dense_feats = normalize_dense_and_strip(
                params, dense_feats, slot_dim=dn_slot_dim)
            emb: Dict[str, jax.Array] = {}
            w: Dict[str, jax.Array] = {}
            for nme in names:
                d = dims[nme]
                picked = tables[dim_order.index(d)][rows[nme]]
                emb[nme] = cast(picked[:, :d])
                w[nme] = cast(picked[:, d])
            logits = model.apply(cast(params), emb, w, segments,
                                 batch_size=bs,
                                 dense_feats=cast(dense_feats))
            return jax.nn.sigmoid(logits.astype(jnp.float32))

        return jax.jit(fwd)

    # -- predict -----------------------------------------------------------

    def predict(self, batch, *, degraded: bool = False) -> np.ndarray:
        """SlotBatch -> probabilities [batch_size]: per-slot row lookup
        in the slot's width group, one jitted forward over all group
        tables. ``degraded`` is accepted for router compatibility (flat
        group tables have no tiers to shed, so it is a no-op)."""
        from paddlebox_tpu.train.ctr_trainer import _concat_dense_host
        caps = {n: batch.ids[n].shape[0] for n in self._slot_names}
        bs = batch.batch_size
        rows: Dict[str, jax.Array] = {}
        with self._lock:
            tables = tuple(self._groups[d].table for d in self.dims)
            dense_params = self._dense_params
            for nme in self._slot_names:
                g = self._groups[self._slot_dims[nme]]
                looked = g.index.lookup(
                    np.ascontiguousarray(batch.ids[nme], np.uint64))
                n_tab = g.table.shape[0] - 1
                rows[nme] = jnp.asarray(
                    np.where(looked < 0, n_tab, looked).astype(np.int32))
        key = (tuple(sorted(caps.items())), bs)
        fwd = self._fwd_cache.get(key)
        if fwd is None:
            fwd = self._fwd_cache[key] = self._build_fwd_grouped(caps, bs)
        segs = {n: jnp.asarray(batch.segments[n])
                for n in self._slot_names}
        monitor.add("serving/requests", int(batch.num_valid))
        probs = fwd(tables, dense_params, rows, segs,
                    jnp.asarray(_concat_dense_host(batch)))
        return np.asarray(probs)

    # -- updates -----------------------------------------------------------

    def apply_update(self, keys, emb, w, *, dense_params=None) -> int:
        """A bare (keys, emb, w) update is routed by WIDTH — emb's
        column count names the target group unambiguously (each group
        has a distinct dim, and a feasign's row in another group is a
        different parameter)."""
        d = int(np.asarray(emb).shape[1])
        if d not in self._groups:
            raise ValueError(
                f"update width {d} matches no serving group "
                f"{self.dims}")
        return self.apply_group_update(d, keys, emb, w,
                                       dense_params=dense_params)

    def apply_group_update(self, dim: int, keys, emb, w, *,
                           dense_params=None) -> int:
        k, vals = _dedup_update(keys, emb, w, int(dim))
        with self._lock:
            g = self._groups[int(dim)]
            if k.shape[0]:
                g.table, n_new = _apply_flat_update(
                    g.index, g.table, k, vals)
            else:
                n_new = 0
            if dense_params is not None:
                self._dense_params = dense_params
        monitor.add("serving/updated_keys", int(k.shape[0]))
        monitor.add("serving/new_keys", int(n_new))
        return int(n_new)

    def apply_update_export(self, path: str, table: str = "embedding",
                            kind: str = "delta") -> int:
        """Dim-grouped delta root: apply each width group's export to
        its table (a group absent from the delta is untouched)."""
        n_new = 0
        for d, (k, e, w) in load_grouped_export(path, table, kind).items():
            n_new += self.apply_group_update(d, k, e, w)
        return n_new
