"""Zero-downtime model publication: donefile tail → live hot-swap.

Role of the consumer half of the reference's online-update pipeline
(``write_model_donefile`` / ``write_xbox_donefile`` produce, the
serving fleet consumes): the training day loop publishes every pass's
delta export through the atomic donefile index
(``checkpoint/protocol.py``); this watcher tails that index from a
serving replica and applies each newly published per-pass delta to the
live :class:`~paddlebox_tpu.serving.predictor.CTRPredictor` through
``apply_update`` — a training pass flows to serving with no restart,
no RPC, and no torn reads (apply_update swaps the model version under
the predictor lock, so every in-flight micro-batch sees exactly one
version).

Records present when the watcher starts are treated as the provenance
of the base model the operator already loaded and are skipped; only
records published AFTER startup hot-swap. Day-level base records
(pass_id == 0) are noted but not applied — a base reload is an operator
action (new replica / restart), not a delta patch.
"""

from __future__ import annotations

import threading
from typing import Optional, Set, Tuple

from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol
from paddlebox_tpu.core import faults, flags, log, monitor


class DonefilePublisher:
    """Tail a checkpoint root's donefile; hot-swap new deltas in."""

    def __init__(self, predictor, root: str, *,
                 table: str = "embedding",
                 poll_s: Optional[float] = None,
                 catch_up: bool = False):
        self.predictor = predictor
        self.table = table
        self._proto = CheckpointProtocol(root)
        self._poll_s = poll_s
        self._seen: Set[Tuple[str, int]] = set()
        if not catch_up:
            # The operator stood the replica up from these records'
            # model — re-applying them would be a no-op at best and a
            # rollback at worst (an older delta over a newer base).
            self._seen = {(r.day, r.pass_id) for r in
                          self._proto.records()}
        self.applied = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-publisher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            poll = self._poll_s
            if poll is None:
                poll = float(flags.flag("serving_publisher_poll_s"))
            self._stop.wait(timeout=max(poll, 0.05))

    # -- the tail ----------------------------------------------------------

    def poll_once(self) -> int:
        """Scan the donefile once; apply every unseen delta record in
        publication order. Returns deltas applied this scan. Tests and
        drills call this directly for determinism."""
        try:
            recs = self._proto.records()
        except (OSError, ValueError) as e:
            log.warning("serving publisher: donefile read failed: %s", e)
            return 0
        n = 0
        for rec in recs:
            if self._stop.is_set():
                break
            tag = (rec.day, rec.pass_id)
            if tag in self._seen:
                continue
            # Mark first: a record whose export is unreadable is
            # skipped forward, not retried forever — the next pass's
            # delta carries newer values for every key that matters.
            self._seen.add(tag)
            if rec.pass_id == 0:
                log.vlog(0, "serving publisher: base record %s/0 noted "
                         "(base reloads are operator actions)", rec.day)
                continue
            try:
                faults.faultpoint("serving/publisher_apply")
                # apply_update_export routes by layout: flat/sharded
                # roots through apply_update, dim-grouped roots through
                # the grouped predictor's per-group path — and a
                # shard-backed replica's tier store lands only the rows
                # it has locally materialized (hot scatter + warm
                # overwrite), since the shared shard tier already holds
                # the training push for everything else.
                n_new = self.predictor.apply_update_export(
                    rec.path, self.table, "delta")
                self.applied += 1
                n += 1
                monitor.add("serving/hotswap_applied", 1)
                log.vlog(0, "serving publisher: hot-swapped %s/%d "
                         "(%d new) from %s", rec.day,
                         rec.pass_id, int(n_new), rec.path)
            except Exception as e:
                self.errors += 1
                monitor.add("serving/hotswap_errors", 1)
                log.warning("serving publisher: delta %s/%d at %s "
                            "failed: %r — skipped", rec.day,
                            rec.pass_id, rec.path, e)
        return n

    # -- rollback ----------------------------------------------------------

    def rollback_to(self, rec) -> int:
        """Re-apply a PRIOR published record — the reverse gear the
        forward-only tail lacks. A base record (pass_id == 0) re-applies
        its full serving-format export, overwriting every row a bad
        delta (or a rolled-back canary base) touched; a delta record
        re-applies that delta. The swap is the same single-version
        ``apply_update`` hot-swap the forward path uses, so it is atomic
        under the predictor lock. Marks the record seen (the tail must
        not immediately re-apply it as new work) and bumps
        ``serving/hotswap_rollbacks``. Returns rows written.

        ``rec`` is a :class:`~paddlebox_tpu.checkpoint.protocol.
        DoneRecord` or anything with ``day``/``pass_id``/``path``."""
        kind = "xbox" if int(rec.pass_id) == 0 else "delta"
        n_new = self.predictor.apply_update_export(
            rec.path, self.table, kind)
        self._seen.add((str(rec.day), int(rec.pass_id)))
        monitor.add("serving/hotswap_rollbacks", 1)
        log.warning("serving publisher: ROLLED BACK to %s/%d (%s, "
                    "%d new rows) from %s", rec.day, int(rec.pass_id),
                    kind, int(n_new), rec.path)
        return int(n_new)
