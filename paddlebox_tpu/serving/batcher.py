"""Server-side ragged micro-batching for the predict service.

Role of the request-coalescing front end every production serving tier
grows (and the TPU shape discipline the Ragged Paged Attention paper
applies to variable-length requests): concurrent predict RPCs do NOT
each pay a device dispatch. Handler threads enqueue their parsed rows
and block on a slot; a single dispatcher thread drains everything
waiting every ``FLAGS_serving_batch_window_ms`` (or as soon as
``FLAGS_serving_batch_max_rows`` rows are queued), segment-packs all
waiting requests into ONE static-shape batch — the same capacity-
bucketed packing the trainer uses, with power-of-two row/capacity
buckets so the jitted-forward trace count stays O(log max_rows) instead
of one trace per distinct request shape — runs one device forward, and
demuxes per-request probability slices back to the blocked handlers.

Padding is explicit masked rows (``SlotBatch.pack`` pads with
``valid=False`` rows whose segments point at the discard row), never
synthesized fake svm lines: no parse work for padding, and a padding
row can never be confused with a real label-0 instance.

Per-request results are bit-identical to a one-request-at-a-time
dispatch: every model op downstream (segment pools, row-wise MLP) is
row-local, so a row's probability depends only on its own ids —
``tests/test_serving_batch.py`` pins exact equality across mixed
request sizes and capacity buckets.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import faults, flags, log, monitor, trace
from paddlebox_tpu.data.slots import DataFeedConfig, Instance, SlotBatch


def pow2_bucket(n: int, floor: int = 8) -> int:
    """Smallest power of two >= max(n, floor) — the shape-bucketing
    shared by batch rows and per-slot capacities (a pow2 ladder gives
    <= log2(max_rows) distinct jit traces; exact shapes gave one per
    distinct request mix)."""
    n = max(int(n), floor)
    return 1 << (n - 1).bit_length()


def bucket_capacities(feed: DataFeedConfig, bs: int) -> Dict[str, int]:
    """Per-slot value capacities for a ``bs``-row bucket: the trainer's
    ``sparse_capacity`` sizing, rounded up to a power of two. Derived
    from ``bs`` ALONE (not the batch's actual id counts) so the trace
    key is just the row bucket; a heavy-tailed request overflowing a
    capacity degrades to counted drops exactly like training packs do
    (``slot_overflow/<slot>``)."""
    return {s.name: pow2_bucket(feed.sparse_capacity(s, bs))
            for s in feed.sparse_slots}


def pack_bucketed(instances: Sequence[Instance], feed: DataFeedConfig
                  ) -> SlotBatch:
    """Pack instances at pow2-bucketed shapes (rows AND capacities) with
    masked padding rows — the shape-stable pack both the micro-batcher
    and the inline (batching-off) predict path share."""
    bs = pow2_bucket(len(instances))
    return SlotBatch.pack(instances, feed, batch_size=bs,
                          capacities=bucket_capacities(feed, bs))


class _Pending:
    """One enqueued request: parsed instances + the slot its handler
    thread blocks on."""

    __slots__ = ("instances", "t_enqueue", "done", "probs", "error",
                 "ctx")

    def __init__(self, instances: List[Instance]):
        self.instances = instances
        self.t_enqueue = time.perf_counter()
        self.done = threading.Event()
        self.probs: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # The enqueuing handler thread's trace context (None when the
        # request was not traced): the dispatcher adopts the batch's
        # first traced context so the device forward and the shard-miss
        # RPCs carry a request trace id across the thread hop.
        self.ctx = trace.current_context()


class MicroBatcher:
    """The dispatcher: a bounded queue of pending requests + one thread
    draining them into single ragged device forwards."""

    def __init__(self, predictor, *, name: str = "serving-batcher",
                 metrics=None):
        self._pred = predictor
        self._feed = predictor.feed
        # Optional per-replica Monitor: fleet runs several replicas in
        # one process, and per-replica batch/fill stats must not
        # last-write-wins each other through the global registry.
        self._metrics = metrics
        self._q: deque = deque()
        self._q_rows = 0
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    # -- request side ------------------------------------------------------

    def predict(self, instances: Sequence[Instance],
                timeout: float = 120.0) -> np.ndarray:
        """Blocking predict: enqueue, wake the dispatcher, wait for the
        demuxed per-request slice. Raises whatever the batch's forward
        raised (an error in one batch fails every request in it — the
        callers retry individually)."""
        window_ms = float(flags.flag("serving_batch_window_ms"))
        if window_ms < 0 or not self._thread.is_alive():
            # Batching off: pack + dispatch inline (still bucketed
            # shapes + masked padding — only the coalescing is gone).
            batch = pack_bucketed(list(instances), self._feed)
            return np.asarray(
                self._pred.predict(batch)[:len(instances)], np.float32)
        req = _Pending(list(instances))
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._q.append(req)
            self._q_rows += len(req.instances)
            self._cv.notify_all()
        if not req.done.wait(timeout):
            raise TimeoutError(
                f"micro-batch dispatch did not complete in {timeout}s")
        if req.error is not None:
            raise req.error
        return req.probs

    # -- dispatcher --------------------------------------------------------

    def _drain_locked(self, max_rows: int) -> List[_Pending]:
        """Pop whole requests until max_rows (a request never splits —
        its rows must land in one batch for per-batch model-version
        consistency). Always takes at least one."""
        out: List[_Pending] = []
        rows = 0
        while self._q:
            nxt = len(self._q[0].instances)
            if out and rows + nxt > max_rows:
                break
            req = self._q.popleft()
            self._q_rows -= len(req.instances)
            out.append(req)
            rows += nxt
        return out

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(timeout=0.5)
                if self._closed and not self._q:
                    return
                window_s = max(
                    float(flags.flag("serving_batch_window_ms")), 0.0
                ) / 1e3
                max_rows = max(int(flags.flag("serving_batch_max_rows")),
                               1)
                deadline = self._q[0].t_enqueue + window_s
                while (self._q_rows < max_rows and not self._closed):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cv.wait(timeout=left)
                batch_reqs = self._drain_locked(max_rows)
            self._dispatch(batch_reqs)

    def _dispatch(self, reqs: List[_Pending]) -> None:
        t0 = time.perf_counter()
        try:
            faults.faultpoint("serving/batch_dispatch")
            all_ins: List[Instance] = []
            offsets = [0]
            for r in reqs:
                all_ins.extend(r.instances)
                offsets.append(len(all_ins))
            # A batch coalesces requests from MANY traces; Dapper-style,
            # the dispatch rides the first traced request's context
            # (its id correlates the downstream shard hops) and records
            # how many traced requests were coalesced under it.
            ctx = next((r.ctx for r in reqs if r.ctx is not None), None)
            with trace.use_context(ctx), \
                    trace.span("serving/batch_dispatch",
                               requests=len(reqs), rows=len(all_ins),
                               coalesced_traces=sum(
                                   1 for r in reqs if r.ctx is not None)):
                batch = pack_bucketed(all_ins, self._feed)
                probs = np.asarray(self._pred.predict(batch), np.float32)
            bs = batch.batch_size
            monitor.add("serving/batches", 1)
            monitor.add("serving/batch_requests", len(reqs))
            monitor.set_gauge("serving/batch_fill_frac",
                              len(all_ins) / max(bs, 1))
            if self._metrics is not None:
                self._metrics.add("serving/batches", 1)
                self._metrics.set_gauge("serving/batch_fill_frac",
                                        len(all_ins) / max(bs, 1))
            wait_anchor = t0
            for i, r in enumerate(reqs):
                r.probs = probs[offsets[i]:offsets[i + 1]]
                monitor.observe_quantile(
                    "serving/batch_wait_ms",
                    (wait_anchor - r.t_enqueue) * 1e3)
        except BaseException as e:  # fail the whole batch, keep serving
            log.warning("serving batcher: dispatch of %d request(s) "
                        "failed: %r", len(reqs), e)
            for r in reqs:
                r.error = e
        finally:
            for r in reqs:
                r.done.set()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
