"""Fleet autopilot: the actuator loop that closes the control loop.

PRs 11-18 built every sensor (``alerts_active`` burn-rate states,
``metrics_history`` rings, the one-scrape cluster snapshot, the PR 15
quality observatory) and every actuator (``start_replica()``/eject in
``serving/fleet.py``, shard re-replication repair, donefile publish) —
this module connects them, the way an SRE would (AUTOPILOT.md has the
full control-loop diagram and action table):

- :class:`Autoscaler` — a poll loop over the merged fleet stats and the
  active alert set: scale OUT on a predict-p99/violation burn, scale IN
  on a cold over-provisioned fleet, repair the shard tier on replica
  lag. Every action is hysteresis-guarded
  (``FLAGS_autopilot_cooldown_s``), clamped
  (``FLAGS_autopilot_{min,max}_replicas``), bounded to one per poll,
  counted under ``autopilot/actions/<kind>``, and journaled to a state
  file BEFORE it applies — a controller killed inside an action window
  resumes past the cooldown instead of double-applying.
- :class:`CanaryController` — COPC-gated publish: a new donefile BASE
  (pass_id == 0, which the per-replica publishers deliberately skip)
  lands on a FLAGS-sized canary subset first; the controller compares
  canary vs incumbent calibration on sampled live labels through the
  PR 15 ``ServingQuality`` join (the ``quality/copc`` gauges in each
  replica's ``metrics_snapshot``), then promotes to full fanout or
  rolls the canary back to the incumbent base — the poisoned model
  never reaches full fanout, and the verdict lands as one
  ``autopilot_report {json}`` line.
- :class:`FleetAutopilot` — both controllers behind one background
  thread at ``FLAGS_autopilot_poll_s``; tests and drills call
  ``poll_once`` directly for determinism.

Faultpoints ``autopilot/{scale_out,scale_in,canary_promote,
canary_rollback}`` sit between the journal write and the action —
ROBUSTNESS.md's crash-drill window for "resume without double-apply".
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from paddlebox_tpu.checkpoint.protocol import CheckpointProtocol, DoneRecord
from paddlebox_tpu.core import alerts, faults, flags, incident, log, monitor

# Alert names (core/alerts.py default rule pack) whose FIRING state is a
# scale-out signal on its own — the burn says the SLO is being missed.
_SCALE_OUT_ALERTS = frozenset({"serving_predict_p99",
                               "slo_violation_burn"})
# Replica-state gauge encoding (fleet/replica_state/<rid>), shared with
# serving/fleet.py's gauge publisher.
STATE_CODES = {"joining": 0.0, "healthy": 1.0, "degraded": 2.0,
               "ejected": 3.0}


class ControllerState:
    """Crash-safe controller journal: one small JSON file written
    tmp+fsync+replace (the donefile discipline). The journal is written
    BEFORE an action applies, so a controller killed inside the action
    window (the ``autopilot/*`` faultpoints) resumes knowing the intent
    — the cooldown stamp suppresses a double scale action, and the
    canary phase is re-driven idempotently instead of half-promoted.
    ``path=None`` keeps the journal in memory (pure in-process tests)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.data: Dict[str, Any] = {"last_action": {}, "canary": None,
                                     "seen_bases": [], "incumbent": None}
        if path is not None and os.path.exists(path):
            try:
                with open(path) as f:
                    self.data.update(json.load(f))
            except (OSError, ValueError) as e:
                log.warning("autopilot: state %s unreadable (%r) — "
                            "starting fresh", path, e)

    def save(self) -> None:
        if self.path is None:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.data, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    # -- cooldown stamps ---------------------------------------------------

    def last_action_ts(self, group: str) -> float:
        return float(self.data["last_action"].get(group, 0.0))

    def stamp(self, group: str, now: float) -> None:
        self.data["last_action"][group] = float(now)
        self.save()


def _record_action(kind: str, reason: str,
                   registries: Sequence = ()) -> None:
    """One action: counter in the global registry (and any attached
    instance registries) + incident-recorder context, so a later bundle
    names what the autopilot last did and why."""
    monitor.add(f"autopilot/actions/{kind}", 1)
    for reg in registries:
        reg.add(f"autopilot/actions/{kind}", 1)
    incident.set_context(autopilot_last_action=f"{kind}: {reason}")


class Autoscaler:
    """Scale replicas out/in and repair the shard tier off the sensor
    plane. Construction wires the actuators explicitly:

    - ``stats_fn`` — merged fleet stats (a router's ``handle_stats``
      payload: ``latency_ms``/``slo_violations``/per-replica briefs);
    - ``spawn`` — start one replica (``start_replica`` in a process, a
      subprocess worker in the drill); returns its id for the log;
    - ``retire(rid)`` — stop a drained replica's server/process;
    - ``shard_repair`` — the PR 13 ``ElasticReshardController.repair``
      seam (probe + promote + re-replicate).

    ``alerts_fn`` defaults to the process-global alert engine; tests
    inject a fake feed. The loop never raises out of ``poll_once`` —
    a sensor read failure is a warning, not a dead autopilot."""

    def __init__(self, fleet, stats_fn: Callable[[], Dict], *,
                 spawn: Optional[Callable[[], str]] = None,
                 retire: Optional[Callable[[str], None]] = None,
                 shard_repair: Optional[Callable[[], Any]] = None,
                 alerts_fn: Callable[[], List[Dict]] =
                 alerts.active_alerts,
                 state: Optional[ControllerState] = None,
                 registry: Optional[monitor.Monitor] = None,
                 clock: Callable[[], float] = time.time):
        self.fleet = fleet
        self._stats_fn = stats_fn
        self._spawn = spawn
        self._retire = retire
        self._shard_repair = shard_repair
        self._alerts_fn = alerts_fn
        self.state = state or ControllerState()
        self._regs = (registry,) if registry is not None else ()
        self._clock = clock
        self._seen_violations = -1
        self.actions: List[Dict[str, Any]] = []

    # -- sensor digestion --------------------------------------------------

    def _cooldown_ok(self, group: str, now: float) -> bool:
        cd = max(float(flags.flag("autopilot_cooldown_s")), 0.0)
        return now - self.state.last_action_ts(group) >= cd

    def read_sensors(self) -> Dict[str, Any]:
        """One digest of the plane: merged p99, mean batch fill, the
        violation delta since the previous poll, and the firing alert
        names. Sensor failures degrade to an empty reading."""
        try:
            st = self._stats_fn()
        except Exception as e:  # noqa: BLE001 - the loop must survive
            log.warning("autopilot: stats read failed: %r", e)
            return {}
        p99 = (st.get("latency_ms") or {}).get("p99") or 0.0
        fills = [b["stats"].get("batch_fill_frac", 0.0)
                 for b in (st.get("replicas") or {}).values()
                 if isinstance(b.get("stats"), dict)]
        viol = int(st.get("slo_violations", 0))
        delta = (max(0, viol - self._seen_violations)
                 if self._seen_violations >= 0 else 0)
        self._seen_violations = viol
        firing = {a["name"] for a in self._alerts_fn()
                  if a.get("state") == "firing"}
        return {"p99_ms": float(p99),
                "fill": (sum(fills) / len(fills)) if fills else None,
                "violation_delta": delta,
                "firing": firing,
                "fleet_size": int(self.fleet.size())}

    # -- actions -----------------------------------------------------------

    def _scale_out(self, now: float, reason: str) -> Dict[str, Any]:
        # Journal the intent FIRST: a kill between the stamp and the
        # spawn costs one cooldown of capacity, never a double spawn.
        self.state.stamp("scale", now)
        faults.faultpoint("autopilot/scale_out")
        rid = self._spawn() if self._spawn is not None else None
        _record_action("scale_out", reason, self._regs)
        log.warning("autopilot: scale OUT (%s) -> %s", reason, rid)
        return {"kind": "scale_out", "reason": reason, "replica": rid,
                "t": now}

    def _scale_in(self, now: float, reason: str) -> Optional[Dict]:
        # Graceful drain: drop the least-loaded healthy replica from
        # the ring (its in-flight requests finish on their open conns;
        # new ones route elsewhere), then retire its server.
        victims = sorted(self.fleet.healthy(),
                         key=lambda r: (r.inflight, r.routed, r.id))
        if not victims:
            return None
        victim = victims[0]
        self.state.stamp("scale", now)
        faults.faultpoint("autopilot/scale_in")
        self.fleet.remove_replica(victim.id)
        if self._retire is not None:
            self._retire(victim.id)
        _record_action("scale_in", reason, self._regs)
        log.warning("autopilot: scale IN (%s): drained %s", reason,
                    victim.id)
        return {"kind": "scale_in", "reason": reason,
                "replica": victim.id, "t": now}

    def poll_once(self, now: Optional[float] = None) -> List[Dict]:
        """One control tick: read sensors, apply AT MOST one scale
        action (hysteresis + clamps) and at most one shard repair.
        Returns the actions taken (also appended to ``self.actions``)."""
        now = self._clock() if now is None else now
        monitor.add("autopilot/polls", 1)
        for reg in self._regs:
            reg.add("autopilot/polls", 1)
        sense = self.read_sensors()
        taken: List[Dict[str, Any]] = []
        if sense:
            n = sense["fleet_size"]
            slo = float(flags.flag("serving_slo_p99_ms"))
            lo = max(int(flags.flag("autopilot_min_replicas")), 1)
            hi = max(int(flags.flag("autopilot_max_replicas")), lo)
            breach_alerts = sense["firing"] & _SCALE_OUT_ALERTS
            # Heal is a breach too: a kill -9 that drops the healthy
            # count under the floor must re-grow capacity without
            # waiting for the latency it will soon cost to show up.
            below_min = 0 < n < lo
            breach = bool(breach_alerts) or below_min or (
                slo > 0 and (sense["p99_ms"] > slo
                             or sense["violation_delta"] > 0))
            if breach_alerts:
                reason = f"alerts={sorted(breach_alerts)}"
            elif below_min:
                reason = f"healthy={n} < min_replicas={lo}"
            else:
                reason = (f"p99={sense['p99_ms']:.1f}ms "
                          f"viol_delta={sense['violation_delta']}")
            if breach and n > 0 and n < hi \
                    and self._cooldown_ok("scale", now) \
                    and self._spawn is not None:
                taken.append(self._scale_out(now, reason))
            elif (not breach and sense["fill"] is not None
                  and sense["fill"] < float(
                      flags.flag("autopilot_scale_in_fill"))
                  and sense["violation_delta"] == 0
                  and (slo <= 0 or sense["p99_ms"] < 0.5 * slo)
                  and n > lo and self._cooldown_ok("scale", now)):
                act = self._scale_in(
                    now, f"fill={sense['fill']:.3f} idle fleet")
                if act is not None:
                    taken.append(act)
        # Shard-tier rebalance: the replication-lag gauge the replicated
        # tier publishes (multihost/replica_lag_p99) past the alert
        # threshold — or its burn alert firing — drives the PR 13
        # promote/re-replicate repair. Its own cooldown group: a shard
        # repair must not eat the replica-scale budget.
        lag_thresh = float(flags.flag("alerts_replica_lag"))
        lag = monitor.get_gauge("multihost/replica_lag_p99", 0.0)
        lag_firing = "replica_lag_p99" in (sense.get("firing") or ())
        if self._shard_repair is not None \
                and (lag_firing or (lag_thresh > 0 and lag > lag_thresh)) \
                and self._cooldown_ok("shard", now):
            self.state.stamp("shard", now)
            try:
                audit = self._shard_repair()
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                log.warning("autopilot: shard repair failed: %r", e)
            else:
                _record_action("shard_repair",
                               f"lag_p99={lag:.1f}", self._regs)
                taken.append({"kind": "shard_repair", "t": now,
                              "lag_p99": lag, "audit": audit})
        self.actions.extend(taken)
        return taken


class CanaryController:
    """COPC-gated canary publish over the donefile protocol.

    Watches ``root``'s donefile for NEW day-level base records
    (pass_id == 0 — the records every per-replica
    :class:`~paddlebox_tpu.serving.publisher.DonefilePublisher`
    deliberately skips: base rollout is a controller action, not a tail
    apply). State machine, journaled per transition::

        watch --new base--> canary --copc ok--> promoting -> watch
                               |                   (full fanout,
                               |                    new incumbent)
                               +--breach/timeout--> rolling_back -> watch
                                                    (incumbent re-applied
                                                     on the canary set)

    The verdict compares |COPC - 1| of the canary subset vs the
    incumbent subset, each read from the replicas' ``quality/copc``
    gauges once both sides joined ``FLAGS_autopilot_canary_min_labels``
    live labels since the canary began — the PR 15 sampled-label join
    is the evidence, not a synthetic probe. Every transition emits one
    ``autopilot_report {json}`` line naming the verdict and objective.
    """

    def __init__(self, fleet, root: str, *, table: str = "embedding",
                 state: Optional[ControllerState] = None,
                 registry: Optional[monitor.Monitor] = None,
                 clock: Callable[[], float] = time.time):
        self.fleet = fleet
        self.table = table
        self._proto = CheckpointProtocol(root)
        self.state = state or ControllerState()
        self._regs = (registry,) if registry is not None else ()
        self._clock = clock
        self.reports: List[Dict[str, Any]] = []
        if self.state.data.get("incumbent") is None \
                and not self.state.data.get("seen_bases"):
            # First boot: the bases already published are the model the
            # operator stood the fleet up from — the LAST one is the
            # incumbent, none of them canary.
            bases = self._bases()
            self.state.data["seen_bases"] = [self._tag(b) for b in bases]
            if bases:
                self.state.data["incumbent"] = bases[-1]._asdict() \
                    if hasattr(bases[-1], "_asdict") else {
                        "day": bases[-1].day, "key": bases[-1].key,
                        "path": bases[-1].path,
                        "pass_id": bases[-1].pass_id}
            self.state.save()

    # -- donefile scan -----------------------------------------------------

    @staticmethod
    def _tag(rec) -> List[str]:
        return [str(rec.day), str(rec.path)]

    def _bases(self) -> List[DoneRecord]:
        try:
            return [r for r in self._proto.records() if r.pass_id == 0]
        except (OSError, ValueError) as e:
            log.warning("canary: donefile read failed: %r", e)
            return []

    def incumbent(self) -> Optional[Dict[str, Any]]:
        return self.state.data.get("incumbent")

    # -- replica RPC helpers ----------------------------------------------

    def _call(self, replica, method: str, **kw):
        conn = replica.pool.acquire()
        try:
            out = conn.call(method, **kw)
        except BaseException:
            conn.close()
            raise
        replica.pool.release(conn)
        return out

    def _apply_base(self, replica, path: str) -> None:
        self._call(replica, "apply_delta", path=path, table=self.table,
                   kind="xbox")

    def _quality_read(self, replica) -> Dict[str, float]:
        snap = self._call(replica, "metrics_snapshot")
        gauges = snap.get("gauges") or {}
        counters = snap.get("counters") or {}
        return {"copc": gauges.get("quality/copc"),
                "joined": float(counters.get("quality/label_joined", 0)),
                "alarms": float(sum(
                    v for k, v in counters.items()
                    if k.startswith("quality/alarms/")))}

    # -- reporting ---------------------------------------------------------

    def _report(self, verdict: str, objective: str,
                detail: Dict[str, Any]) -> None:
        rec = {"verdict": verdict, "objective": objective, **detail}
        self.reports.append(rec)
        print("autopilot_report " + json.dumps(rec, default=str),
              flush=True)

    # -- state machine -----------------------------------------------------

    def _begin_canary(self, rec: DoneRecord, now: float) -> None:
        healthy = sorted(self.fleet.healthy(), key=lambda r: r.id)
        k = max(int(flags.flag("autopilot_canary_replicas")), 1)
        # At least one incumbent must keep serving the old model or
        # there is nothing to compare against.
        k = min(k, max(len(healthy) - 1, 0))
        if k == 0:
            log.warning("canary: fleet too small for a canary subset "
                        "(%d healthy) — base %s held", len(healthy),
                        rec.path)
            return
        subset = [r.id for r in healthy[:k]]
        labels0 = {}
        for r in healthy:
            try:
                labels0[r.id] = self._quality_read(r)["joined"]
            except Exception:  # noqa: BLE001 - replica may be mid-join
                labels0[r.id] = 0.0
        self.state.data["canary"] = {
            "phase": "canary",
            "day": rec.day, "key": rec.key, "path": rec.path,
            "pass_id": rec.pass_id, "canary_ids": subset,
            "since": now, "labels0": labels0}
        self.state.data["seen_bases"].append(self._tag(rec))
        # Journal BEFORE applying: a kill mid-apply resumes in phase
        # 'canary' and re-applies idempotently (apply_update overwrites
        # the same rows) instead of leaving an unknown subset.
        self.state.save()
        for rid in subset:
            r = self.fleet.get(rid)
            if r is not None:
                self._apply_base(r, rec.path)
        _record_action("canary_start",
                       f"base {rec.day} -> {subset}", self._regs)
        log.warning("canary: base %s/%s staged on %s", rec.day,
                    rec.path, subset)

    def _verdict(self, can: Dict[str, Any], now: float
                 ) -> Optional[Dict[str, Any]]:
        """None = keep gathering; else {'promote': bool, 'objective',
        sides}."""
        subset = set(can["canary_ids"])
        labels0 = can.get("labels0") or {}
        sides: Dict[str, List[Dict[str, float]]] = {"canary": [],
                                                    "incumbent": []}
        for r in self.fleet.healthy():
            try:
                q = self._quality_read(r)
            except Exception:  # noqa: BLE001 - a dying replica abstains
                continue
            q["joined_new"] = q["joined"] - float(
                labels0.get(r.id, 0.0))
            sides["canary" if r.id in subset else "incumbent"].append(q)
        need = max(int(flags.flag("autopilot_canary_min_labels")), 0)

        def ready(rows):
            return rows and all(x["copc"] is not None for x in rows) \
                and sum(x["joined_new"] for x in rows) >= need

        if not (ready(sides["canary"]) and ready(sides["incumbent"])):
            timeout = float(flags.flag("autopilot_canary_timeout_s"))
            if timeout > 0 and now - float(can["since"]) > timeout:
                return {"promote": False, "objective": "timeout",
                        "sides": sides}
            return None

        def dev(rows):
            return sum(abs(x["copc"] - 1.0) for x in rows) / len(rows)

        margin = float(flags.flag("autopilot_canary_copc_margin"))
        c_dev, i_dev = dev(sides["canary"]), dev(sides["incumbent"])
        if c_dev > i_dev + margin:
            return {"promote": False, "objective": "copc",
                    "canary_copc_dev": c_dev,
                    "incumbent_copc_dev": i_dev, "sides": sides}
        return {"promote": True, "objective": "copc",
                "canary_copc_dev": c_dev, "incumbent_copc_dev": i_dev,
                "sides": sides}

    def _promote(self, can: Dict[str, Any], verdict: Dict) -> None:
        can["phase"] = "promoting"
        self.state.save()
        faults.faultpoint("autopilot/canary_promote")
        subset = set(can["canary_ids"])
        for r in self.fleet.healthy():
            if r.id not in subset:
                self._apply_base(r, can["path"])
        self.state.data["incumbent"] = {
            "day": can["day"], "key": can["key"], "path": can["path"],
            "pass_id": can["pass_id"]}
        self.state.data["canary"] = None
        self.state.save()
        _record_action("canary_promote",
                       f"base {can['day']} full fanout", self._regs)
        self._report("promote", verdict.get("objective", "copc"), {
            "day": can["day"], "path": can["path"],
            "canary": sorted(subset),
            "canary_copc_dev": verdict.get("canary_copc_dev"),
            "incumbent_copc_dev": verdict.get("incumbent_copc_dev")})

    def _rollback(self, can: Dict[str, Any], verdict: Dict) -> None:
        can["phase"] = "rolling_back"
        self.state.save()
        faults.faultpoint("autopilot/canary_rollback")
        inc = self.incumbent()
        for rid in can["canary_ids"]:
            r = self.fleet.get(rid)
            if r is None:
                continue
            if inc is not None:
                # Republish the incumbent base on the canary replica:
                # its rollback_to handler re-applies the prior base
                # atomically and bumps serving/hotswap_rollbacks.
                self._call(r, "rollback_to", day=inc["day"],
                           key=inc.get("key", ""), path=inc["path"],
                           pass_id=int(inc.get("pass_id", 0)),
                           table=self.table)
        self.state.data["canary"] = None
        self.state.save()
        _record_action("canary_rollback",
                       f"base {can['day']}: {verdict.get('objective')}",
                       self._regs)
        self._report("rollback", verdict.get("objective", "copc"), {
            "day": can["day"], "path": can["path"],
            "canary": can["canary_ids"],
            "canary_copc_dev": verdict.get("canary_copc_dev"),
            "incumbent_copc_dev": verdict.get("incumbent_copc_dev"),
            "restored": (inc or {}).get("path")})

    def poll_once(self, now: Optional[float] = None) -> Optional[str]:
        """One canary tick. Returns the transition taken (``canary``/
        ``promote``/``rollback``) or None."""
        now = self._clock() if now is None else now
        can = self.state.data.get("canary")
        if can is None:
            seen = {tuple(t) for t in self.state.data["seen_bases"]}
            for rec in self._bases():
                if tuple(self._tag(rec)) not in seen:
                    self._begin_canary(rec, now)
                    return "canary"
            return None
        # Crash resume: a journaled decision re-drives idempotently.
        if can["phase"] == "promoting":
            self._promote(can, {"objective": "resume"})
            return "promote"
        if can["phase"] == "rolling_back":
            self._rollback(can, {"objective": "resume"})
            return "rollback"
        verdict = self._verdict(can, now)
        if verdict is None:
            return None
        if verdict["promote"]:
            self._promote(can, verdict)
            return "promote"
        self._rollback(can, verdict)
        return "rollback"


class FleetAutopilot:
    """Both controllers behind one poll thread. ``state_path`` journals
    both (one file): the crash-drill contract is that killing this
    process inside any ``autopilot/*`` faultpoint and restarting it
    with the same path resumes without double-applied scale actions or
    a half-promoted canary."""

    def __init__(self, fleet, stats_fn: Callable[[], Dict], *,
                 donefile_root: Optional[str] = None,
                 table: str = "embedding",
                 spawn: Optional[Callable[[], str]] = None,
                 retire: Optional[Callable[[str], None]] = None,
                 shard_repair: Optional[Callable[[], Any]] = None,
                 alerts_fn: Callable[[], List[Dict]] =
                 alerts.active_alerts,
                 state_path: Optional[str] = None,
                 registry: Optional[monitor.Monitor] = None,
                 clock: Callable[[], float] = time.time):
        self.state = ControllerState(state_path)
        self.scaler = Autoscaler(
            fleet, stats_fn, spawn=spawn, retire=retire,
            shard_repair=shard_repair, alerts_fn=alerts_fn,
            state=self.state, registry=registry, clock=clock)
        self.canary = None
        if donefile_root is not None:
            self.canary = CanaryController(
                fleet, donefile_root, table=table, state=self.state,
                registry=registry, clock=clock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self, now: Optional[float] = None) -> List[Dict]:
        acts = self.scaler.poll_once(now)
        if self.canary is not None:
            try:
                t = self.canary.poll_once(now)
            except Exception as e:  # noqa: BLE001 - loop must survive
                log.warning("autopilot: canary tick failed: %r", e)
            else:
                if t is not None:
                    acts.append({"kind": f"canary_{t}"})
        return acts

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-autopilot")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - keep polling
                log.warning("autopilot: poll failed: %r", e)
            self._stop.wait(max(
                float(flags.flag("autopilot_poll_s")), 0.05))

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
