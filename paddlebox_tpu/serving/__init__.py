from paddlebox_tpu.serving.batcher import MicroBatcher, pack_bucketed
from paddlebox_tpu.serving.predictor import (CTRPredictor,
                                             ServingTierStore,
                                             load_delta_update,
                                             load_serving_predictor,
                                             load_xbox_model)
from paddlebox_tpu.serving.publisher import DonefilePublisher
from paddlebox_tpu.serving.service import PredictClient, PredictServer

__all__ = ["CTRPredictor", "DonefilePublisher", "MicroBatcher",
           "PredictClient", "PredictServer", "ServingTierStore",
           "load_delta_update", "load_serving_predictor",
           "load_xbox_model", "pack_bucketed"]
