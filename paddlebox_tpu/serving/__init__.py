from paddlebox_tpu.serving.predictor import (CTRPredictor,
                                             load_delta_update,
                                             load_xbox_model)

__all__ = ["CTRPredictor", "load_delta_update", "load_xbox_model"]
