from paddlebox_tpu.serving.batcher import MicroBatcher, pack_bucketed
from paddlebox_tpu.serving.fleet import (Replica, ServingFleet,
                                         ShardBackedStore, start_replica)
from paddlebox_tpu.serving.predictor import (CTRPredictor,
                                             GroupedCTRPredictor,
                                             ServingTierStore,
                                             load_delta_update,
                                             load_grouped_export,
                                             load_serving_predictor,
                                             load_xbox_model)
from paddlebox_tpu.serving.publisher import DonefilePublisher
from paddlebox_tpu.serving.router import FleetRouter
from paddlebox_tpu.serving.service import PredictClient, PredictServer

__all__ = ["CTRPredictor", "DonefilePublisher", "FleetRouter",
           "GroupedCTRPredictor", "MicroBatcher", "PredictClient",
           "PredictServer", "Replica", "ServingFleet",
           "ServingTierStore", "ShardBackedStore", "load_delta_update",
           "load_grouped_export", "load_serving_predictor",
           "load_xbox_model", "pack_bucketed", "start_replica"]
