from paddlebox_tpu.serving.predictor import (CTRPredictor,
                                             load_xbox_model)

__all__ = ["CTRPredictor", "load_xbox_model"]
