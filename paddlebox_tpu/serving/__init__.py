from paddlebox_tpu.serving.predictor import (CTRPredictor,
                                             load_delta_update,
                                             load_serving_predictor,
                                             load_xbox_model)
from paddlebox_tpu.serving.service import PredictClient, PredictServer

__all__ = ["CTRPredictor", "PredictClient", "PredictServer",
           "load_delta_update", "load_serving_predictor",
           "load_xbox_model"]
