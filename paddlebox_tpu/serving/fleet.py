"""Serving fleet state: replica registry, hash ring, SLO admission,
and the shared shard-tier miss resolver.

Role of the fleet half of the reference's online deployment (one AIBox
inference tier = N workers over ONE sparse parameter service): this
module owns everything about a fleet that is NOT a socket — the replica
registry with its health/admission state machine, the consistent-hash
ring that gives a user key a stable home replica, discovery through the
elastic :class:`~paddlebox_tpu.launch.elastic.RankTable` heartbeat
``meta`` (replicas advertise ``serving_endpoint`` exactly the way the
multihost tier advertises ``shard_endpoint``), and the
:class:`ShardBackedStore` pure-read resolver that lets every replica's
warm/cold misses land on the SHARED ShardServer tier instead of a
private disk shard — so the fleet serves one model out of one backing
store and its aggregate hot set, not one replica's HBM, bounds the
servable model ("Dissecting Embedding Bag Performance in DLRM
Inference": the gather working set is what must live close, and N
private copies of the cold tier buy nothing).

The RPC front-end that drives this state lives in
``serving/router.py``; tests drive :class:`ServingFleet` directly
(``health_check_once`` / ``discover_once``) for determinism.

Replica lifecycle (SERVING_FLEET.md has the full state machine)::

    JOINING --stats ok--> HEALTHY --N check fails--> EJECTED
     (warms first)          |  ^
                            v  | clean window
                  DEGRADED admission (slo/violations tripped)

``EJECTED`` is terminal for a replica id; a restarted process registers
under a fresh id (or the same id re-added by discovery after its
endpoint answers again).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import faults, flags, log, monitor, trace
from paddlebox_tpu.distributed import rpc

_SERVING = ("healthy", "degraded")   # states the ring routes to
# Gauge encoding for fleet/replica_state/<rid> (metrics_snapshot
# topology view; serving/autopilot.py mirrors this table).
_STATE_CODES = {"joining": 0.0, "healthy": 1.0, "degraded": 2.0,
                "ejected": 3.0}


def stable_hash64(s: str) -> int:
    """Process-stable 64-bit hash for ring placement (builtin ``hash``
    is salted per process — two routers would disagree on the ring)."""
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "little")


def route_key_hash(lines: Sequence[str]) -> int:
    """The request's routing key: the FIRST feature token of the first
    line (by convention the user slot leads the svm line, so one user's
    requests share a home replica and its hot rows). Requests with no
    parseable token hash the raw line — still deterministic."""
    if not lines:
        return 0
    line = lines[0]
    for tok in line.split():
        if ":" in tok:
            return stable_hash64(tok)
    return stable_hash64(line)


class HashRing:
    """Consistent-hash ring over replica ids (vnode-replicated)."""

    def __init__(self, ids: Sequence[str], vnodes: int):
        points: List[Tuple[int, str]] = []
        for rid in ids:
            for v in range(max(int(vnodes), 1)):
                points.append((stable_hash64(f"{rid}#{v}"), rid))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._ids = [r for _, r in points]

    def lookup(self, key_hash: int) -> Optional[str]:
        if not self._ids:
            return None
        i = bisect.bisect_right(self._hashes, int(key_hash))
        return self._ids[i % len(self._ids)]


class _ConnPool:
    """Per-replica conn source for router handler threads. With the mux
    wire (PR 16, ``FLAGS_rpc_mux``) this collapses to ONE multiplexed
    conn shared by every thread: in-flight request ids let N
    outstanding predicts interleave on a single socket, so the per-conn
    serialization that motivated a pool is gone (per-thread latency
    attribution — ``last_server_ms`` — is thread-local on the conn).
    ``release`` on the shared conn is a no-op and an error-path
    ``conn.close()`` just poisons the current mux generation — the next
    acquire reuses the object and it reconnects lazily. ``--norpc_mux``
    restores the legacy pool-of-conns (one conn per concurrent caller).
    Predict is deliberately NOT declared idempotent on these conns: a
    dead replica must surface immediately so the ROUTER re-routes,
    instead of the conn burning its retry deadline reconnecting to a
    corpse."""

    def __init__(self, endpoint: str, timeout: float):
        self.endpoint = endpoint
        self._timeout = timeout
        self._free: List[rpc.FramedRPCConn] = []
        self._shared: Optional[rpc.FramedRPCConn] = None
        self._lock = threading.Lock()

    def _new(self) -> rpc.FramedRPCConn:
        return rpc.FramedRPCConn(self.endpoint, timeout=self._timeout,
                                 service_name="fleet-replica")

    def acquire(self) -> rpc.FramedRPCConn:
        if flags.flag("rpc_mux"):
            with self._lock:
                if self._shared is None:
                    self._shared = self._new()
                return self._shared
        with self._lock:
            if self._free:
                return self._free.pop()
        return self._new()

    def release(self, conn: rpc.FramedRPCConn) -> None:
        with self._lock:
            if conn is self._shared:
                return
            self._free.append(conn)

    def close(self) -> None:
        with self._lock:
            conns, self._free = list(self._free), []
            if self._shared is not None:
                conns.append(self._shared)
                self._shared = None
        for c in conns:
            c.close()


class Replica:
    """One replica's registry entry. Mutable fields are guarded by the
    owning fleet's lock."""

    def __init__(self, rid: str, endpoint: str, *, source: str = "static",
                 timeout: float = 30.0):
        self.id = rid
        self.endpoint = endpoint
        self.source = source              # "static" | "elastic"
        self.state = "joining"            # joining|healthy|ejected
        self.admission = "ok"             # ok|degraded
        self.inflight = 0
        self.fails = 0
        self.routed = 0
        self.degraded_served = 0
        # SLO admission window state: cumulative slo_violations as last
        # read from the replica's stats, and the delta accumulated over
        # the current window.
        self.seen_violations = -1         # -1 = never read
        self.window_violations = 0
        self.window_start = time.monotonic()
        self.pool = _ConnPool(endpoint, timeout)

    def brief(self) -> Dict[str, object]:
        return {"id": self.id, "endpoint": self.endpoint,
                "state": self.state, "admission": self.admission,
                "inflight": int(self.inflight), "routed": int(self.routed),
                "degraded_served": int(self.degraded_served),
                "fails": int(self.fails), "source": self.source}


class ServingFleet:
    """Replica registry + ring + health/admission + elastic discovery.

    ``epoch`` is the topology generation: any membership or
    serving-state change bumps it, and clients that cached a replica
    endpoint re-resolve through it (``PredictClient`` resolver)."""

    def __init__(self, *, elastic_root: Optional[str] = None,
                 replica_timeout: float = 30.0,
                 stats_call: Optional[Callable] = None):
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._ring = HashRing((), 1)
        self.epoch = 0
        self.elastic_root = elastic_root
        self._replica_timeout = replica_timeout
        # Seam for tests: (replica) -> stats dict. Default RPCs.
        self._stats_call = stats_call or self._stats_rpc
        # Instance registries mirroring the topology gauges (a router
        # attaches its own so ONE metrics_snapshot on it carries the
        # whole membership picture — no stats fan-out needed).
        self._registries: List[monitor.Monitor] = []
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def attach_registry(self, registry: monitor.Monitor) -> None:
        """Mirror ``fleet/topology_epoch`` + per-replica state gauges
        into ``registry`` (the owning router's instance registry, so its
        ``metrics_snapshot`` exposes membership in one scrape)."""
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)
            self._publish_gauges_locked()

    # -- membership --------------------------------------------------------

    def _publish_gauges_locked(self) -> None:
        """Topology as gauges: ``fleet/topology_epoch`` plus one
        ``fleet/replica_state/<rid>`` per known replica, encoded
        0=joining 1=healthy 2=degraded 3=ejected (DEGRADED is a healthy
        replica whose SLO admission window tripped). The autoscaler and
        ``fleet_top`` read these from any single ``metrics_snapshot``
        instead of fanning stats out to every replica."""
        monitor.set_gauge("fleet/topology_epoch", float(self.epoch))
        for reg in self._registries:
            reg.set_gauge("fleet/topology_epoch", float(self.epoch))
        for r in self._replicas.values():
            if r.state == "healthy" and r.admission == "degraded":
                code = _STATE_CODES["degraded"]
            else:
                code = _STATE_CODES.get(r.state, 0.0)
            monitor.set_gauge(f"fleet/replica_state/{r.id}", code)
            for reg in self._registries:
                reg.set_gauge(f"fleet/replica_state/{r.id}", code)

    def _bump_epoch_locked(self) -> None:
        self.epoch += 1
        self._ring = HashRing(
            [r.id for r in self._replicas.values()
             if r.state in _SERVING or r.state == "healthy"],
            int(flags.flag("fleet_vnodes")))
        monitor.set_gauge("fleet/epoch", float(self.epoch))
        monitor.set_gauge("fleet/replicas", float(sum(
            1 for r in self._replicas.values() if r.state == "healthy")))
        self._publish_gauges_locked()

    def add_replica(self, rid: str, endpoint: str, *,
                    source: str = "static", ready: bool = False) -> Replica:
        """Register a replica. ``ready=True`` admits it to the ring
        immediately (tests/bench with known-warm replicas); otherwise it
        stays JOINING until a health check confirms it answers stats —
        the join gate that keeps a cold replica from taking traffic
        before its warm-up (donefile base + shard-tier pulls) is done."""
        with self._lock:
            if rid in self._replicas:
                return self._replicas[rid]
            r = Replica(rid, endpoint, source=source,
                        timeout=self._replica_timeout)
            self._replicas[rid] = r
            if ready:
                r.state = "healthy"
                monitor.add("fleet/joined", 1)
            self._bump_epoch_locked()
        log.vlog(0, "fleet: replica %s at %s registered (%s)", rid,
                 endpoint, "ready" if ready else "joining")
        return r

    def remove_replica(self, rid: str) -> None:
        """Clean leave: drop from the ring and close its conns."""
        with self._lock:
            r = self._replicas.pop(rid, None)
            if r is None:
                return
            monitor.add("fleet/left", 1)
            self._bump_epoch_locked()
            # The departed replica's state gauge must not freeze at its
            # last serving code — observers reading one snapshot would
            # keep counting it as live capacity.
            code = _STATE_CODES["ejected"]
            monitor.set_gauge(f"fleet/replica_state/{rid}", code)
            for reg in self._registries:
                reg.set_gauge(f"fleet/replica_state/{rid}", code)
        r.pool.close()
        log.vlog(0, "fleet: replica %s left", rid)

    def replicas(self) -> List[Dict[str, object]]:
        with self._lock:
            return [r.brief() for r in self._replicas.values()]

    def healthy(self) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == "healthy"]

    def get(self, rid: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(rid)

    def size(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values()
                       if r.state == "healthy")

    # -- routing -----------------------------------------------------------

    def pick(self, key_hash: int, exclude: Tuple[str, ...] = ()
             ) -> Tuple[Optional[Replica], str, bool]:
        """Route one request: (replica, mode, degraded). Mode is
        ``affinity`` (hash home) or ``spillover`` (home overloaded or
        excluded, least-loaded healthy instead); (None, "none", False)
        when no healthy replica remains. ``exclude`` names replicas
        this request already failed on (the router's in-RPC re-route
        must not hand the request back to the replica that just died
        before the strike threshold ejects it). ``degraded`` means the
        home replica's SLO admission tripped AND every candidate is at
        the in-flight ceiling: the request is shed to the cheap path
        instead of queueing behind a replica already missing its SLO."""
        spill = max(int(flags.flag("fleet_spillover_inflight")), 1)
        with self._lock:
            home_id = self._ring.lookup(key_hash)
            home = self._replicas.get(home_id) if home_id else None
            if home is None or home.state != "healthy" \
                    or home.id in exclude:
                cands = [r for r in self._replicas.values()
                         if r.state == "healthy"
                         and r.id not in exclude]
                if not cands:
                    return None, "none", False
                home = min(cands, key=lambda r: r.inflight)
            if home.inflight < spill:
                home.inflight += 1
                home.routed += 1
                return home, "affinity", False
            # Home is saturated: spill to the least-loaded healthy
            # replica (cache affinity yields to load under key skew).
            cands = [r for r in self._replicas.values()
                     if r.state == "healthy" and r.id not in exclude]
            alt = min(cands, key=lambda r: r.inflight)
            if alt.inflight < spill:
                alt.inflight += 1
                alt.routed += 1
                monitor.add("fleet/spillover", 1)
                return alt, "spillover", False
            # Everyone is at the ceiling. If the home replica's SLO
            # admission tripped, shed its overflow to the degraded path
            # on the least-loaded candidate; otherwise queue on home
            # (backpressure, the SLO is still being met).
            target = alt if alt.inflight <= home.inflight else home
            target.inflight += 1
            target.routed += 1
            if home.admission == "degraded":
                target.degraded_served += 1
                monitor.add("fleet/degraded", 1)
                return target, "spillover", True
            return target, "affinity", False

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)

    def strike(self, replica: Replica) -> None:
        """A routed call hit a dead connection: one health strike,
        ejecting at the same threshold as the health thread (the router
        already re-routed the request — ejection is about not routing
        the NEXT one there)."""
        with self._lock:
            replica.fails += 1
            should_eject = (replica.state != "ejected" and
                            replica.fails >= max(
                                int(flags.flag("fleet_health_fails")), 1))
        if should_eject:
            self._eject(replica, reason="predict connection error")

    def _eject(self, replica: Replica, *, reason: str) -> None:
        faults.faultpoint("fleet/health_eject")
        with self._lock:
            if replica.state == "ejected":
                return
            replica.state = "ejected"
            monitor.add("fleet/ejected", 1)
            self._bump_epoch_locked()
        replica.pool.close()
        log.warning("fleet: ejected replica %s (%s)", replica.id, reason)
        # Flight recorder: an eject is capacity lost — bundle the
        # forensics that led here (contained + rate-limited inside).
        from paddlebox_tpu.core import incident
        incident.trigger("replica_eject",
                         context={"replica": replica.id,
                                  "endpoint": replica.endpoint,
                                  "reason": reason})

    # -- health + admission ------------------------------------------------

    def _stats_rpc(self, replica: Replica) -> Dict:
        conn = replica.pool.acquire()
        try:
            out = conn.call("stats")
        except BaseException:
            conn.close()
            raise
        replica.pool.release(conn)
        return out

    def health_check_once(self) -> None:
        """One health + admission sweep over every non-ejected replica:
        a stats answer clears strikes, admits JOINING replicas
        (``fleet/replica_join``), and feeds the SLO admission window;
        repeated failures eject (``fleet/health_eject``)."""
        with self._lock:
            todo = [r for r in self._replicas.values()
                    if r.state != "ejected"]
        thresh = max(int(flags.flag("fleet_health_fails")), 1)
        for r in todo:
            try:
                st = self._stats_call(r)
            except (OSError, ConnectionError, RuntimeError,
                    faults.InjectedFault) as e:
                with self._lock:
                    r.fails += 1
                    should_eject = r.fails >= thresh
                if should_eject:
                    self._eject(r, reason=f"health check failed: {e!r}")
                continue
            with self._lock:
                r.fails = 0
                if r.state == "joining":
                    faults.faultpoint("fleet/replica_join")
                    r.state = "healthy"
                    monitor.add("fleet/joined", 1)
                    self._bump_epoch_locked()
                    log.vlog(0, "fleet: replica %s joined serving", r.id)
                self._admission_update_locked(
                    r, int(st.get("slo_violations", 0)))

    def _admission_update_locked(self, r: Replica, violations: int) -> None:
        """Feed one stats reading into the replica's SLO window. The
        counter is cumulative on the replica; the window sums deltas,
        trips DEGRADED at ``fleet_slo_trip``, and one clean (zero-delta)
        full window restores OK."""
        if r.seen_violations < 0:
            r.seen_violations = violations
            return
        delta = max(0, violations - r.seen_violations)
        r.seen_violations = violations
        r.window_violations += delta
        now = time.monotonic()
        window = max(float(flags.flag("fleet_slo_window_s")), 1e-3)
        trip = max(int(flags.flag("fleet_slo_trip")), 1)
        if r.window_violations >= trip:
            if r.admission != "degraded":
                r.admission = "degraded"
                monitor.add("fleet/admission_trips", 1)
                self._publish_gauges_locked()
                log.warning(
                    "fleet: replica %s SLO admission tripped (%d "
                    "violations in window)", r.id, r.window_violations)
            # Re-arm: a replica still violating keeps re-tripping.
            r.window_violations = 0
            r.window_start = now
        elif now - r.window_start >= window:
            if r.window_violations == 0 and r.admission != "ok":
                r.admission = "ok"
                self._publish_gauges_locked()
                log.vlog(0, "fleet: replica %s admission restored", r.id)
            r.window_violations = 0
            r.window_start = now

    # -- elastic discovery -------------------------------------------------

    def discover_once(self) -> bool:
        """Adopt the elastic rank table's ``serving_endpoint`` meta:
        hosts advertising one and not yet known register (JOINING —
        the next health sweep admits them once they answer); known
        elastic-sourced replicas whose host left the table are removed
        (clean leave — a kill -9 is caught faster by the health
        thread). Returns whether membership changed."""
        if self.elastic_root is None:
            return False
        from paddlebox_tpu.launch.elastic import read_rank_table
        table = read_rank_table(self.elastic_root)
        if table is None:
            return False
        eps: Dict[str, str] = {}
        for host in table.hosts:
            m = table.meta.get(host) or {}
            ep = m.get("serving_endpoint")
            if ep:
                eps[host] = str(ep)
        changed = False
        with self._lock:
            known = dict(self._replicas)
        for host, ep in eps.items():
            r = known.get(host)
            if r is None:
                self.add_replica(host, ep, source="elastic")
                changed = True
            elif r.state == "ejected" and r.endpoint != ep:
                # Same host id came back on a fresh endpoint (restart):
                # re-register it as a joining replica.
                self.remove_replica(host)
                self.add_replica(host, ep, source="elastic")
                changed = True
        for rid, r in known.items():
            if r.source == "elastic" and rid not in eps:
                self.remove_replica(rid)
                changed = True
        return changed

    # -- background loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-health")
        self._thread.start()

    def _loop(self) -> None:
        while self._running:
            try:
                self.discover_once()
                self.health_check_once()
            except Exception as e:  # keep the fleet alive
                log.warning("fleet health loop: %s", e)
            time.sleep(max(
                float(flags.flag("fleet_health_interval_s")), 0.05))

    def stop(self) -> None:
        self._running = False
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        with self._lock:
            reps = list(self._replicas.values())
        for r in reps:
            r.pool.close()


class ShardBackedStore:
    """Pure-read miss resolver over the shared ShardServer tier.

    The ``backing`` a :class:`~paddlebox_tpu.serving.predictor.
    ServingTierStore` plugs its cold path into instead of private
    :class:`~paddlebox_tpu.embedding.ssd_tier.DiskShards`: batched
    ``pull_serving`` RPCs over the framed wire (int8/f16 wire dtype
    honored via ``FLAGS_multihost_wire_dtype``), fused ``[emb | w]``
    rows back, and a found mask so a feasign training never saw keeps
    serving zeros. Replicas NEVER write through this object — training
    owns the tier; a replica's deltas land only on its local hot/warm
    copies (the donefile publisher), which shadow the backing rows.
    """

    def __init__(self, endpoints: Sequence[str], dim: int, *,
                 ranges=None, timeout: float = 60.0, replica_map=None):
        from paddlebox_tpu.multihost.keyrange import ShardRangeTable
        self.dim = int(dim)
        self._timeout = float(timeout)
        if replica_map is not None:
            self.replica_map = replica_map
            self.ranges = replica_map.table
            self.endpoints = replica_map.primaries()
        else:
            self.replica_map = None
            self.ranges = (ranges if ranges is not None
                           else ShardRangeTable.for_world(len(endpoints)))
            if self.ranges.world != len(endpoints):
                raise ValueError(
                    f"{len(endpoints)} endpoints != range table world "
                    f"{self.ranges.world}")
            self.endpoints = list(endpoints)
        self._clients = self._build_clients()

    def _build_clients(self):
        # Replicated tier: each slot conn's reconnect-time resolve hook
        # cycles through the slot's CURRENT replica set, so a replica's
        # miss-path read survives a shard-host kill -9 at the cost of
        # one reconnect — pull_serving is a pure read any replica
        # answers (zero failed predict RPCs in the failover drill).
        from paddlebox_tpu.multihost.shard_service import ShardClient

        def replicas_fn(slot):
            if self.replica_map is None:
                return None
            return lambda: (self.replica_map.replicas_of(slot)
                            if self.replica_map is not None else ())
        return [ShardClient(self.endpoints[s], timeout=self._timeout,
                            replicas_fn=replicas_fn(s))
                for s in range(self.ranges.world)]

    def set_replica_map(self, replica_map) -> None:
        """Adopt a promoted/repaired replica-map generation (same slot
        count, endpoints re-pointed)."""
        old = self._clients
        self.replica_map = replica_map
        self.ranges = replica_map.table
        self.endpoints = replica_map.primaries()
        self._clients = self._build_clients()
        for c in old:
            c.close()

    def read(self, keys: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
        """(found [n], fused vals [n, dim+1]) for sorted unique keys —
        the DiskShards.read-shaped surface the serving tier store's
        miss path consumes. One RPC per owning shard, concurrent."""
        from paddlebox_tpu.multihost import shard_service
        faults.faultpoint("fleet/shard_miss")
        keys = np.ascontiguousarray(keys, np.uint64)
        n = keys.shape[0]
        found = np.zeros((n,), bool)
        vals = np.zeros((n, self.dim + 1), np.float32)
        if n == 0:
            return found, vals
        wire = shard_service.wire_mode()
        owner = self.ranges.owner_of(keys)
        order = np.argsort(owner, kind="stable")
        starts = np.searchsorted(owner[order],
                                 np.arange(self.ranges.world + 1))
        work = []
        for h in range(self.ranges.world):
            idx = order[starts[h]:starts[h + 1]]
            if idx.size:
                work.append((h, idx))
        results: Dict[int, dict] = {}
        errs: List[BaseException] = []
        # Pipelined on the slots' mux'd conns (PR 16): the sends leave
        # back-to-back from this thread — which also means the caller's
        # trace context (the coalesced batch's, via the micro-batcher)
        # rides each request without thread plumbing.
        if len(work) == 1:
            h, idx = work[0]
            try:
                results[h] = self._clients[h].call(
                    "pull_serving", keys=keys[idx], wire=wire)
            except BaseException as e:
                errs.append(e)
        else:
            futs = []
            for h, idx in work:
                try:
                    futs.append((h, self._clients[h].call_async(
                        "pull_serving", keys=keys[idx], wire=wire)))
                except BaseException as e:
                    errs.append(e)
            for h, f in futs:
                try:
                    results[h] = f.result()
                except BaseException as e:
                    errs.append(e)
        if errs:
            # A lost shard fails the miss resolution loudly — serving a
            # zero row for a key the tier OWNS would silently mis-rank.
            raise errs[0]
        rx = 0
        for h, idx in work:
            res = results[h]
            rx += shard_service.payload_nbytes(res)
            emb = shard_service.decode_emb(res)
            f = np.asarray(res["found"], bool)
            found[idx] = f
            vals[idx, :self.dim] = emb
            vals[idx, self.dim] = np.asarray(res["w"], np.float32)
        monitor.add("serving/shard_miss_keys", int(n))
        monitor.add("serving/shard_miss_bytes", int(rx))
        monitor.add("serving/shard_miss_unknown", int(n - found.sum()))
        return found, vals

    def num_features(self) -> int:
        """Total keys resident in the backing tier (stats fan-out)."""
        total = 0
        for c in self._clients:
            total += int(c.call("stats")["num_features"])
        return total

    def close(self) -> None:
        for c in self._clients:
            c.close()


def start_replica(model, feed_config, *, endpoint: str = "127.0.0.1:0",
                  base_export: Optional[str] = None,
                  dense_params=None,
                  shard_endpoints: Optional[Sequence[str]] = None,
                  shard_replicas: int = 1,
                  hbm_rows: Optional[int] = None,
                  watch_root: Optional[str] = None,
                  table: str = "embedding",
                  elastic_root: Optional[str] = None,
                  host_id: Optional[str] = None,
                  warm_lines: Optional[Sequence[str]] = None,
                  **predictor_kw):
    """Stand one serving replica up and (optionally) register it with
    the fleet: build the predictor from the donefile-base xbox export,
    plug its warm/cold misses into the shared shard tier, run a warm-up
    predict BEFORE advertising the endpoint (a joining replica must
    never take traffic cold), then heartbeat ``serving_endpoint`` into
    the elastic root the router watches. Returns (server, manager) —
    manager is None without an elastic root."""
    from paddlebox_tpu.serving.predictor import CTRPredictor, load_xbox_model
    from paddlebox_tpu.serving.service import PredictServer

    # Fail LOUDLY on a taken port before the expensive part. The bind
    # itself happens only after the predictor build + warm-up below —
    # minutes on a real model — so without this probe a supervisor
    # restarting a replica onto a port the old process still holds
    # burns the whole build first (and a subprocess worker dies after
    # its parent gave up waiting on the ready file: a hang, not an
    # error). Port 0 always binds; nothing to probe.
    host, _, port = endpoint.rpartition(":")
    if port not in ("", "0"):
        import socket
        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            # Match create_server's SO_REUSEADDR: a TIME_WAIT remnant
            # must not fail the probe — only a live listener should.
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            probe.bind((host or "127.0.0.1", int(port)))
        except OSError as e:
            raise RuntimeError(
                f"start_replica: endpoint {endpoint} is already bound "
                f"({e}) — refusing to build a predictor for a port "
                "this replica can never serve on") from e
        finally:
            probe.close()

    backing = None
    if shard_endpoints:
        if base_export is not None:
            keys, emb, w = load_xbox_model(base_export, table)
            dim = emb.shape[1]
        else:
            # No base export: the replica starts empty and warms every
            # row it serves from the shard tier on first miss.
            dim = int(predictor_kw.pop("dim"))
            keys = np.empty((0,), np.uint64)
            emb = np.empty((0, dim), np.float32)
            w = np.empty((0,), np.float32)
        # shard_replicas > 1: the backing tier is replicated (ring map
        # over the listed endpoints, MULTIHOST.md) — miss-path reads
        # then fail over across a slot's backups on a shard-host death.
        from paddlebox_tpu.multihost.replication import ReplicaMap
        rmap = (ReplicaMap.ring(list(shard_endpoints), shard_replicas)
                if int(shard_replicas) > 1 else None)
        backing = ShardBackedStore(shard_endpoints, dim,
                                   replica_map=rmap)
        pred = CTRPredictor(model, feed_config, keys, emb, w, dense_params,
                            hbm_rows=hbm_rows, shard_backing=backing,
                            **predictor_kw)
    else:
        keys, emb, w = load_xbox_model(base_export, table)
        pred = CTRPredictor(model, feed_config, keys, emb, w, dense_params,
                            hbm_rows=hbm_rows, **predictor_kw)
    if warm_lines:
        from paddlebox_tpu.data.parser import parse_lines
        from paddlebox_tpu.serving.batcher import pack_bucketed
        ins = parse_lines(list(warm_lines), feed_config)
        pred.predict(pack_bucketed(ins, feed_config))
    server = PredictServer(endpoint, pred, watch_root=watch_root,
                           watch_table=table)
    manager = None
    if elastic_root is not None:
        from paddlebox_tpu.launch.elastic import ElasticManager
        manager = ElasticManager(
            elastic_root, host_id or f"replica-{server.endpoint}",
            meta={"serving_endpoint": server.endpoint})
        manager.start()
    return server, manager
