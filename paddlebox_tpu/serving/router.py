"""FleetRouter: the serving fleet's wire front-end.

Role of the request-routing tier in front of the reference's AIBox
inference workers: ONE endpoint speaking the existing predict/stats
typed-frame protocol (``PredictClient`` works against it unchanged),
fanning requests across N :class:`~paddlebox_tpu.serving.service.
PredictServer` replicas that all serve the same model out of the
shared shard tier.

Routing policy (state lives in :class:`~paddlebox_tpu.serving.fleet.
ServingFleet`; SERVING_FLEET.md documents the full machine):

- **consistent hash** on the request's leading feature token (the user
  key by svm convention) → a stable home replica, so one user's
  requests keep hitting the replica whose HBM/warm tiers already hold
  their rows;
- **least-loaded spillover** when the home replica's in-flight predicts
  exceed ``FLAGS_fleet_spillover_inflight`` — affinity yields to load
  under key skew;
- **SLO-driven admission**: a replica whose ``slo/violations`` trips
  within the admission window serves its OVERFLOW through the cheap
  degraded path (HBM-hot-rows-only forward, ``degraded=true`` in the
  reply) instead of queueing behind a replica already missing its SLO;
- **health ejection + transparent re-route**: predict is a pure read,
  so a routed call that dies on a dead connection re-routes to another
  healthy replica inside the SAME client RPC — a kill -9'd replica
  costs latency, never a failed client call.

Replies are ``{"probs", "degraded", "replica", "epoch"}`` dicts;
``PredictClient.predict`` unwraps them (``last_degraded`` /
``last_replica``) and plain float arrays from a bare replica pass
through untouched, so one client speaks to both.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.core import faults, log, monitor, timeseries, trace
from paddlebox_tpu.core.quantiles import LogQuantileDigest
from paddlebox_tpu.distributed import rpc, wire
from paddlebox_tpu.serving.fleet import (Replica, ServingFleet,
                                         route_key_hash)


class FleetRouter(rpc.FramedRPCServer):
    """Route the predict/stats wire protocol across a serving fleet."""

    service_name = "fleet-router"

    # The router's ``stats`` fans RPCs out to every replica — a blocking
    # network handler, so it must NOT run inline on the poller thread.
    POLLER_INLINE = rpc.FramedRPCServer.POLLER_INLINE - {"stats"}

    def __init__(self, endpoint: str = "127.0.0.1:0", *,
                 fleet: Optional[ServingFleet] = None,
                 replicas: Optional[Sequence[str]] = None,
                 elastic_root: Optional[str] = None,
                 start_health: bool = True):
        self.fleet = fleet or ServingFleet(elastic_root=elastic_root)
        if replicas:
            for i, ep in enumerate(replicas):
                self.fleet.add_replica(f"replica-{i}", ep, ready=True)
        self._route_lat = LogQuantileDigest()
        self._route_lock = threading.Lock()
        # Per-ROUTER registry beside the global (the PredictServer /
        # ShardServer instance-Monitor pattern): hop decomposition and
        # routing counters for THIS router, servable to the cluster
        # scrape without conflating in-process test fleets.
        self.metrics = monitor.Monitor()
        # Router trend ring (core/timeseries.py) for the
        # metrics_history RPC; idle until the sampler is armed.
        self.history = timeseries.history_for(self.metrics,
                                              label="router")
        # Mirror the fleet's topology gauges (fleet/topology_epoch +
        # per-replica state codes) into this router's registry: ONE
        # metrics_snapshot on the router shows membership without a
        # stats fan-out (what the autoscaler and fleet_top read).
        self.fleet.attach_registry(self.metrics)
        if start_health:
            self.fleet.start()
        rpc.FramedRPCServer.__init__(self, endpoint, backlog=128)

    def _bump(self, name: str, delta: int = 1) -> None:
        monitor.add(name, delta)
        self.metrics.add(name, delta)

    def _observe_q(self, name: str, value: float) -> None:
        monitor.observe_quantile(name, value)
        self.metrics.observe_quantile(name, value)

    # -- predict routing ---------------------------------------------------

    def _forward(self, replica: Replica, lines: List[str],
                 degraded: bool, rid: Optional[str] = None):
        """One predict attempt against one replica (conn from its
        pool; a broken conn is closed, not returned). Returns
        (reply, replica server ms from the framed reply — None on a
        pre-decomposition peer)."""
        conn = replica.pool.acquire()
        try:
            kw = {"lines": lines}
            if degraded:
                kw["degraded"] = True
            if rid is not None:
                # The rid rides to the replica: quality sampling keys
                # on it there, and the late-label fanout
                # (handle_labels) joins on the SAME id.
                kw["rid"] = rid
            out = conn.call("predict", **kw)
            server_ms = conn.last_server_ms
        except BaseException:
            conn.close()
            raise
        replica.pool.release(conn)
        return out, server_ms

    def handle_predict(self, req) -> dict:
        """Route one predict: hash-affinity pick (spillover/degraded per
        admission state), forward, and on a DEAD CONNECTION re-route to
        the next healthy replica inside this same RPC — predict is a
        pure read, so the retry is safe and the client never sees the
        kill. In-band replica errors (a ValueError for an oversized
        request) are NOT retried: they would fail identically
        anywhere."""
        t0 = time.perf_counter()
        faults.faultpoint("fleet/route")
        lines: List[str] = list(req["lines"])
        key_hash = route_key_hash(lines)
        tried: set = set()
        last_err: Optional[BaseException] = None
        with trace.span("fleet/route", lines=len(lines)):
            for _attempt in range(max(self.fleet.size(), 1) + 1):
                replica, _mode, degraded = self.fleet.pick(
                    key_hash, exclude=tuple(tried))
                if replica is None:
                    break
                tried.add(replica.id)
                t_pick = time.perf_counter()
                try:
                    probs, srv_ms = self._forward(
                        replica, lines, degraded,
                        rid=req.get("rid"))
                except (OSError, wire.WireError) as e:
                    # Dead socket / torn reply stream: strike (ejects at
                    # the same threshold as the health thread) and
                    # re-route — predict is a pure read, so replaying it
                    # on another replica is safe.
                    last_err = e
                    self.fleet.release(replica)
                    self.fleet.strike(replica)
                    self._bump("fleet/reroutes", 1)
                    continue
                self.fleet.release(replica)
                self._bump("fleet/routed", 1)
                t_done = time.perf_counter()
                ms = (t_done - t0) * 1e3
                monitor.observe_quantile("fleet/route_ms", ms)
                with self._route_lock:
                    self._route_lat.observe(ms)
                # Per-hop decomposition: router queue/pick share, the
                # replica's server wall (off its framed reply), and the
                # router→replica wire remainder. Returned in the reply
                # so the CLIENT adds its own wire share on top.
                route_ms = (t_pick - t0) * 1e3
                fwd_ms = (t_done - t_pick) * 1e3
                hop = {"route_ms": round(route_ms, 3)}
                if isinstance(srv_ms, (int, float)):
                    hop["server_ms"] = round(float(srv_ms), 3)
                    hop["wire_ms"] = round(
                        max(0.0, fwd_ms - float(srv_ms)), 3)
                    self._observe_q("fleet/hop_server_ms",
                                    hop["server_ms"])
                    self._observe_q("fleet/hop_wire_ms", hop["wire_ms"])
                self._observe_q("fleet/hop_route_ms", route_ms)
                return {"probs": np.asarray(probs, np.float32),
                        "degraded": bool(degraded),
                        "replica": replica.id,
                        "epoch": int(self.fleet.epoch),
                        "hop": hop}
        self._bump("fleet/route_failures", 1)
        raise RuntimeError(
            f"no serving replica could answer (tried {sorted(tried)}): "
            f"{last_err!r}")

    def handle_apply_delta(self, req) -> int:
        """Fan a delta export out to EVERY healthy replica (the RPC
        update path; the donefile publisher per replica is the usual
        route). Returns the first replica's new-key count — replicas
        serve the same model, so the counts agree. Not idempotent: a
        replica failure surfaces to the caller instead of retrying."""
        n_new: Optional[int] = None
        applied = 0
        for r in self.fleet.healthy():
            conn = r.pool.acquire()
            try:
                got = conn.call("apply_delta", path=req["path"],
                                table=req.get("table", "embedding"),
                                kind=req.get("kind", "delta"))
            except BaseException:
                conn.close()
                raise
            r.pool.release(conn)
            applied += 1
            if n_new is None:
                n_new = int(got)
        if applied == 0:
            raise RuntimeError("no healthy replica to apply the delta")
        monitor.add("fleet/delta_fanout", applied)
        return int(n_new)

    def handle_labels(self, req) -> dict:
        """Fan a sampled request's late labels to every healthy replica.
        The label feed does not know which replica served a rid (the
        router's hash pick, plus spillover/re-routes, decided that), so
        it delivers through the router and exactly the replica holding
        the rid in its pending window joins — the others count a miss,
        which the quality layer already treats as normal trailing-feed
        behavior. Returns whether ANY replica joined."""
        joined = False
        fanout = 0
        for r in self.fleet.healthy():
            conn = r.pool.acquire()
            try:
                got = conn.call("labels", rid=str(req["rid"]),
                                labels=req["labels"])
            except (OSError, ConnectionError, RuntimeError):
                conn.close()
                continue
            r.pool.release(conn)
            fanout += 1
            if got.get("joined"):
                joined = True
        self._bump("fleet/label_fanout", 1)
        return {"joined": joined, "fanout": fanout}

    # -- control plane -----------------------------------------------------

    def handle_topology(self, req) -> dict:
        """The fleet's current membership + epoch — what a
        direct-to-replica ``PredictClient`` re-resolves through after a
        reconnect, and what drills assert ejection/join against."""
        return {"epoch": int(self.fleet.epoch),
                "replicas": self.fleet.replicas()}

    def handle_stats(self, req) -> dict:
        """Fleet-wide stats: fan ``metrics_snapshot`` out to every
        healthy replica and fold the per-replica registries through
        ``monitor.merge_snapshots`` (counters summed, digests merged) —
        ``slo/violations`` and the predict-latency quantiles become
        fleet-wide observables in one read. Per-replica briefs +
        summaries ride along for skew diagnosis."""
        snaps: List[dict] = []
        briefs: Dict[str, dict] = {}
        rps_total = 0.0
        for r in self.fleet.healthy():
            conn = r.pool.acquire()
            try:
                snap = conn.call("metrics_snapshot")
                st = conn.call("stats")
            except (OSError, ConnectionError, RuntimeError) as e:
                conn.close()
                log.warning("fleet stats: replica %s unreachable: %r",
                            r.id, e)
                continue
            r.pool.release(conn)
            snaps.append(snap)
            b = r.brief()
            b["stats"] = st
            briefs[r.id] = b
            rps_total += float(st.get("throughput_rps", 0.0))
        merged = monitor.merge_snapshots(snaps)
        lat = {}
        pred = merged.get("quantiles", {}).get("serving/predict_ms")
        if pred:
            lat = {k: (round(v, 3) if v is not None else None)
                   for k, v in LogQuantileDigest.from_dict(
                       pred).quantiles().items()}
        with self._route_lock:
            route_q = {k: (round(v, 3) if v is not None else None)
                       for k, v in self._route_lat.quantiles().items()}
        counters = merged.get("counters", {})
        snap = monitor.snapshot()
        return {"fleet_size": len(snaps),
                "epoch": int(self.fleet.epoch),
                "throughput_rps": round(rps_total, 3),
                "latency_ms": lat,
                "route_ms": route_q,
                "predict_rpcs": int(
                    counters.get("serving/predict_rpcs", 0)),
                "degraded_rpcs": int(
                    counters.get("serving/degraded_rpcs", 0)),
                "slo_violations": int(counters.get("slo/violations", 0)),
                # Router-process conn health: reconnects/retries its
                # replica pools burned (the failover-blip assertions).
                "rpc_reconnects": int(snap.get("rpc/reconnects", 0)),
                "rpc_retries": int(snap.get("rpc/retries", 0)),
                "merged": merged,
                "replicas": briefs}

    def handle_metrics_snapshot(self, req) -> dict:
        """The ROUTER's own instance registry (hop decomposition,
        routing counters) with the route-latency digest injected — its
        share of the one-scrape cluster snapshot. Replica registries
        are scraped directly from the replicas (or folded via
        ``handle_stats``), not re-served here."""
        out = self.metrics.snapshot_all(
            labels={"service": self.service_name,
                    "endpoint": self.endpoint})
        with self._route_lock:
            out["quantiles"]["fleet/route_ms"] = \
                self._route_lat.to_dict()
        return out

    def handle_metrics_history(self, req) -> dict:
        """The router's own trend ring (routing counters, hop
        latencies) for the fleet_top sparkline pane."""
        return self.history.to_dict(window_s=req.get("window_s"),
                                    last_n=req.get("last_n"))

    def handle_stop(self, req) -> bool:
        self.stop()
        return True

    def stop(self) -> None:
        self.fleet.stop()
        rpc.FramedRPCServer.stop(self)
