"""Online predict service: the CTRPredictor behind the typed wire.

Role of the serving deployment the reference pairs its training stack
with (an online service loads the per-pass xbox exports and answers CTR
requests while deltas stream in — the "realtime model update" half of
the README's pitch): a socket server owning one :class:`CTRPredictor`,
answering predict RPCs on raw svm-format lines and accepting live
base/delta updates between requests, over the same typed-frame protocol
as the PS and graph services (service loop/framing from
``distributed/rpc.py`` — no pickle, version-checked; trusted cluster
network).

Concurrent predict RPCs do not serialize on the device: handler threads
parse their lines and hand the rows to the shared
:class:`~paddlebox_tpu.serving.batcher.MicroBatcher`, which coalesces
everything waiting into ONE ragged device forward per batching window
and demuxes per-request probability slices back. Padding is masked
rows inside the packed batch — never synthesized svm lines — and the
predictor's internal lock gives every micro-batch one consistent model
version against live ``apply_update`` / publisher hot-swaps.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from paddlebox_tpu.core import flags, monitor, report, trace
from paddlebox_tpu.core.quantiles import LogQuantileDigest
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.distributed import rpc
from paddlebox_tpu.serving.batcher import MicroBatcher
from paddlebox_tpu.serving.predictor import CTRPredictor, load_delta_update


class PredictServer(rpc.FramedRPCServer):
    """One predictor endpoint (role of a serving replica).

    ``watch_root`` (optional) points at a training day loop's checkpoint
    root: a :class:`~paddlebox_tpu.serving.publisher.DonefilePublisher`
    thread then tails its donefile and hot-swaps each newly published
    per-pass delta into the live predictor — the zero-downtime
    train→serve flow, no RPC required.
    """

    service_name = "serving"

    def __init__(self, endpoint: str, predictor: CTRPredictor, *,
                 watch_root: Optional[str] = None,
                 watch_table: str = "embedding"):
        self.predictor = predictor
        # Arm the telemetry sinks (trace/metrics paths) once per replica;
        # per-request cost is one cached-bool check when disabled.
        report.init_telemetry_from_flags()
        # SLO layer: server-side predict latency quantile digest (the
        # log-bucketed sketch — sub-ms CPU predicts and multi-second
        # tunnel stalls both land within 1% relative error) + the
        # rotating window snapshots behind the throughput gauge. The
        # digest is per-replica state; the registry copy under
        # serving/predict_ms merges across replicas via
        # monitor.merge_snapshots.
        self._started = time.time()
        self._latency = LogQuantileDigest()
        self._lat_lock = threading.Lock()  # handlers run per-connection
        # Sliding-window throughput state: (anchor time, digest copy at
        # anchor). Rotated every FLAGS_serving_rps_window_s; the rate is
        # delta-counts over the previous anchor, so an idle replica
        # decays to 0 within two windows instead of reporting a stale
        # lifetime average.
        self._win_prev = (self._started, self._latency.copy())
        self._win_cur = (self._started, self._latency.copy())
        self._batcher = MicroBatcher(predictor)
        self._publisher = None
        if watch_root is not None:
            from paddlebox_tpu.serving.publisher import DonefilePublisher
            self._publisher = DonefilePublisher(
                predictor, watch_root, table=watch_table)
            self._publisher.start()
        rpc.FramedRPCServer.__init__(self, endpoint)

    # -- throughput window -------------------------------------------------

    def _window_rps(self, now: float) -> float:
        """Requests/s over the sliding window: LogQuantileDigest.delta()
        count against the previous window anchor (callers hold
        _lat_lock)."""
        win = max(float(flags.flag("serving_rps_window_s")), 1e-3)
        if now - self._win_cur[0] >= win:
            self._win_prev = self._win_cur
            self._win_cur = (now, self._latency.copy())
        t0, base = self._win_prev
        return self._latency.delta(base).count / max(now - t0, 1e-9)

    # -- handlers ---------------------------------------------------------

    def handle_predict(self, req) -> np.ndarray:
        """Raw svm-format lines -> CTR probabilities [n_lines]. Requests
        beyond the predictor's feed batch_size are rejected (the caller
        splits; the micro-batcher coalesces many small requests, it
        does not split one huge one)."""
        t0 = time.perf_counter()
        lines: List[str] = list(req["lines"])
        feed = self.predictor.feed
        if len(lines) > feed.batch_size:
            raise ValueError(
                f"{len(lines)} lines exceed the serving batch size "
                f"{feed.batch_size} — split the request")
        n = len(lines)
        with trace.span("serving/predict", lines=n):
            # Real rows only: padding to the packed shape is masked
            # rows inside the batcher's bucketed pack — the old path
            # synthesized '0' svm lines and paid parse work to create
            # rows indistinguishable from real label-0 instances.
            instances = parse_lines(lines, feed)
            out = self._batcher.predict(instances)
        ms = (time.perf_counter() - t0) * 1e3
        monitor.add("serving/predict_rpcs", 1)
        monitor.add("serving/predict_lines", n)
        monitor.observe("serving/predict_ms", ms)
        monitor.observe_quantile("serving/predict_ms", ms)
        now = time.time()
        with self._lat_lock:
            self._latency.observe(ms)
            rps = self._window_rps(now)
        # SLO check (FLAGS_serving_slo_p99_ms): each breaching RPC is a
        # counted violation — the p99 the operator reads from
        # handle_stats then says how much margin remains.
        slo = float(flags.flag("serving_slo_p99_ms"))
        if slo > 0 and ms > slo:
            monitor.add("slo/violations", 1)
        monitor.set_gauge("serving/throughput_rps", rps)
        return out

    def handle_apply_delta(self, req) -> int:
        """Live model refresh from a delta export directory (the online
        update path — serving_online_update's surface over the wire)."""
        with trace.span("serving/apply_delta", path=req["path"]):
            keys, emb, w = load_delta_update(req["path"], req.get(
                "table", "embedding"))
            n_new = self.predictor.apply_update(keys, emb, w)
        monitor.add("serving/delta_rpcs", 1)
        return int(n_new)

    def handle_stats(self, req) -> dict:
        snap = monitor.snapshot()
        gauges = monitor.snapshot_all().get("gauges", {})
        now = time.time()
        uptime = now - self._started
        with self._lat_lock:
            lat = {k: (round(v, 3) if v is not None else None)
                   for k, v in self._latency.quantiles().items()}
            n_lat = self._latency.count
            rps = self._window_rps(now)
        return {"keys": int(self.predictor.num_keys),
                "dim": int(self.predictor._dim),
                "predict_rpcs": int(snap.get("serving/predict_rpcs", 0)),
                "predict_lines": int(snap.get("serving/predict_lines",
                                              0)),
                "delta_rpcs": int(snap.get("serving/delta_rpcs", 0)),
                "uptime_s": round(uptime, 3),
                # Server-side latency quantiles + the SLO they are read
                # against (client predict keeps its OWN digest, so
                # server time vs wire time separate cleanly).
                "latency_ms": lat,
                "latency_count": n_lat,
                # Sliding-window rate (NOT lifetime count / lifetime
                # uptime — that decays forever on an idle replica).
                "throughput_rps": round(rps, 3),
                "batches": int(snap.get("serving/batches", 0)),
                "batch_fill_frac": float(
                    gauges.get("serving/batch_fill_frac", 0.0)),
                "hotswap_applied": int(
                    snap.get("serving/hotswap_applied", 0)),
                "slo_p99_ms": float(flags.flag("serving_slo_p99_ms")),
                "slo_violations": int(snap.get("slo/violations", 0))}

    def handle_stop(self, req) -> bool:
        self.stop()
        return True

    def stop(self) -> None:
        if self._publisher is not None:
            self._publisher.stop()
            self._publisher = None
        self._batcher.close()
        rpc.FramedRPCServer.stop(self)


class PredictClient:
    """Blocking client for one serving endpoint."""

    def __init__(self, endpoint: str, timeout: float = 60.0):
        # predict/stats are pure reads: a serving blip reconnects and
        # retries them under the rpc retry flags; apply_delta/stop are
        # NOT idempotent and surface connection errors to the caller.
        self._conn = rpc.FramedRPCConn(endpoint, timeout=timeout,
                                       service_name="serving",
                                       idempotent=("predict", "stats"))
        # End-to-end predict latency (RPC round-trip included): diffing
        # these quantiles against the server's handle_stats latency_ms
        # separates server time from wire time per percentile.
        self._latency = LogQuantileDigest()

    def predict(self, lines: List[str]) -> np.ndarray:
        # The wire serializes str natively (utf-8 frames) — no
        # per-line encode/decode round-trip.
        t0 = time.perf_counter()
        out = self._conn.call("predict", lines=list(lines))
        self._latency.observe((time.perf_counter() - t0) * 1e3)
        return out

    def latency_quantiles(self) -> dict:
        """Client-observed end-to-end predict latency (ms): p50/p90/
        p99/p999 + count — the wire-inclusive twin of the server's
        ``stats()['latency_ms']``."""
        out = {k: (round(v, 3) if v is not None else None)
               for k, v in self._latency.quantiles().items()}
        out["count"] = self._latency.count
        return out

    def apply_delta(self, path: str, table: str = "embedding") -> int:
        return self._conn.call("apply_delta", path=path, table=table)

    def stats(self) -> dict:
        return self._conn.call("stats")

    def stop_server(self) -> None:
        try:
            self._conn.call("stop")
        except (RuntimeError, OSError, ConnectionError):
            pass

    def close(self) -> None:
        self._conn.close()
