"""Online predict service: the CTRPredictor behind the typed wire.

Role of the serving deployment the reference pairs its training stack
with (an online service loads the per-pass xbox exports and answers CTR
requests while deltas stream in — the "realtime model update" half of
the README's pitch): a socket server owning one :class:`CTRPredictor`,
answering predict RPCs on raw svm-format lines and accepting live
base/delta updates between requests, over the same typed-frame protocol
as the PS and graph services (service loop/framing from
``distributed/rpc.py`` — no pickle, version-checked; trusted cluster
network).

Concurrent predict RPCs do not serialize on the device: handler threads
parse their lines and hand the rows to the shared
:class:`~paddlebox_tpu.serving.batcher.MicroBatcher`, which coalesces
everything waiting into ONE ragged device forward per batching window
and demuxes per-request probability slices back. Padding is masked
rows inside the packed batch — never synthesized svm lines — and the
predictor's internal lock gives every micro-batch one consistent model
version against live ``apply_update`` / publisher hot-swaps.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from paddlebox_tpu.core import (flags, monitor, quality, report,
                                timeseries, trace)
from paddlebox_tpu.core.quantiles import LogQuantileDigest
from paddlebox_tpu.data.parser import parse_lines
from paddlebox_tpu.distributed import rpc
from paddlebox_tpu.serving.batcher import MicroBatcher
from paddlebox_tpu.serving.predictor import CTRPredictor


class PredictServer(rpc.FramedRPCServer):
    """One predictor endpoint (role of a serving replica).

    ``watch_root`` (optional) points at a training day loop's checkpoint
    root: a :class:`~paddlebox_tpu.serving.publisher.DonefilePublisher`
    thread then tails its donefile and hot-swaps each newly published
    per-pass delta into the live predictor — the zero-downtime
    train→serve flow, no RPC required.
    """

    service_name = "serving"

    def __init__(self, endpoint: str, predictor: CTRPredictor, *,
                 watch_root: Optional[str] = None,
                 watch_table: str = "embedding",
                 replica_id: Optional[str] = None):
        self.predictor = predictor
        self.replica_id = replica_id or ""
        # Arm the telemetry sinks (trace/metrics paths) once per replica;
        # per-request cost is one cached-bool check when disabled.
        report.init_telemetry_from_flags()
        # Per-REPLICA registry beside the process-global one: a fleet
        # test/bench runs several replicas in one process, and the
        # router's cluster-stats fan-out must merge per-replica
        # snapshots, not N copies of the same global registry. Serving
        # counters bump both; the global keeps its existing meaning.
        self.metrics = monitor.Monitor()
        # Trend ring over the instance registry (core/timeseries.py):
        # registered with the global sampler, answered by the
        # metrics_history RPC — idle (never sampled) until the sampler
        # is armed.
        self.history = timeseries.history_for(
            self.metrics, label=f"replica:{self.replica_id}")
        # SLO layer: server-side predict latency quantile digest (the
        # log-bucketed sketch — sub-ms CPU predicts and multi-second
        # tunnel stalls both land within 1% relative error) + the
        # rotating window snapshots behind the throughput gauge. The
        # digest is per-replica state; the registry copy under
        # serving/predict_ms merges across replicas via
        # monitor.merge_snapshots.
        self._started = time.time()
        self._latency = LogQuantileDigest()
        self._lat_lock = threading.Lock()  # handlers run per-connection
        # Sliding-window throughput state: (anchor time, digest copy at
        # anchor). Rotated every FLAGS_serving_rps_window_s; the rate is
        # delta-counts over the previous anchor, so an idle replica
        # decays to 0 within two windows instead of reporting a stale
        # lifetime average.
        self._win_prev = (self._started, self._latency.copy())
        self._win_cur = (self._started, self._latency.copy())
        self._batcher = MicroBatcher(predictor, metrics=self.metrics)
        # Served-traffic calibration (core/quality.py): sampled
        # prediction logging keyed by request id + late label join —
        # labels trail through the stream tier's event log. Alarms and
        # gauges land in the instance registry too, so the fleet's
        # metrics_snapshot scrape shows THIS replica's model health.
        # Eagerly built (small fixed arrays): no handler-thread race;
        # sampling itself is off until FLAGS_quality_sample_rate > 0.
        self.quality = quality.ServingQuality(registries=(self.metrics,))
        self._publisher = None
        if watch_root is not None:
            from paddlebox_tpu.serving.publisher import DonefilePublisher
            self._publisher = DonefilePublisher(
                predictor, watch_root, table=watch_table)
            self._publisher.start()
        rpc.FramedRPCServer.__init__(self, endpoint)

    # -- throughput window -------------------------------------------------

    def _window_rps(self, now: float) -> float:
        """Requests/s over the sliding window: LogQuantileDigest.delta()
        count against the previous window anchor (callers hold
        _lat_lock)."""
        win = max(float(flags.flag("serving_rps_window_s")), 1e-3)
        if now - self._win_cur[0] >= win:
            self._win_prev = self._win_cur
            self._win_cur = (now, self._latency.copy())
        t0, base = self._win_prev
        return self._latency.delta(base).count / max(now - t0, 1e-9)

    # -- handlers ---------------------------------------------------------

    def handle_predict(self, req) -> np.ndarray:
        """Raw svm-format lines -> CTR probabilities [n_lines]. Requests
        beyond the predictor's feed batch_size are rejected (the caller
        splits; the micro-batcher coalesces many small requests, it
        does not split one huge one). ``degraded=True`` (the fleet
        router's SLO-shed path) packs and dispatches INLINE with
        HBM-hot-rows-only resolution — never coalesced with normal
        requests, whose batch would otherwise inherit the degraded
        lookup."""
        t0 = time.perf_counter()
        lines: List[str] = list(req["lines"])
        degraded = bool(req.get("degraded", False))
        feed = self.predictor.feed
        if len(lines) > feed.batch_size:
            raise ValueError(
                f"{len(lines)} lines exceed the serving batch size "
                f"{feed.batch_size} — split the request")
        n = len(lines)
        with trace.span("serving/predict", lines=n):
            # Real rows only: padding to the packed shape is masked
            # rows inside the batcher's bucketed pack — the old path
            # synthesized '0' svm lines and paid parse work to create
            # rows indistinguishable from real label-0 instances.
            instances = parse_lines(lines, feed)
            if degraded:
                from paddlebox_tpu.serving.batcher import pack_bucketed
                batch = pack_bucketed(instances, feed)
                out = np.asarray(
                    self.predictor.predict(batch, degraded=True)
                    [:len(instances)], np.float32)
                monitor.add("serving/degraded_rpcs", 1)
                self.metrics.add("serving/degraded_rpcs", 1)
            else:
                out = self._batcher.predict(instances)
        # Sampled calibration logging: a request carrying a rid may be
        # selected (crc32 hash, FLAGS_quality_sample_rate) — its
        # predictions wait in the bounded pending window for the late
        # label join (handle_labels).
        rid = req.get("rid")
        if rid is not None and float(
                flags.flag("quality_sample_rate")) > 0.0:
            self.quality.sample(str(rid), out)
        ms = (time.perf_counter() - t0) * 1e3
        monitor.add("serving/predict_rpcs", 1)
        monitor.add("serving/predict_lines", n)
        monitor.observe("serving/predict_ms", ms)
        monitor.observe_quantile("serving/predict_ms", ms)
        self.metrics.add("serving/predict_rpcs", 1)
        self.metrics.add("serving/predict_lines", n)
        # Instance-registry digest too: the per-replica history ring
        # computes window p99s from the registry it samples.
        self.metrics.observe_quantile("serving/predict_ms", ms)
        now = time.time()
        with self._lat_lock:
            self._latency.observe(ms)
            rps = self._window_rps(now)
        # SLO check (FLAGS_serving_slo_p99_ms): each breaching RPC is a
        # counted violation — the p99 the operator reads from
        # handle_stats then says how much margin remains.
        slo = float(flags.flag("serving_slo_p99_ms"))
        if slo > 0 and ms > slo:
            monitor.add("slo/violations", 1)
            self.metrics.add("slo/violations", 1)
        monitor.set_gauge("serving/throughput_rps", rps)
        self.metrics.set_gauge("serving/throughput_rps", rps)
        return out

    def handle_apply_delta(self, req) -> int:
        """Live model refresh from a delta export directory (the online
        update path — serving_online_update's surface over the wire).
        Routed through ``apply_update_export`` so flat, sharded, and
        dim-grouped delta roots all land."""
        kind = str(req.get("kind", "delta"))
        with trace.span("serving/apply_delta", path=req["path"]):
            # kind='xbox' applies a full serving-format BASE export —
            # the canary controller's staging/promote path (autopilot);
            # the default 'delta' stays the per-pass online update.
            n_new = self.predictor.apply_update_export(
                req["path"], req.get("table", "embedding"), kind)
        monitor.add("serving/delta_rpcs", 1)
        return int(n_new)

    def handle_rollback_to(self, req) -> int:
        """Re-apply a prior published record (autopilot canary rollback
        / operator reverse gear): routes through the publisher's
        ``rollback_to`` when this replica tails a donefile — marking
        the record seen so the tail will not re-apply it — else applies
        the export directly. Either way bumps
        ``serving/hotswap_rollbacks``. Returns rows written."""
        from paddlebox_tpu.checkpoint.protocol import DoneRecord
        rec = DoneRecord(str(req["day"]), int(req.get("key", 0)),
                         req["path"], int(req.get("pass_id", 0)))
        table = req.get("table", "embedding")
        with trace.span("serving/rollback_to", path=rec.path):
            if self._publisher is not None:
                return int(self._publisher.rollback_to(rec))
            kind = "xbox" if rec.pass_id == 0 else "delta"
            n_new = self.predictor.apply_update_export(
                rec.path, table, kind)
            monitor.add("serving/hotswap_rollbacks", 1)
            return int(n_new)

    def handle_labels(self, req) -> dict:
        """Late labels for a sampled predict (``rid`` + ``labels`` in
        request order): joins against the pending prediction log and
        feeds the served-traffic COPC/calibration window. An expired
        or never-sampled rid is a counted miss, never an error — the
        label feed (the stream tier's event log) trails serving by
        minutes and may replay."""
        joined = self.quality.join(
            str(req["rid"]), np.asarray(req["labels"], np.float64))
        return {"joined": bool(joined),
                "pending": int(self.quality.pending())}

    def handle_stats(self, req) -> dict:
        snap = monitor.snapshot()
        # Per-REPLICA counters come from the instance registry: with N
        # replicas in one process (fleet tests/bench) the global would
        # conflate them, and the router's SLO admission window must see
        # THIS replica's violations, not the fleet's.
        mine = self.metrics.snapshot()
        now = time.time()
        uptime = now - self._started
        with self._lat_lock:
            lat = {k: (round(v, 3) if v is not None else None)
                   for k, v in self._latency.quantiles().items()}
            n_lat = self._latency.count
            rps = self._window_rps(now)
        return {"keys": int(self.predictor.num_keys),
                "dim": int(self.predictor._dim),
                "replica_id": self.replica_id,
                "predict_rpcs": int(mine.get("serving/predict_rpcs", 0)),
                "predict_lines": int(mine.get("serving/predict_lines",
                                              0)),
                "degraded_rpcs": int(mine.get("serving/degraded_rpcs",
                                              0)),
                "delta_rpcs": int(snap.get("serving/delta_rpcs", 0)),
                "uptime_s": round(uptime, 3),
                # Server-side latency quantiles + the SLO they are read
                # against (client predict keeps its OWN digest, so
                # server time vs wire time separate cleanly).
                "latency_ms": lat,
                "latency_count": n_lat,
                # Sliding-window rate (NOT lifetime count / lifetime
                # uptime — that decays forever on an idle replica).
                "throughput_rps": round(rps, 3),
                "batches": int(mine.get("serving/batches", 0)),
                "batch_fill_frac": float(
                    self.metrics.get_gauge("serving/batch_fill_frac")),
                "hotswap_applied": int(
                    snap.get("serving/hotswap_applied", 0)),
                "slo_p99_ms": float(flags.flag("serving_slo_p99_ms")),
                "slo_violations": int(mine.get("slo/violations", 0)),
                # Process-level conn health (global registry: reconnect/
                # retry totals of every conn this process owns) — the
                # failover-blip drills assert the retry budget actually
                # consumed through the stats surface.
                "rpc_reconnects": int(snap.get("rpc/reconnects", 0)),
                "rpc_retries": int(snap.get("rpc/retries", 0)),
                # Model health of THIS replica (served-traffic sampled
                # calibration): total quality alarms raised here.
                "quality_alarms": int(sum(
                    v for k, v in mine.items()
                    if k.startswith("quality/alarms/")))}

    def handle_metrics_snapshot(self, req) -> dict:
        """This replica's labeled ``snapshot_all()`` (instance registry
        + the per-replica latency digest injected under quantiles) —
        what the fleet router's ``handle_stats`` fan-out merges with
        ``monitor.merge_snapshots`` into one cluster view."""
        out = self.metrics.snapshot_all(
            labels={"replica": self.replica_id,
                    "endpoint": self.endpoint})
        with self._lat_lock:
            out["quantiles"]["serving/predict_ms"] = \
                self._latency.to_dict()
        return out

    def handle_metrics_history(self, req) -> dict:
        """This replica's trend ring (instance registry) — the
        per-replica half of the fleet_top sparkline pane."""
        return self.history.to_dict(window_s=req.get("window_s"),
                                    last_n=req.get("last_n"))

    def handle_stop(self, req) -> bool:
        self.stop()
        return True

    def stop(self) -> None:
        if self._publisher is not None:
            self._publisher.stop()
            self._publisher = None
        self._batcher.close()
        rpc.FramedRPCServer.stop(self)


class PredictClient:
    """Blocking client for one serving endpoint — a replica directly,
    or a :class:`~paddlebox_tpu.serving.router.FleetRouter` (same wire
    protocol; the router's replies carry a ``degraded`` flag surfaced
    via :attr:`last_degraded`).

    ``router`` (optional) names a fleet router endpoint used as a
    TOPOLOGY resolver for a direct-to-replica client: when an
    idempotent retry has to reconnect, the client first re-resolves its
    endpoint through the router's current topology epoch — so a
    predict retried after a replica eject lands on a live replica
    instead of burning the whole retry deadline reconnecting to the
    dead one (the retry loop used to re-resolve against the fixed
    endpoint it was constructed with)."""

    def __init__(self, endpoint: str, timeout: float = 60.0, *,
                 router: Optional[str] = None):
        # predict/stats are pure reads: a serving blip reconnects and
        # retries them under the rpc retry flags; apply_delta/stop are
        # NOT idempotent and surface connection errors to the caller.
        self._router_ep = router
        self._router_conn: Optional[rpc.FramedRPCConn] = None
        self._topology_epoch = -1
        self._conn = rpc.FramedRPCConn(
            endpoint, timeout=timeout, service_name="serving",
            idempotent=("predict", "stats"),
            resolve=(self._resolve_endpoint if router else None))
        # End-to-end predict latency (RPC round-trip included): diffing
        # these quantiles against the server's handle_stats latency_ms
        # separates server time from wire time per percentile.
        self._latency = LogQuantileDigest()
        self.last_degraded = False
        self.last_replica: Optional[str] = None
        # Per-hop decomposition of the newest predict: the reply's
        # server share (router or replica handler wall) vs the client-
        # observed remainder (wire + connect), and — through a router —
        # the router's own hop split (route/wire/replica-server ms).
        self.last_server_ms: Optional[float] = None
        self.last_wire_ms: Optional[float] = None
        self.last_hop: Optional[dict] = None

    def _resolve_endpoint(self, current: str) -> str:
        """Reconnect-time hook: ask the router which replicas serve
        NOW; keep the current endpoint while it is still listed, else
        move to a live one (hashed by client identity so a fleet of
        retrying clients spreads instead of stampeding one replica)."""
        try:
            if self._router_conn is None:
                self._router_conn = rpc.FramedRPCConn(
                    self._router_ep, timeout=10.0,
                    service_name="fleet-router",
                    idempotent=("topology",))
            topo = self._router_conn.call("topology")
        except (OSError, ConnectionError, RuntimeError):
            return current  # router unreachable: retry where we were
        self._topology_epoch = int(topo.get("epoch", -1))
        live = [r["endpoint"] for r in topo.get("replicas", ())
                if r.get("state") == "healthy"]
        if not live:
            return current
        if current in live:
            return current
        monitor.add("serving/client_reresolves", 1)
        return live[hash(id(self)) % len(live)]

    def predict(self, lines: List[str], *,
                rid: Optional[str] = None) -> np.ndarray:
        # The wire serializes str natively (utf-8 frames) — no
        # per-line encode/decode round-trip. ``rid`` tags the request
        # for sampled calibration logging on the replica (late labels
        # follow via send_labels) — direct-replica clients only; the
        # router rebuilds its forwarded request without it.
        t0 = time.perf_counter()
        kwargs = {"lines": list(lines)}
        if rid is not None:
            kwargs["rid"] = str(rid)
        out = self._conn.call("predict", **kwargs)
        if isinstance(out, dict):
            # Router reply: probabilities + routing metadata (degraded
            # = the SLO-shed hot-rows-only path answered; hop = the
            # router's route/wire/replica-server decomposition).
            self.last_degraded = bool(out.get("degraded", False))
            self.last_replica = out.get("replica")
            self.last_hop = out.get("hop")
            out = out["probs"]
        else:
            self.last_degraded = False
            self.last_replica = None
            self.last_hop = None
        total_ms = (time.perf_counter() - t0) * 1e3
        self._latency.observe(total_ms)
        # The reply's _server_ms (every framed reply carries it) lets
        # the client attribute its observed latency: wire share = total
        # minus the peer's handler wall.
        self.last_server_ms = self._conn.last_server_ms
        self.last_wire_ms = self._conn.last_wire_ms
        if self.last_wire_ms is not None:
            monitor.observe_quantile("serving/client_wire_ms",
                                     self.last_wire_ms)
        return out

    def latency_quantiles(self) -> dict:
        """Client-observed end-to-end predict latency (ms): p50/p90/
        p99/p999 + count — the wire-inclusive twin of the server's
        ``stats()['latency_ms']``."""
        out = {k: (round(v, 3) if v is not None else None)
               for k, v in self._latency.quantiles().items()}
        out["count"] = self._latency.count
        return out

    def send_labels(self, rid: str, labels) -> dict:
        """Deliver a sampled request's late labels (the stream tier's
        event log catching up with served traffic) for the replica's
        prediction+label calibration join."""
        return self._conn.call("labels", rid=str(rid),
                               labels=[float(v) for v in labels])

    def apply_delta(self, path: str, table: str = "embedding",
                    kind: str = "delta") -> int:
        return self._conn.call("apply_delta", path=path, table=table,
                               kind=kind)

    def rollback_to(self, day: str, path: str, *, key: int = 0,
                    pass_id: int = 0, table: str = "embedding") -> int:
        """Re-apply a prior published record on the replica (the
        autopilot's canary-rollback actuator)."""
        return self._conn.call("rollback_to", day=str(day), path=path,
                               key=int(key), pass_id=int(pass_id),
                               table=table)

    def stats(self) -> dict:
        return self._conn.call("stats")

    def stop_server(self) -> None:
        try:
            self._conn.call("stop")
        except (RuntimeError, OSError, ConnectionError):
            pass

    def close(self) -> None:
        self._conn.close()
        if self._router_conn is not None:
            self._router_conn.close()
            self._router_conn = None
