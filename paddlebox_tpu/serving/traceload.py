"""Replay-pure serving-traffic trace generator: the fleet bench AND
the chaos drill harness.

Role of the load half of the autopilot loop (AUTOPILOT.md): the
reference's serving tier is sized against diurnal, heavily skewed CTR
traffic — a small hot set of users/items takes most of the lookups
("Dissecting Embedding Bag Performance in DLRM Inference", PAPERS.md) —
so the generator that exercises the autoscaler must reproduce exactly
that shape, deterministically. Everything here derives from an INJECTED
seed and a VIRTUAL clock:

- the request sequence (timestamps, rids, svm lines) is a pure function
  of :class:`TraceConfig` — two generators with the same config yield
  byte-identical traces, which is what makes the chaos drill's
  bit-identical-routing assertion and the bench's cross-run comparisons
  meaningful;
- the rate follows a diurnal sine (``base_rps``/``diurnal_amp``/
  ``diurnal_period_s``) with scriptable 10x spike windows on top;
- key draws follow a hot-set split calibrated from the live
  ``quality/slot_top_share`` gauges the PR 15 observatory collects
  (:func:`skew_from_gauges`) — the head ``hot_frac`` of the key space
  takes ``hot_share`` of the draws;
- chaos events (replica kill -9, shard-host kill, spike, calibration-
  poisoned base publish) are part of the trace, so a drill IS a trace
  and replays like one.

graftlint's replay_purity pass roots here: wall-clock reads
(``time.time``/``datetime.now``) and global RNG draws are contract
breaks, not style. :func:`replay` paces the virtual timeline against a
real monotonic clock (monotonic/sleep are pacing, not trace inputs —
the trace CONTENT never depends on them).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import (Callable, Dict, Iterator, List, Mapping, NamedTuple,
                    Optional, Sequence, Tuple)

import numpy as np

# Chaos kinds the drill harness understands. ``spike`` shapes the rate
# inside the generator; the other three are handed to the replay
# driver's handlers (the process-touching half lives with the caller).
CHAOS_KINDS = ("kill_replica", "kill_shard", "spike", "poison_delta")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One scripted fault on the virtual timeline."""

    at_s: float                 # virtual trace time the event fires
    kind: str                   # one of CHAOS_KINDS
    arg: str = ""               # replica id / shard endpoint / export path
    duration_s: float = 0.0     # spike window length (spike only)
    factor: float = 10.0        # spike rate multiplier (spike only)

    def __post_init__(self):
        if self.kind not in CHAOS_KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r} "
                             f"(want one of {CHAOS_KINDS})")


class TraceRequest(NamedTuple):
    """One replayed predict request: virtual timestamp, deterministic
    request id (the rid the quality join samples on), and raw svm
    lines."""

    t: float
    rid: str
    lines: Tuple[str, ...]


def skew_from_gauges(gauges: Mapping[str, float]) -> Optional[float]:
    """Hot-set share calibrated from a live metrics snapshot's gauge
    map: the mean ``quality/slot_top_share/<slot>`` (the head-1%%
    occurrence share ``core/quality.py`` measures on real ingest), or
    the cross-slot ``quality/skew_top_share`` when per-slot gauges are
    absent. None when the observatory has not reported yet."""
    shares = [float(v) for k, v in gauges.items()
              if k.startswith("quality/slot_top_share/")]
    if shares:
        return min(max(sum(shares) / len(shares), 0.0), 1.0)
    v = gauges.get("quality/skew_top_share")
    if v is not None:
        return min(max(float(v), 0.0), 1.0)
    return None


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Everything a trace is a function of. Frozen: the config IS the
    trace identity (equal configs replay equal traces)."""

    seed: int = 0
    duration_s: float = 10.0
    base_rps: float = 50.0
    # Diurnal shaping: rate(t) = base * (1 + amp * sin(2 pi t / period)),
    # floored at 5% of base so the trough never stalls the replay.
    diurnal_amp: float = 0.5
    diurnal_period_s: float = 10.0
    # Key-space skew: the head ``hot_frac`` of n_keys takes ``hot_share``
    # of the draws (the quality observatory's top-share statistic).
    n_keys: int = 1000
    hot_frac: float = 0.01
    hot_share: float = 0.5
    slots: Tuple[str, ...] = ("u", "i")
    rows_per_request: int = 2
    chaos: Tuple[ChaosEvent, ...] = ()

    @classmethod
    def from_quality(cls, gauges: Mapping[str, float],
                     **kw) -> "TraceConfig":
        """Config whose ``hot_share`` is the LIVE skew statistic
        (``skew_from_gauges``); explicit kwargs win, absent gauges keep
        the class default."""
        share = skew_from_gauges(gauges)
        if share is not None and "hot_share" not in kw:
            kw["hot_share"] = share
        return cls(**kw)


class TraceGenerator:
    """Deterministic request stream + chaos schedule for one config."""

    def __init__(self, cfg: TraceConfig):
        if cfg.n_keys < 2:
            raise ValueError("n_keys must be >= 2")
        self.cfg = cfg
        self._hot_n = max(1, int(cfg.n_keys * cfg.hot_frac))

    # -- rate shape --------------------------------------------------------

    def rate_at(self, t: float) -> float:
        """Diurnal sine with scripted spike windows folded in."""
        cfg = self.cfg
        rate = cfg.base_rps * (1.0 + cfg.diurnal_amp * math.sin(
            2.0 * math.pi * t / max(cfg.diurnal_period_s, 1e-9)))
        rate = max(rate, 0.05 * cfg.base_rps)
        for ev in cfg.chaos:
            if ev.kind == "spike" and ev.at_s <= t < ev.at_s + \
                    max(ev.duration_s, 0.0):
                rate *= max(ev.factor, 1.0)
        return rate

    # -- request stream ----------------------------------------------------

    def _draw_keys(self, rng: np.random.Generator, n: int) -> np.ndarray:
        """Hot-set split: each draw comes from the head ``hot_n`` keys
        with probability ``hot_share``, else uniform over the whole
        space. Keys are 1-based (0 is the svm label position)."""
        cfg = self.cfg
        hot = rng.random(n) < cfg.hot_share
        keys = rng.integers(1, cfg.n_keys + 1, n)
        keys[hot] = rng.integers(1, self._hot_n + 1, hot.sum())
        return keys

    def requests(self) -> Iterator[TraceRequest]:
        """The trace: virtual-clock-paced TraceRequests. Pure — a fresh
        iterator replays the identical sequence."""
        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed)
        t = 0.0
        seq = 0
        while True:
            t += 1.0 / self.rate_at(t)
            if t >= cfg.duration_s:
                return
            keys = self._draw_keys(
                rng, cfg.rows_per_request * len(cfg.slots))
            lines = []
            for r in range(cfg.rows_per_request):
                toks = ["0"]
                for j, slot in enumerate(cfg.slots):
                    toks.append(
                        f"{slot}:{keys[r * len(cfg.slots) + j]}")
                lines.append(" ".join(toks))
            yield TraceRequest(t, f"trace-{cfg.seed}-{seq}",
                               tuple(lines))
            seq += 1

    def events(self) -> List[ChaosEvent]:
        """The non-spike chaos schedule in firing order (spikes shape
        the rate inside ``requests`` and need no handler)."""
        return sorted((e for e in self.cfg.chaos if e.kind != "spike"),
                      key=lambda e: e.at_s)


def replay(gen: TraceGenerator,
           send: Callable[[TraceRequest], None], *,
           handlers: Optional[Mapping[
               str, Callable[[ChaosEvent], None]]] = None,
           speed: float = 1.0,
           clock: Callable[[], float] = time.monotonic,
           sleep: Callable[[float], None] = time.sleep) -> Dict[str, int]:
    """Pace the virtual timeline against a real monotonic clock:
    ``send(req)`` per request (the caller's RPC; its exceptions are the
    caller's to count), ``handlers[kind](event)`` once as virtual time
    passes each chaos event. ``speed`` > 1 compresses wall time (the
    CPU-small bench runs a 60 s trace in 6 s of wall) without changing
    the trace content. Returns replay counts."""
    handlers = dict(handlers or {})
    events = gen.events()
    next_ev = 0
    sent = 0
    fired = 0
    t0 = clock()
    for req in gen.requests():
        while next_ev < len(events) and events[next_ev].at_s <= req.t:
            ev = events[next_ev]
            next_ev += 1
            fn = handlers.get(ev.kind)
            if fn is not None:
                fn(ev)
                fired += 1
        lag = req.t / max(speed, 1e-9) - (clock() - t0)
        if lag > 0:
            sleep(lag)
        send(req)
        sent += 1
    for ev in events[next_ev:]:
        fn = handlers.get(ev.kind)
        if fn is not None:
            fn(ev)
            fired += 1
    return {"sent": sent, "events_fired": fired}
