"""FleetExecutor: actor-style microbatch dataflow runtime.

Role of the reference fleet executor (``distributed/fleet_executor/``):
``Carrier`` hosting ``Interceptor`` message loops (``carrier.h``,
``interceptor.h``) with compute/source/sink/amplifier interceptor types,
``TaskLoop`` worker threads, and a brpc ``MessageBus`` crossing nodes
(``message_bus.h``); ``FleetExecutor::Run`` (``fleet_executor.h:35``)
drives ``num_micro_batches`` scopes through the task DAG.

TPU-first framing: device-side pipeline parallelism compiles into the pjit
program (``parallel/pp.py``), so this runtime orchestrates *host-side*
stages — data load → pass build → train-dispatch → dump/eval chains,
cross-host control flow, and any CPU pre/post-processing DAG — where an
actor model with bounded queues is the right tool. Messages carry
(scope_id, payload); each interceptor processes scopes in order, with
backpressure from bounded inboxes.

In-process buses wire carriers directly; a TCP bus (length-prefixed
pickle, same framing as the PS service) crosses hosts.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.core import log

STOP = object()  # sentinel flowing through the DAG after the last scope


@dataclasses.dataclass
class TaskNode:
    """One node of the dataflow DAG (role of fleet_executor TaskNode):
    ``fn(payload) -> payload`` for compute nodes; source nodes call
    ``fn(scope_id)`` to produce payloads; sink nodes collect; amplifiers
    replicate each input ``factor`` times (role of the amplifier
    interceptor driving per-microbatch repeated stages)."""

    task_id: int
    role: str = "compute"               # source | compute | sink | amplifier
    fn: Optional[Callable[..., Any]] = None
    downstream: Tuple[int, ...] = ()
    upstream: Tuple[int, ...] = ()
    rank: int = 0                       # which carrier owns this node
    factor: int = 1                     # amplifier replication factor
    buffer_size: int = 8                # inbox bound (backpressure)


@dataclasses.dataclass
class _Msg:
    src: int
    dst: int
    scope: int          # microbatch / scope id
    payload: Any        # STOP or data


class MessageBus:
    """Routes messages to the carrier owning the destination task (role of
    message_bus.h). In-process: direct enqueue. Remote ranks: register a
    sender callable (e.g. built on transport.TcpTransport)."""

    def __init__(self):
        self._local: Dict[int, "Carrier"] = {}
        self._remote: Dict[int, Callable[[_Msg], None]] = {}

    def register_carrier(self, rank: int, carrier: "Carrier") -> None:
        self._local[rank] = carrier

    def register_remote(self, rank: int,
                        send: Callable[[_Msg], None]) -> None:
        self._remote[rank] = send

    def send(self, dst_rank: int, msg: _Msg) -> None:
        if dst_rank in self._local:
            self._local[dst_rank].deliver(msg)
        elif dst_rank in self._remote:
            self._remote[dst_rank](msg)
        else:
            raise KeyError(f"no route to rank {dst_rank}")


class Interceptor:
    """One actor: bounded inbox + handler thread (role of interceptor.h
    message loop; the dedicated thread is the TaskLoop)."""

    def __init__(self, node: TaskNode, carrier: "Carrier"):
        self.node = node
        self.carrier = carrier
        self.inbox: "queue.Queue[_Msg]" = queue.Queue(node.buffer_size)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        # scope_id -> {src: payload}: compute nodes join all upstreams
        # before firing (role of in_readys_ counting in compute_interceptor)
        self._pending: Dict[int, Dict[int, Any]] = {}
        self._stops_seen = 0
        self.error: Optional[BaseException] = None

    def start(self) -> None:
        self._thread.start()

    def join(self) -> None:
        self._thread.join()

    def _send_down(self, scope: int, payload: Any) -> None:
        for dst in self.node.downstream:
            self.carrier.route(_Msg(self.node.task_id, dst, scope, payload))

    def _loop(self) -> None:
        node = self.node
        n_up = max(len(node.upstream), 1)
        try:
            while True:
                msg = self.inbox.get()
                if msg.payload is STOP:
                    self._stops_seen += 1
                    # forward STOP once every upstream has finished
                    if self._stops_seen >= n_up:
                        self._send_down(msg.scope, STOP)
                        return
                    continue
                if node.role == "amplifier":
                    for i in range(node.factor):
                        out = node.fn(msg.payload) if node.fn else msg.payload
                        self._send_down(msg.scope * node.factor + i, out)
                    continue
                if n_up == 1:
                    joined = msg.payload
                else:
                    slot = self._pending.setdefault(msg.scope, {})
                    slot[msg.src] = msg.payload
                    if len(slot) < n_up:
                        continue
                    joined = [slot[s] for s in node.upstream]
                    del self._pending[msg.scope]
                # Source payloads were already produced by fn(scope) in
                # the feeder — applying fn again here would double-invoke
                # it (and exhaust generator-backed sources early).
                if node.role == "source":
                    out = joined
                else:
                    out = node.fn(joined) if node.fn else joined
                if node.role == "sink":
                    self.carrier.collect(msg.scope, out)
                else:
                    self._send_down(msg.scope, out)
        except BaseException as e:  # propagate to carrier, stop DAG
            self.error = e
            self.carrier.abort(e)


class Carrier:
    """Owns the interceptors of one rank's task nodes (role of carrier.h);
    ``run`` drives source nodes for num_micro_batches scopes and returns
    the sink's collected outputs in scope order."""

    def __init__(self, nodes: Sequence[TaskNode], rank: int = 0,
                 bus: Optional[MessageBus] = None):
        self.rank = rank
        self.bus = bus or MessageBus()
        self.bus.register_carrier(rank, self)
        self.nodes = {n.task_id: n for n in nodes}
        self._rank_of = {n.task_id: n.rank for n in nodes}
        self._results: Dict[int, Any] = {}
        self._results_lock = threading.Lock()
        self._done = threading.Event()
        self._aborted = threading.Event()
        self._error: Optional[BaseException] = None
        self._expected: Optional[int] = None
        self._consumed = False
        self.interceptors: Dict[int, Interceptor] = {}
        self._spawn_interceptors()

    def _spawn_interceptors(self) -> None:
        self.interceptors = {n.task_id: Interceptor(n, self)
                             for n in self.nodes.values()
                             if n.rank == self.rank}
        for it in self.interceptors.values():
            it.start()

    def reset(self) -> None:
        """Arm for another run: interceptor threads exit after forwarding
        STOP (or on abort), so each run needs a fresh set. Dead threads
        blocked on full inboxes from an aborted run are daemons and are
        simply abandoned. Non-driving carriers of a multi-rank DAG must
        reset between runs too."""
        self._aborted.set()   # release anything blocked in deliver()
        self._aborted = threading.Event()
        self._done.clear()
        with self._results_lock:
            self._error = None
        self._results.clear()
        self._consumed = False
        self._spawn_interceptors()

    # -- routing -----------------------------------------------------------

    def register_remote_node(self, task_id: int, rank: int) -> None:
        """Declare a node living on another rank (its carrier must be
        reachable through the shared bus)."""
        self._rank_of[task_id] = rank

    def route(self, msg: _Msg) -> None:
        self.bus.send(self._rank_of[msg.dst], msg)

    def deliver(self, msg: _Msg) -> None:
        # Bounded put that bails out on abort: without the check, a sender
        # blocked on a dead interceptor's full inbox would hang forever.
        inbox = self.interceptors[msg.dst].inbox
        while not self._aborted.is_set():
            try:
                inbox.put(msg, timeout=0.05)
                return
            except queue.Full:
                continue

    # -- sink/collection ---------------------------------------------------

    def collect(self, scope: int, payload: Any) -> None:
        if payload is STOP:
            self._done.set()
            return
        with self._results_lock:
            self._results[scope] = payload
            if self._expected is not None \
                    and len(self._results) >= self._expected:
                self._done.set()

    def abort(self, err: BaseException) -> None:
        # Interceptor threads race each other (and run()'s reader) here;
        # first error wins, publication ordered by the lock + done event.
        with self._results_lock:
            if self._error is None:
                self._error = err
        self._aborted.set()
        self._done.set()

    # -- driving -----------------------------------------------------------

    def run(self, num_micro_batches: int,
            feeds: Optional[Sequence[Any]] = None,
            timeout: float = 300.0) -> List[Any]:
        """Emit one scope per microbatch from every source node, wait for
        the sink to drain (role of FleetExecutor::Run)."""
        if self._consumed:
            self.reset()
        self._results.clear()
        self._done.clear()
        with self._results_lock:
            self._error = None
        self._expected = self._count_sink_scopes(num_micro_batches)
        sources = [n for n in self.nodes.values() if n.role == "source"
                   and n.rank == self.rank]
        if not sources:
            raise ValueError("carrier has no local source node")

        def feed(src: TaskNode):
            it = self.interceptors[src.task_id]

            def put(msg: _Msg) -> bool:
                # Abort-aware bounded put: after an interceptor error the
                # queues stop draining, and a plain blocking put would
                # wedge this feeder (and run()'s join) forever. Bail only
                # on ABORT — _done also fires on the expected-count fast
                # path while STOP still must be delivered so the stage
                # threads can exit (run() joins them).
                while not self._aborted.is_set():
                    try:
                        it.inbox.put(msg, timeout=0.05)
                        return True
                    except queue.Full:
                        continue
                return False

            for scope in range(num_micro_batches):
                payload = feeds[scope] if feeds is not None \
                    else (src.fn(scope) if src.fn else scope)
                if not put(_Msg(-1, src.task_id, scope, payload)):
                    return
            put(_Msg(-1, src.task_id, num_micro_batches, STOP))

        feeders = [threading.Thread(target=feed, args=(s,), daemon=True)
                   for s in sources]
        [t.start() for t in feeders]
        try:
            if not self._done.wait(timeout):
                # Name the missing participants: which sink scopes never
                # arrived and which stage threads are still live — a
                # wedged stage debugs from this line alone.
                with self._results_lock:
                    got = sorted(self._results)
                missing = ([s for s in range(self._expected or 0)
                            if s not in set(got)]
                           if self._expected is not None else [])
                alive = [tid for tid, it in self.interceptors.items()
                         if it._thread.is_alive()]
                raise TimeoutError(
                    f"fleet executor did not drain within {timeout}s: "
                    f"{len(got)}/{self._expected} sink scopes arrived "
                    f"(missing scopes {missing[:8]}"
                    f"{'...' if len(missing) > 8 else ''}); "
                    f"interceptors still running: {alive}")
        finally:
            self._consumed = True
        [t.join() for t in feeders]
        with self._results_lock:
            err = self._error
        if err is not None:
            raise RuntimeError("interceptor failed") from err
        # Drain the STOP cascade before returning: done fires on the
        # expected result count, but STOP may still be propagating — a
        # back-to-back run() would reset() to fresh interceptors and the
        # straggler STOP would terminate a NEW stage before it works.
        for it in self.interceptors.values():
            it.join()
        return [self._results[k] for k in sorted(self._results)]

    def _count_sink_scopes(self, num_micro_batches: int) -> int:
        """Scopes the sink will see = microbatches × product of amplifier
        factors along any path (assumed uniform)."""
        n = num_micro_batches
        for node in self.nodes.values():
            if node.role == "amplifier":
                n *= node.factor
        return n

    def shutdown(self) -> None:
        self._done.set()


def linear_pipeline(fns: Sequence[Callable[[Any], Any]],
                    buffer_size: int = 8) -> List[TaskNode]:
    """Helper: source → fn1 → fn2 → ... → sink DAG, the common host
    pipeline shape (load → parse → build → consume)."""
    nodes = [TaskNode(task_id=0, role="source", downstream=(1,),
                      buffer_size=buffer_size)]
    for i, fn in enumerate(fns, start=1):
        nodes.append(TaskNode(task_id=i, role="compute", fn=fn,
                              upstream=(i - 1,), downstream=(i + 1,),
                              buffer_size=buffer_size))
    last = len(fns) + 1
    nodes.append(TaskNode(task_id=last, role="sink", upstream=(last - 1,),
                          buffer_size=buffer_size))
    return nodes
