"""CPU parameter-server service: sharded sparse/dense tables over TCP RPC.

Role of the pscore brpc PS runtime (``distributed/ps/service/
brpc_ps_server.h:40``, ``brpc_ps_client.h``) with its tables
(``MemorySparseTable``, ``MemoryDenseTable``, ``ps/table/table.h:67``) and
sparse SGD rules (``sparse_sgd_rule.h``): workers pull/push sparse values
by feasign key and pull/push dense params by name; the server applies the
sparse optimizer to pushed gradients.

TPU-first framing: the *training-time* embedding path never touches this
service — per-pass tables live in TPU HBM (``embedding/``). The PS is the
host control/persistence plane: the between-pass backing store for
multi-host CTR jobs (pass build pulls, EndPass pushes back — role of
``BuildPull``/``EndPass``, ``ps_gpu_wrapper.cc:362,983``), plus dense
param distribution for async CPU setups. Protocol: versioned typed
frames over TCP (``distributed/wire.py`` — struct header + numpy
buffers; no pickle on the socket; stdlib stand-in for brpc). Trusted
cluster network only — frames are validated, not authenticated (same
stance as the reference's brpc fabric).

Key sharding is client-side ``key % num_servers`` (exactly the reference's
``key % num_devices`` shard rule, ``heter_comm.h:332``), so any number of
clients agree on placement without a directory service.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.core import monitor
from paddlebox_tpu.distributed import rpc, wire
from paddlebox_tpu.distributed.transport import _recv_exact
from paddlebox_tpu.embedding.store import FeatureStore
from paddlebox_tpu.embedding.table import TableConfig


def _send_msg(sock: socket.socket, obj) -> None:
    sock.sendall(wire.pack_frame(obj))


def _recv_msg(sock: socket.socket):
    ln = wire.read_frame_header(_recv_exact(sock, wire.HEADER.size))
    return wire.loads(_recv_exact(sock, ln))


class DenseTable:
    """Named dense parameter block with server-side SGD apply (role of
    MemoryDenseTable: workers push summed grads, server applies the rule)."""

    def __init__(self, value: np.ndarray, learning_rate: float = 1.0):
        self.value = np.asarray(value, np.float32).copy()
        self.lr = float(learning_rate)
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.value.copy()

    def push(self, grad: np.ndarray) -> None:
        with self._lock:
            self.value -= self.lr * np.asarray(grad, np.float32)

    def set(self, value: np.ndarray) -> None:
        with self._lock:
            self.value = np.asarray(value, np.float32).copy()


class PSServer(rpc.FramedRPCServer):
    """One PS shard: serves the keys with ``key % num_servers == index``.

    Sparse tables are :class:`FeatureStore` instances (sorted-key columnar
    host store); pushes apply the table's sparse optimizer server-side —
    the CPU twin of the in-kernel update the device path fuses into
    push_sparse (``optimizer.cuh.h:31``).

    ``store_factory(cfg, shard_index)`` swaps the backing store per shard
    — pass a :class:`~paddlebox_tpu.embedding.ssd_tier.TieredFeatureStore`
    factory to bound each remote shard's RAM with disk overflow (the
    remote twin of the reference's SSD table under the PS service,
    ``box_wrapper.h:635`` LoadSSD2Mem staging on a served shard).
    """

    def __init__(self, endpoint: str, index: int, num_servers: int,
                 tables: Dict[str, TableConfig],
                 dense: Optional[Dict[str, np.ndarray]] = None,
                 dense_lr: float = 1.0, store_factory=None):
        self.index = index
        self.num_servers = num_servers
        if store_factory is None:
            def store_factory(cfg, idx):
                return FeatureStore(cfg, seed=idx)
        self.tables: Dict[str, FeatureStore] = {
            name: store_factory(cfg, index) for name, cfg in
            tables.items()}
        self._opts = {name: self.tables[name].opt for name in tables}
        # Per-table lock serializing read-modify-write sequences: the
        # FeatureStore lock only covers single calls, but pull→update→push
        # from two concurrent client connections racing on the same key
        # would lose one side's gradient without this.
        self._table_locks = {name: threading.Lock() for name in tables}
        self.dense_lr = float(dense_lr)
        self.dense: Dict[str, DenseTable] = {
            name: DenseTable(v, dense_lr) for name, v in (dense or {}).items()}
        # Service identity BEFORE the base starts accepting (handler
        # threads read it for log attribution).
        self.service_name = f"ps[{index}]"
        rpc.FramedRPCServer.__init__(self, endpoint, backlog=64)

    def _after_reply(self) -> bool:
        if not self._running:
            # stop RPC: response sent, now actually close the listener
            # (stop accepting new work; other live connections drain
            # until their clients close).
            self.stop()
            return True
        return False

    # -- sparse ------------------------------------------------------------

    def _check_owned(self, keys: np.ndarray) -> None:
        if keys.size and not np.all(keys % self.num_servers == self.index):
            raise ValueError(f"keys not owned by server {self.index}")

    def handle_pull_sparse(self, req) -> Dict[str, np.ndarray]:
        """Values for requested keys in request order (duplicates allowed).
        Unseen keys get initialized rows (accessor init semantics)."""
        store = self.tables[req["table"]]
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        uniq, inv = np.unique(keys, return_inverse=True)
        with self._table_locks[req["table"]]:
            present = store.contains(uniq)
            rows = store.pull_for_pass(uniq)
            # Persist ONLY genuinely-new keys so repeated pulls are
            # stable; re-pushing present keys would mark them dirty and
            # land every read-only pull in the next save_delta.
            if not present.all():
                new = ~present
                store.push_from_pass(
                    uniq[new], {f: v[new] for f, v in rows.items()})
        monitor.add("ps/pull_keys", int(keys.size))
        return {"emb": rows["emb"][inv], "w": rows["w"][inv]}

    def handle_push_sparse(self, req) -> int:
        """Merge duplicate-key grads (segment sum — role of
        dynamic_merge_grad, heter_comm.h:69) then apply the sparse
        optimizer and show/click accumulation."""
        store = self.tables[req["table"]]
        opt = self._opts[req["table"]]
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        uniq, inv = np.unique(keys, return_inverse=True)
        n = uniq.size
        d = store.config.dim
        emb_g = np.zeros((n, d), np.float32)
        np.add.at(emb_g, inv, np.asarray(req["emb_grad"], np.float32))
        w_g = np.zeros((n,), np.float32)
        np.add.at(w_g, inv, np.asarray(req["w_grad"], np.float32))
        with self._table_locks[req["table"]]:
            rows = store.pull_for_pass(uniq)
            emb, emb_st = opt.update_vector(rows["emb"], rows["emb_state"],
                                            emb_g)
            w, w_st = opt.update_scalar(rows["w"], rows["w_state"], w_g)
            rows["emb"] = np.asarray(emb, np.float32)
            rows["emb_state"] = np.asarray(emb_st, np.float32)
            rows["w"] = np.asarray(w, np.float32)
            rows["w_state"] = np.asarray(w_st, np.float32)
            if "show" in req:
                np.add.at(rows["show"], inv,
                          np.asarray(req["show"], np.float32))
            if "click" in req:
                np.add.at(rows["click"], inv,
                          np.asarray(req["click"], np.float32))
            store.push_from_pass(uniq, rows)
        monitor.add("ps/push_keys", int(keys.size))
        return n

    def handle_pull_pass(self, req):
        """Bulk fetch for pass build (role of BuildPull): full value rows
        including optimizer state, for sorted unique keys."""
        store = self.tables[req["table"]]
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        with self._table_locks[req["table"]]:
            return store.pull_for_pass(keys)

    def handle_push_pass(self, req) -> int:
        """Bulk write-back at EndPass (ps_gpu_wrapper.cc:983)."""
        store = self.tables[req["table"]]
        keys = np.asarray(req["keys"], np.uint64)
        self._check_owned(keys)
        # Table lock: a concurrent push_sparse RMW reading stale rows must
        # not overwrite this bulk write-back.
        with self._table_locks[req["table"]]:
            store.push_from_pass(keys, req["values"])
        return int(keys.size)

    # -- dense -------------------------------------------------------------

    def handle_pull_dense(self, req) -> np.ndarray:
        return self.dense[req["name"]].pull()

    def handle_push_dense(self, req) -> bool:
        self.dense[req["name"]].push(req["grad"])
        return True

    def handle_set_dense(self, req) -> bool:
        if req["name"] in self.dense:
            table = self.dense[req["name"]]
            table.set(req["value"])
            if "lr" in req:  # omitting lr preserves the configured rate
                table.lr = float(req["lr"])
        else:
            self.dense[req["name"]] = DenseTable(
                req["value"], float(req.get("lr", self.dense_lr)))
        return True

    # -- lifecycle ---------------------------------------------------------

    def handle_save(self, req) -> bool:
        for store in self.tables.values():
            if req.get("mode", "base") == "base":
                store.save_base(self._shard_dir(req["path"]))
            else:
                store.save_delta(self._shard_dir(req["path"]))
        return True

    def handle_load(self, req) -> bool:
        for store in self.tables.values():
            store.load(self._shard_dir(req["path"]), req.get("mode", "base"))
        return True

    def _shard_dir(self, path: str) -> str:
        import os
        d = os.path.join(path, f"part-{self.index:05d}")
        os.makedirs(d, exist_ok=True)
        return d

    def handle_shrink(self, req) -> int:
        # Under the same per-table locks as pull/push: shrink evicting a
        # key between a pull's contains() check and its pull_for_pass()
        # would hand out an ephemeral (never-persisted) init row.
        total = 0
        for name, store in self.tables.items():
            with self._table_locks[name]:
                total += store.shrink(min_show=req.get("min_show", 0.0))
        return total

    def handle_stats(self, req) -> Dict[str, int]:
        return {name: store.num_features
                for name, store in self.tables.items()}

    def handle_stop(self, req) -> bool:
        self._running = False
        return True



class PSClient:
    """Client-side sharding + fan-out (role of BrpcPsClient).

    One persistent connection per server; sparse requests are split by
    ``key % num_servers``, issued concurrently, and reassembled in request
    order.
    """

    def __init__(self, endpoints: Sequence[str]):
        self.endpoints = list(endpoints)
        self.num_servers = len(self.endpoints)
        self._socks: List[Optional[socket.socket]] = \
            [None] * self.num_servers
        self._locks = [threading.Lock() for _ in range(self.num_servers)]

    def _sock(self, i: int) -> socket.socket:
        if self._socks[i] is None:
            host, port = self.endpoints[i].rsplit(":", 1)
            self._socks[i] = socket.create_connection((host, int(port)),
                                                      timeout=60)
        return self._socks[i]

    def _call(self, server: int, method: str, **kw):
        with self._locks[server]:
            sock = self._sock(server)
            try:
                _send_msg(sock, {"method": method, **kw})
                resp = _recv_msg(sock)
            except (OSError, ConnectionError, wire.WireError):
                # A timed-out / half-read / desynced stream cannot be
                # reused — drop it so the next call reconnects cleanly.
                try:
                    sock.close()
                except OSError:
                    pass
                self._socks[server] = None
                raise
        if not resp["ok"]:
            raise RuntimeError(f"ps[{server}].{method}: {resp['error']}")
        return resp["result"]

    def _fanout(self, method: str, **kw) -> List:
        outs: List = [None] * self.num_servers
        errs: List = []

        def run(i):
            try:
                outs[i] = self._call(i, method, **kw)
            except Exception as e:
                errs.append(e)

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(self.num_servers)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        if errs:
            raise errs[0]
        return outs

    # -- sparse ------------------------------------------------------------

    def _split(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        owner = (keys % np.uint64(self.num_servers)).astype(np.int64)
        order = np.argsort(owner, kind="stable")
        return owner, order

    def pull_sparse(self, table: str, keys: np.ndarray
                    ) -> Dict[str, np.ndarray]:
        keys = np.asarray(keys, np.uint64)
        owner, order = self._split(keys)
        outs_emb = None
        out_w = np.empty((keys.size,), np.float32)
        results: Dict[int, Tuple[np.ndarray, Dict[str, np.ndarray]]] = {}
        errs: List[BaseException] = []
        threads = []
        for s in range(self.num_servers):
            idx = order[owner[order] == s]
            if idx.size == 0:
                continue

            def run(s=s, idx=idx):
                try:
                    results[s] = (idx, self._call(
                        s, "pull_sparse", table=table, keys=keys[idx]))
                except BaseException as e:
                    errs.append(e)
            threads.append(threading.Thread(target=run))
        [t.start() for t in threads]
        [t.join() for t in threads]
        if errs:
            # A lost shard must fail loudly — returning np.empty garbage
            # for its rows would silently corrupt training.
            raise errs[0]
        for s, (idx, res) in results.items():
            if outs_emb is None:
                outs_emb = np.empty((keys.size, res["emb"].shape[1]),
                                    np.float32)
            outs_emb[idx] = res["emb"]
            out_w[idx] = res["w"]
        if outs_emb is None:
            outs_emb = np.empty((0, 0), np.float32)
        return {"emb": outs_emb, "w": out_w}

    def push_sparse(self, table: str, keys: np.ndarray,
                    emb_grad: np.ndarray, w_grad: np.ndarray,
                    show: Optional[np.ndarray] = None,
                    click: Optional[np.ndarray] = None) -> None:
        keys = np.asarray(keys, np.uint64)
        owner, order = self._split(keys)
        threads = []
        errs: List[BaseException] = []
        for s in range(self.num_servers):
            idx = order[owner[order] == s]
            if idx.size == 0:
                continue
            kw = dict(table=table, keys=keys[idx], emb_grad=emb_grad[idx],
                      w_grad=w_grad[idx])
            if show is not None:
                kw["show"] = show[idx]
            if click is not None:
                kw["click"] = click[idx]

            def run(s=s, kw=kw):
                try:
                    self._call(s, "push_sparse", **kw)
                except BaseException as e:
                    errs.append(e)
            threads.append(threading.Thread(target=run))
        [t.start() for t in threads]
        [t.join() for t in threads]
        if errs:
            # Dropped gradients must not be silent.
            raise errs[0]

    def pull_pass(self, table: str, keys_sorted: np.ndarray
                  ) -> Dict[str, np.ndarray]:
        """Bulk pass-build fetch, reassembled to the sorted key order."""
        keys = np.asarray(keys_sorted, np.uint64)
        if keys.size == 0:
            # Preserve the FeatureStore contract: an empty pass returns
            # fully-shaped (0, ...) field arrays, not {} — ask one server
            # for an empty pull to get the schema.
            return self._call(0, "pull_pass", table=table, keys=keys)
        owner, order = self._split(keys)
        results: Dict[int, Tuple[np.ndarray, Dict[str, np.ndarray]]] = {}
        errs: List[BaseException] = []
        threads = []
        for s in range(self.num_servers):
            idx = order[owner[order] == s]
            if idx.size == 0:
                continue

            def run(s=s, idx=idx):
                try:
                    results[s] = (idx, self._call(s, "pull_pass",
                                                  table=table,
                                                  keys=keys[idx]))
                except BaseException as e:
                    errs.append(e)
            threads.append(threading.Thread(target=run))
        [t.start() for t in threads]
        [t.join() for t in threads]
        if errs:
            raise errs[0]
        fields: Dict[str, np.ndarray] = {}
        for s, (idx, res) in results.items():
            for f, arr in res.items():
                if f not in fields:
                    fields[f] = np.empty((keys.size,) + arr.shape[1:],
                                         arr.dtype)
                fields[f][idx] = arr
        return fields

    def push_pass(self, table: str, keys_sorted: np.ndarray,
                  values: Dict[str, np.ndarray]) -> None:
        keys = np.asarray(keys_sorted, np.uint64)
        owner, order = self._split(keys)
        errs: List[BaseException] = []
        threads = []
        for s in range(self.num_servers):
            idx = order[owner[order] == s]
            if idx.size == 0:
                continue

            def run(s=s, idx=idx):
                try:
                    self._call(s, "push_pass", table=table, keys=keys[idx],
                               values={f: a[idx] for f, a in values.items()})
                except BaseException as e:
                    errs.append(e)
            threads.append(threading.Thread(target=run))
        [t.start() for t in threads]
        [t.join() for t in threads]
        if errs:
            raise errs[0]

    # -- dense / lifecycle -------------------------------------------------

    def pull_dense(self, name: str, server: int = 0) -> np.ndarray:
        return self._call(server, "pull_dense", name=name)

    def push_dense(self, name: str, grad: np.ndarray,
                   server: int = 0) -> None:
        self._call(server, "push_dense", name=name, grad=grad)

    def set_dense(self, name: str, value: np.ndarray,
                  server: int = 0, lr: Optional[float] = None) -> None:
        req = dict(name=name, value=value)
        if lr is not None:
            req["lr"] = float(lr)
        self._call(server, "set_dense", **req)

    def save(self, path: str, mode: str = "base") -> None:
        self._fanout("save", path=path, mode=mode)

    def load(self, path: str, mode: str = "base") -> None:
        self._fanout("load", path=path, mode=mode)

    def shrink(self, min_show: float = 0.0) -> int:
        return int(np.sum(self._fanout("shrink", min_show=min_show)))

    def stats(self) -> List[Dict[str, int]]:
        return self._fanout("stats")

    def stop_servers(self) -> None:
        try:
            self._fanout("stop")
        except Exception:
            pass

    def close(self) -> None:
        for s in self._socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass


class PSBackedStore:
    """FeatureStore-shaped adapter over a remote PS cluster — plugs into
    :class:`~paddlebox_tpu.embedding.pass_engine.PassEngine` as its
    backing store, making the pass build pull values from the PS servers
    and EndPass write them back (exactly the reference's BuildPull-from-
    CPU-PS flow, ps_gpu_wrapper.cc:362, and EndPass write-back :983 —
    but with the hot training tier in TPU HBM)."""

    #: One backing cluster shared by all ranks: day-end shrink must run
    #: exactly once (rank 0), unlike per-rank replica stores.
    shared = True

    def __init__(self, client: PSClient, table: str):
        self.client = client
        self.table = table

    def pull_for_pass(self, pass_keys_sorted: np.ndarray
                      ) -> Dict[str, np.ndarray]:
        return self.client.pull_pass(self.table, pass_keys_sorted)

    def push_from_pass(self, pass_keys_sorted: np.ndarray,
                       values: Dict[str, np.ndarray]) -> None:
        self.client.push_pass(self.table, pass_keys_sorted, values)

    @property
    def num_features(self) -> int:
        return int(sum(s.get(self.table, 0) for s in self.client.stats()))

    # Checkpoint/maintenance surface, delegated to the PS cluster so the
    # documented trainer flow (engine.store.save_base(path)) works the
    # same against a remote tier — each server writes part-NNNNN shards.
    def save_base(self, path: str) -> None:
        self.client.save(path, "base")

    def save_delta(self, path: str) -> None:
        self.client.save(path, "delta")

    def load(self, path: str, kind: str = "base") -> None:
        self.client.load(path, kind)

    def shrink(self, *, min_show: float = 0.0) -> int:
        return self.client.shrink(min_show=min_show)


def start_local_cluster(num_servers: int, tables: Dict[str, TableConfig],
                        dense: Optional[Dict[str, np.ndarray]] = None,
                        store_factory=None
                        ) -> Tuple[List[PSServer], PSClient]:
    """Spin up an in-process PS cluster on localhost ephemeral ports (role
    of the reference's localhost fake-cluster test mechanism,
    test_dist_base.py:1041)."""
    servers = [PSServer("127.0.0.1:0", i, num_servers, tables, dense,
                        store_factory=store_factory)
               for i in range(num_servers)]
    client = PSClient([s.endpoint for s in servers])
    return servers, client
