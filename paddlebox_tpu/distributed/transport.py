"""Host-side control-plane transports.

Role of the reference's host RPC substrates where the payload is bulk
host data, not device tensors: brpc PS traffic (``brpc_ps_client.h``),
boxps MPI dataset shuffle (``data_set.cc:2436``), and the Gloo
``HdfsStore`` file rendezvous (``gloo_wrapper.h:53``).

Two implementations:
- :class:`FileStore` — shared-filesystem KV store with barrier, the
  HdfsStore equivalent (works on any NFS/GCS-fuse mount; used for
  bootstrap-less rank sync in tests and single-host multiprocess).
- :class:`TcpTransport` — length-prefixed TCP mesh for exchange()
  (all-to-all of host byte buffers, the dataset global_shuffle transport)
  built only on the standard library.
"""

from __future__ import annotations

import os
import queue
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.core import faults, log


class FileStore:
    """Shared-directory KV + barrier (role of gloo HdfsStore)."""

    #: Chunk-manifest marker (a value starting with these bytes is
    #: force-chunked so a literal payload can never be misread as one).
    _CHUNK_MAGIC = b"__PBX_CHUNKS1__:"

    def __init__(self, root: str, rank: int, world: int):
        self.root = root
        self.rank = rank
        self.world = world
        # Per-name generation counters: reusing a barrier/all_gather name
        # must not match a previous round's marker files.
        self._gens: Dict[str, int] = {}
        os.makedirs(root, exist_ok=True)

    def _gen(self, name: str) -> int:
        g = self._gens.get(name, 0)
        self._gens[name] = g + 1
        return g

    def _write_atomic(self, key: str, value: bytes) -> None:
        tmp = os.path.join(self.root, f".{key}.{self.rank}.tmp")
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, os.path.join(self.root, key))

    def set(self, key: str, value: bytes) -> None:
        """Publish ``value`` under ``key``. Values above
        ``FLAGS_filestore_chunk_bytes`` split into numbered chunk files
        (each its own atomic rename) behind a manifest written LAST —
        a reader that sees the manifest is guaranteed every chunk is
        already visible, so a multi-MB rank-table or gathered cluster
        snapshot can never exceed one frame/rename window or present a
        torn read."""
        faults.faultpoint("transport/set")
        from paddlebox_tpu.core import flags as _flags
        cap = int(_flags.flag("filestore_chunk_bytes"))
        if (cap <= 0 or len(value) <= cap) \
                and not value.startswith(self._CHUNK_MAGIC):
            self._write_atomic(key, value)
            return
        cap = max(cap, 1)
        n = max(1, -(-len(value) // cap))
        for i in range(n):
            self._write_atomic(f"{key}.c{i}", value[i * cap:(i + 1) * cap])
        self._write_atomic(key, self._CHUNK_MAGIC
                           + f"{n}:{len(value)}".encode())

    def get(self, key: str, timeout: float = 60.0) -> bytes:
        faults.faultpoint("transport/get")
        path = os.path.join(self.root, key)
        deadline = time.time() + timeout
        # Exponential poll backoff 10ms -> ~250ms: a long rendezvous wait
        # (slow rank, cold start) must not spin the shared filesystem
        # with 100 stat()s/s per rank per key.
        poll = 0.01
        while not os.path.exists(path):
            if time.time() > deadline:
                raise TimeoutError(
                    f"FileStore.get({key!r}) timed out after {timeout}s "
                    f"(rank {self.rank}/{self.world}, root {self.root})")
            time.sleep(poll)
            poll = min(poll * 2.0, 0.25)
        with open(path, "rb") as f:
            data = f.read()
        if not data.startswith(self._CHUNK_MAGIC):
            return data
        # Chunked value: manifest was published AFTER its chunks, so
        # every chunk file already exists — missing/short means
        # corruption, not a race; fail loudly.
        try:
            n_s, total_s = data[len(self._CHUNK_MAGIC):].split(b":")
            n, total = int(n_s), int(total_s)
        except ValueError:
            raise OSError(f"FileStore.get({key!r}): malformed chunk "
                          f"manifest {data[:64]!r}") from None
        parts = []
        for i in range(n):
            cpath = os.path.join(self.root, f"{key}.c{i}")
            with open(cpath, "rb") as f:
                parts.append(f.read())
        out = b"".join(parts)
        if len(out) != total:
            raise OSError(
                f"FileStore.get({key!r}): chunked value reassembled to "
                f"{len(out)} bytes, manifest says {total}")
        return out

    def _gather_from_all(self, prefix: str, what: str, name: str,
                         timeout: float) -> List[bytes]:
        """Collect one marker per rank, converting a per-key timeout into
        an error naming the MISSING RANKS and the waited key — 'rank 3
        never arrived' debugs a wedged barrier; 'get(...) timed out'
        does not."""
        deadline = time.time() + timeout
        out: List[Optional[bytes]] = [None] * self.world
        for r in range(self.world):
            left = deadline - time.time()
            try:
                out[r] = self.get(f"{prefix}.{r}", max(left, 0.0))
            except TimeoutError:
                missing = [i for i in range(self.world)
                           if out[i] is None and not os.path.exists(
                               os.path.join(self.root, f"{prefix}.{i}"))]
                raise TimeoutError(
                    f"FileStore.{what}({name!r}) timed out after "
                    f"{timeout}s on rank {self.rank}: ranks {missing} "
                    f"never arrived (waited key {prefix}.{r})") from None
        return out  # type: ignore[return-value]

    def _cleanup_old_gen(self, prefix: str, g: int) -> None:
        """Unlink our own generation g-2 marker: by the time any rank runs
        generation g it has completed g-1, which required every rank to
        have entered g-1 — i.e. to have finished g-2. So no reader can
        still need a g-2 file, and the directory stays bounded."""
        if g >= 2:
            try:
                os.unlink(os.path.join(self.root,
                                       f"{prefix}.{g - 2}.{self.rank}"))
            except FileNotFoundError:
                pass

    def barrier(self, name: str, timeout: float = 60.0) -> None:
        """All ranks arrive (role of _barrier_worker). Reusable: each call
        under the same name is a fresh generation."""
        g = self._gen(f"barrier.{name}")
        self._cleanup_old_gen(f"barrier.{name}", g)
        self.set(f"barrier.{name}.{g}.{self.rank}", b"1")
        self._gather_from_all(f"barrier.{name}.{g}", "barrier", name,
                              timeout)

    def all_gather(self, name: str, value: bytes,
                   timeout: float = 60.0) -> List[bytes]:
        g = self._gen(f"ag.{name}")
        self._cleanup_old_gen(f"ag.{name}", g)
        self.set(f"ag.{name}.{g}.{self.rank}", value)
        return self._gather_from_all(f"ag.{name}.{g}", "all_gather", name,
                                     timeout)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf.extend(part)
    return bytes(buf)


def _recv_into_exact(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket with ``recv_into`` — the payload
    lands in the caller's preallocated buffer with no intermediate
    chunk copies (the RPC plane's zero-copy receive discipline)."""
    got = 0
    n = len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if r == 0:
            raise ConnectionError("peer closed")
        got += r


class TcpTransport:
    """Length-prefixed TCP mesh for host-buffer exchange.

    Each rank listens on ``ports[rank]``; ``exchange(buffers)`` sends
    buffers[r] to rank r and returns what every rank sent to us —
    exactly the contract of the boxps PaddleShuffler used by
    ``PadBoxSlotDataset::ShuffleData``/``ReceiveSuffleData``.
    """

    HDR = struct.Struct("<iqq")  # (src_rank, round, payload_len)

    def __init__(self, rank: int, endpoints: Sequence[str]):
        self.rank = rank
        self.endpoints = list(endpoints)
        self.world = len(endpoints)
        host, port = self.endpoints[rank].rsplit(":", 1)
        self._server = socket.create_server((host, int(port)), backlog=16,
                                            reuse_port=False)
        self._recv_lock = threading.Lock()
        # Messages keyed by (src, round): concurrent connections from the
        # same peer across back-to-back exchange() rounds may deliver out
        # of order, so the round tag — not arrival order — pairs them up.
        self._inbox: Dict[Tuple[int, int], bytes] = {}
        self._round = 0
        # Rounds at or below this are finished/abandoned; late arrivals for
        # them are discarded instead of pinning payload bytes forever.
        self._retired_round = -1
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._running = True
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while True:
                    hdr = _recv_exact(conn, self.HDR.size)
                    src, rnd, ln = self.HDR.unpack(hdr)
                    payload = _recv_exact(conn, ln) if ln else b""
                    with self._recv_lock:
                        if rnd > self._retired_round:
                            self._inbox[(src, rnd)] = payload
        except (ConnectionError, OSError):
            return

    def _send(self, dst: int, rnd: int, payload: bytes) -> None:
        faults.faultpoint("transport/send")
        host, port = self.endpoints[dst].rsplit(":", 1)
        deadline = time.time() + 30
        while True:
            try:
                with socket.create_connection((host, int(port)),
                                              timeout=10) as s:
                    s.sendall(self.HDR.pack(self.rank, rnd, len(payload)))
                    s.sendall(payload)
                return
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)

    def exchange(self, buffers: Sequence[bytes],
                 timeout: float = 120.0) -> List[bytes]:
        """All-to-all: send buffers[r] to rank r; return one buffer per
        peer (self's slot short-circuits locally)."""
        if len(buffers) != self.world:
            raise ValueError(f"{len(buffers)} buffers != world {self.world}")
        faults.faultpoint("transport/recv")
        rnd = self._round
        self._round += 1
        out: List[Optional[bytes]] = [None] * self.world
        out[self.rank] = buffers[self.rank]
        # Flow control (role of FLAGS_padbox_max_shuffle_wait_count in
        # the reference's shuffle): at most `window` concurrent sends per
        # rank — an unbounded fan-out at large world sizes floods the
        # receiver sockets and this host's thread table, so the window
        # bounds BOTH: `window` worker threads drain a destination
        # queue (not one gated thread per destination).
        from paddlebox_tpu.core import flags as _flags
        window = max(1, int(_flags.flag("padbox_max_shuffle_wait_count")))
        dst_q: "queue.Queue[int]" = queue.Queue()
        for dst in range(self.world):
            if dst != self.rank:
                dst_q.put(dst)
        send_errors: List[BaseException] = []

        def _drain() -> None:
            while True:
                try:
                    dst = dst_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    self._send(dst, rnd, buffers[dst])
                except BaseException as e:  # surfaced after the joins
                    send_errors.append(e)

        senders = []
        for _ in range(min(window, self.world - 1)):
            t = threading.Thread(target=_drain, daemon=True)
            t.start()
            senders.append(t)
        want = [(src, rnd) for src in range(self.world) if src != self.rank]
        deadline = time.time() + timeout
        try:
            while True:
                with self._recv_lock:
                    if all(k in self._inbox for k in want):
                        for src, _ in want:
                            out[src] = self._inbox.pop((src, rnd))
                        break
                if time.time() > deadline:
                    # Surface the root cause: a refused send explains a
                    # missing buffer far better than a bare timeout.
                    raise TimeoutError(
                        "exchange timed out"
                        + (f" (send errors: {send_errors!r})"
                           if send_errors else ""))
                time.sleep(0.002)
        finally:
            # Success or timeout, this round is over: drop any partial or
            # late payloads so they can't leak or mispair.
            with self._recv_lock:
                self._retired_round = rnd
                for k in [k for k in self._inbox if k[1] <= rnd]:
                    del self._inbox[k]
        for t in senders:
            t.join()
        if send_errors:
            raise send_errors[0]
        return out  # type: ignore[return-value]

    def exchange_objects(self, objs: Sequence[Any]) -> List[Any]:
        """All-to-all of structured values over the TYPED wire encoding
        (dicts/lists/numpy/scalars — distributed/wire.py): the shuffle
        path carries no pickle, same discipline as the PS protocol (a
        malformed frame raises WireError instead of executing bytes)."""
        from paddlebox_tpu.distributed import wire
        bufs = [wire.dumps(o) for o in objs]
        return [wire.loads(b) for b in self.exchange(bufs)]

    def close(self) -> None:
        self._running = False
        try:
            # shutdown() wakes the blocked accept(); close() alone leaves
            # the listening file description alive inside the syscall.
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._server.close()
        except OSError:
            pass


def make_chunk_exchanger(transport: TcpTransport
                         ) -> Callable[[List[Any]], Any]:
    """Adapter: Dataset.global_shuffle(exchange=...) over a TcpTransport —
    ships ColumnarChunk buckets to their owner ranks and concatenates what
    this rank receives (role of ShuffleData → ReceiveSuffleData)."""
    from paddlebox_tpu.data.columnar import ColumnarChunk

    def exchange(buckets: List[ColumnarChunk]) -> ColumnarChunk:
        # Chunk -> dict-of-arrays for the typed wire via the dataclass
        # fields themselves (a future ColumnarChunk column rides along
        # automatically instead of being silently dropped); rebuilt on
        # receive. ColumnarChunk is exactly wire-shaped: numpy leaves.
        received = transport.exchange_objects(
            [vars(b).copy() for b in buckets])
        return ColumnarChunk.concat(
            [ColumnarChunk(**d) for d in received])

    return exchange
