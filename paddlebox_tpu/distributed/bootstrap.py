"""Multi-host cluster bootstrap over jax.distributed.

Role of the reference's cluster-init machinery: ``c_gen_nccl_id`` /
``c_comm_init_all`` ops, Gloo ``HdfsStore`` rendezvous
(``gloo_wrapper.h:53``), and the env contract
(``PADDLE_TRAINER_ENDPOINTS``/``PADDLE_TRAINER_ID``) set up by launch.

TPU-first: ``jax.distributed.initialize`` is the whole control plane —
after it, ``jax.devices()`` spans the pod slice and XLA collectives ride
ICI/DCN; no communicator objects exist to manage. The env contract is
``PBX_COORDINATOR`` / ``PBX_NUM_PROCESSES`` / ``PBX_PROCESS_ID`` (set by
``paddlebox_tpu.launch``), falling back to single-process.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from paddlebox_tpu.core import log

_initialized = False


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the cluster (idempotent). Reads PBX_* env when args omitted."""
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("PBX_COORDINATOR")
    if num_processes is None:
        num_processes = int(os.environ.get("PBX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("PBX_PROCESS_ID", "0"))
    if num_processes > 1:
        if not coordinator:
            raise ValueError("multi-process init needs a coordinator "
                             "address (PBX_COORDINATOR)")
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
        log.vlog(0, "joined cluster: rank %d/%d via %s", process_id,
                 num_processes, coordinator)
    _initialized = True


def is_initialized() -> bool:
    return _initialized


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
