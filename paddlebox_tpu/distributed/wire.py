"""Typed wire protocol for the PS service (no pickle on the socket).

Role of the brpc PS message layer (``ps/service/brpc_ps_server.h:40``,
``sendrecv.proto``): a versioned, length-prefixed frame whose payload is a
TYPED tree of scalars / strings / numpy buffers — deserialization can
construct only these types, unlike pickle (which executes arbitrary
reduce callables from the peer and is unacceptable even one hop past
localhost).

Frame layout (little-endian):

    magic   2s   b"PB"
    version u8   1 (legacy blocking) or 2 (multiplexed) — per FRAME, so
                 one connection can carry both during negotiation
    flags   u8   v1: reserved (0); v2: FLAG_SG / FLAG_SHM payload form
    length  u64  payload byte length (bounded by MAX_PAYLOAD)

v1 payload: one value, tag-prefixed; containers recurse.

    0x00 None
    0x01 bool      u8
    0x02 int       i64
    0x03 float     f64
    0x04 str       u32 len + utf-8
    0x05 bytes     u64 len + raw
    0x06 ndarray   u8 dtype-code, u8 ndim, ndim*u64 shape, raw buffer
    0x07 dict      u32 count + (str key, value)*
    0x08 list      u32 count + value*

v2 payload (the RPC mux plane, RPC.md): a u64 REQUEST ID leads, so N
calls can be in flight per socket and replies match out of order.

    plain (flags=0):  u64 req_id + one v1-encoded value
    FLAG_SG:          u64 req_id, u32 meta_len, meta, u32 nseg,
                      nseg * (u64 offset, u64 nbytes), pad, segments.
                      ``meta`` is the typed tree with ndarray leaves
                      replaced by tag 0x09 (dtype, shape, seg index);
                      raw array bytes are 64-byte-aligned TRAILING
                      segments (the shm_channel frame discipline), so
                      the sender can scatter/gather ``sendmsg`` live
                      array views with no join copy and the receiver
                      decodes views straight out of the frame buffer.
    FLAG_SHM:         like FLAG_SG but the segment table indexes into a
                      named shared-memory block (u32 name_len + name
                      follow the meta) instead of trailing bytes — the
                      co-located-process shortcut (FLAGS_rpc_shm).

SECURITY SCOPE: the protocol authenticates nothing — it is for a trusted
cluster network (same stance as the reference's brpc PS, which runs on
the job's private fabric). It is robust against malformed and truncated
frames (every length is bounds-checked; unknown tags/dtypes/versions
raise :class:`WireError`), not against an active adversary.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

WIRE_VERSION = 1
WIRE_VERSION_MUX = 2
MAX_PAYLOAD = 1 << 34          # 16 GiB frame cap
_MAGIC = b"PB"
HEADER = struct.Struct("<2sBBQ")

# v2 frame flags.
FLAG_SG = 0x01                 # scatter/gather segmented array payload
FLAG_SHM = 0x02                # segments live in a shared-memory block

_ALIGN = 64                    # segment alignment (shm_channel discipline)


def _align(off: int) -> int:
    return (off + _ALIGN - 1) & ~(_ALIGN - 1)

# dtype allowlist (code <-> dtype); anything else is rejected.
_DTYPES = (np.dtype(np.float32), np.dtype(np.float64),
           np.dtype(np.int32), np.dtype(np.int64),
           np.dtype(np.uint8), np.dtype(np.uint32),
           np.dtype(np.uint64), np.dtype(np.bool_),
           np.dtype(np.int8), np.dtype(np.uint16), np.dtype(np.int16),
           np.dtype(np.float16))
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}

_MAX_NDIM = 16
_MAX_CONTAINER = 1 << 24       # sanity cap on dict/list entries


class WireError(ValueError):
    """Malformed, truncated, oversized, or version-mismatched frame."""


def _enc_value(out: List[bytes], v: Any, segs: List[np.ndarray] = None
               ) -> None:
    """Encode one value into ``out`` (a list of buffer segments joined
    or gathered by the caller). With ``segs`` not None (the SG meta
    form), ndarray leaves emit tag 0x09 — dtype/shape + an index into
    ``segs`` — and the raw bytes are collected into ``segs`` for the
    frame's aligned trailing segments instead of inlining."""
    if v is None:
        out.append(b"\x00")
    elif isinstance(v, bool):           # before int (bool is int subclass)
        out.append(b"\x01" + (b"\x01" if v else b"\x00"))
    elif isinstance(v, (int, np.integer)):
        out.append(b"\x02" + struct.pack("<q", int(v)))
    elif isinstance(v, (float, np.floating)):
        out.append(b"\x03" + struct.pack("<d", float(v)))
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(b"\x04" + struct.pack("<I", len(b)) + b)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(b"\x05" + struct.pack("<Q", len(b)) + b)
    elif isinstance(v, np.ndarray):
        a = np.ascontiguousarray(v)
        code = _DTYPE_CODE.get(a.dtype)
        if code is None:
            raise WireError(f"dtype {a.dtype} not on the wire allowlist")
        if a.ndim > _MAX_NDIM:
            raise WireError(f"ndim {a.ndim} > {_MAX_NDIM}")
        if segs is not None:
            out.append(b"\x09" + struct.pack("<BB", code, a.ndim)
                       + struct.pack(f"<{a.ndim}Q", *a.shape)
                       + struct.pack("<I", len(segs)))
            segs.append(a)
            return
        out.append(b"\x06" + struct.pack("<BB", code, a.ndim)
                   + struct.pack(f"<{a.ndim}Q", *a.shape))
        # A memoryview SEGMENT, not tobytes(): the final join (or the
        # sendmsg gather) reads the array buffer directly, so encoding
        # never pays a payload-sized intermediate copy. Frames are
        # bit-identical to the tobytes() form (pinned by
        # tests/test_rpc_mux.py round-trip). Empty arrays cannot be
        # cast (zeros in shape) and contribute zero bytes anyway.
        out.append(memoryview(a).cast("B") if a.size else b"")
    elif isinstance(v, dict):
        out.append(b"\x07" + struct.pack("<I", len(v)))
        for k, item in v.items():
            if not isinstance(k, str):
                raise WireError(f"dict key must be str, got {type(k)}")
            kb = k.encode("utf-8")
            out.append(struct.pack("<I", len(kb)) + kb)
            _enc_value(out, item, segs)
    elif isinstance(v, (list, tuple)):
        out.append(b"\x08" + struct.pack("<I", len(v)))
        for item in v:
            _enc_value(out, item, segs)
    else:
        raise WireError(f"type {type(v).__name__} not wire-serializable")


def dumps(obj: Any) -> bytes:
    out: List[bytes] = []
    _enc_value(out, obj)
    return b"".join(out)


def array_nbytes(obj: Any) -> int:
    """Total ndarray payload bytes in a tree — the cheap scan deciding
    whether a v2 frame is worth the SG/shm form."""
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, dict):
        return sum(array_nbytes(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(array_nbytes(v) for v in obj)
    return 0


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise WireError("truncated frame")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def unpack(self, st: struct.Struct) -> Tuple:
        return st.unpack(self.take(st.size))


_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_BB = struct.Struct("<BB")


def _dec_value(r: _Reader, segs: Optional[List[Any]] = None) -> Any:
    tag = r.take(1)
    if tag == b"\x00":
        return None
    if tag == b"\x01":
        return r.take(1) != b"\x00"
    if tag == b"\x02":
        return r.unpack(_I64)[0]
    if tag == b"\x03":
        return r.unpack(_F64)[0]
    if tag == b"\x04":
        (n,) = r.unpack(_U32)
        try:
            return r.take(n).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"bad utf-8 string: {e}") from None
    if tag == b"\x05":
        (n,) = r.unpack(_U64)
        return r.take(n)
    if tag == b"\x06":
        code, ndim = r.unpack(_BB)
        if code >= len(_DTYPES):
            raise WireError(f"unknown dtype code {code}")
        if ndim > _MAX_NDIM:
            raise WireError(f"ndim {ndim} > {_MAX_NDIM}")
        shape = struct.unpack(f"<{ndim}Q", r.take(8 * ndim))
        dt = _DTYPES[code]
        count = 1
        for s in shape:
            count *= s
        nbytes = count * dt.itemsize
        if nbytes > MAX_PAYLOAD:
            raise WireError("array larger than frame cap")
        raw = r.take(nbytes)
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == b"\x07":
        (n,) = r.unpack(_U32)
        if n > _MAX_CONTAINER:
            raise WireError("dict too large")
        d: Dict[str, Any] = {}
        for _ in range(n):
            (kl,) = r.unpack(_U32)
            try:
                k = r.take(kl).decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad utf-8 key: {e}") from None
            d[k] = _dec_value(r, segs)
        return d
    if tag == b"\x08":
        (n,) = r.unpack(_U32)
        if n > _MAX_CONTAINER:
            raise WireError("list too large")
        return [_dec_value(r, segs) for _ in range(n)]
    if tag == b"\x09":
        if segs is None:
            raise WireError("segment-ref array outside an SG frame")
        code, ndim = r.unpack(_BB)
        if code >= len(_DTYPES):
            raise WireError(f"unknown dtype code {code}")
        if ndim > _MAX_NDIM:
            raise WireError(f"ndim {ndim} > {_MAX_NDIM}")
        shape = struct.unpack(f"<{ndim}Q", r.take(8 * ndim))
        (idx,) = r.unpack(_U32)
        if idx >= len(segs):
            raise WireError(f"segment index {idx} >= {len(segs)}")
        dt = _DTYPES[code]
        count = 1
        for s in shape:
            count *= s
        nbytes = count * dt.itemsize
        seg = segs[idx]
        if nbytes != len(seg):
            raise WireError(
                f"segment {idx}: {len(seg)} bytes != shape {shape} "
                f"({nbytes} bytes)")
        # A VIEW over the frame's receive buffer — no copy; the buffer
        # outlives the arrays (each frame owns its own buffer).
        return np.frombuffer(seg, dtype=dt).reshape(shape)
    raise WireError(f"unknown type tag {tag!r}")


def loads(buf: bytes) -> Any:
    r = _Reader(buf)
    v = _dec_value(r)
    if r.pos != len(buf):
        raise WireError(f"{len(buf) - r.pos} trailing bytes after value")
    return v


def pack_frame(obj: Any) -> bytes:
    payload = dumps(obj)
    if len(payload) > MAX_PAYLOAD:
        raise WireError(f"payload {len(payload)} exceeds cap")
    return HEADER.pack(_MAGIC, WIRE_VERSION, 0, len(payload)) + payload


def read_frame_header(hdr: bytes) -> int:
    """Validate a header; returns the payload length to read next."""
    try:
        magic, version, _flags, length = HEADER.unpack(hdr)
    except struct.error as e:
        raise WireError(f"bad header: {e}") from None
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireError(f"peer wire version {version} != {WIRE_VERSION} — "
                        f"mixed-version cluster; upgrade in lockstep")
    if length > MAX_PAYLOAD:
        raise WireError(f"frame length {length} exceeds cap")
    return length


# ---------------------------------------------------------------------------
# v2 (multiplexed) frames — request-id'd payloads, optional SG/shm array
# segments. The v1 surface above is untouched; a connection negotiates
# up via the ``wire_caps`` probe (distributed/rpc.py) and every frame
# still self-describes its version, so mixed-version peers interoperate
# per-frame.
# ---------------------------------------------------------------------------

_REQID = struct.Struct("<Q")
_SEG = struct.Struct("<QQ")     # (offset, nbytes) per segment


def read_any_header(hdr: bytes) -> Tuple[int, int, int]:
    """Validate a v1 OR v2 header; returns (version, flags, length)."""
    try:
        magic, version, fl, length = HEADER.unpack(hdr)
    except struct.error as e:
        raise WireError(f"bad header: {e}") from None
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version not in (WIRE_VERSION, WIRE_VERSION_MUX):
        raise WireError(f"peer wire version {version} not in "
                        f"({WIRE_VERSION}, {WIRE_VERSION_MUX}) — "
                        f"mixed-version cluster; upgrade in lockstep")
    if version == WIRE_VERSION and fl != 0:
        raise WireError(f"v1 frame with flags {fl:#x}")
    if length > MAX_PAYLOAD:
        raise WireError(f"frame length {length} exceeds cap")
    return version, fl, length


def pack_frame_v2(obj: Any, req_id: int) -> bytes:
    """One plain (non-SG) v2 frame: header + req id + typed tree."""
    out: List[bytes] = []
    _enc_value(out, obj)
    payload_len = 8 + sum(len(b) for b in out)
    if payload_len > MAX_PAYLOAD:
        raise WireError(f"payload {payload_len} exceeds cap")
    return b"".join([HEADER.pack(_MAGIC, WIRE_VERSION_MUX, 0, payload_len),
                     _REQID.pack(req_id)] + out)


def loads_v2(payload) -> Tuple[int, Any]:
    """Decode a plain v2 payload -> (req_id, value)."""
    buf = bytes(payload)
    if len(buf) < 8:
        raise WireError("v2 payload shorter than its request id")
    (req_id,) = _REQID.unpack_from(buf)
    return req_id, loads(buf[8:])


def dumps_sg(obj: Any) -> Tuple[bytes, List[np.ndarray]]:
    """SG meta encoding: (meta bytes, contiguous arrays referenced by
    tag-0x09 leaves, in segment order)."""
    out: List[bytes] = []
    segs: List[np.ndarray] = []
    _enc_value(out, obj, segs)
    return b"".join(out), segs


def sg_frame_buffers(obj: Any, req_id: int) -> List[Any]:
    """Scatter/gather buffer list for ONE SG frame — header + head in a
    single small bytes object, then alternating pad/array-view buffers.
    ``socket.sendmsg(bufs)`` gathers straight from the live array
    buffers: the encode path never materializes the payload. The caller
    must not mutate the arrays until the send completes."""
    meta, arrays = dumps_sg(obj)
    nseg = len(arrays)
    head_len = 8 + 4 + len(meta) + 4 + _SEG.size * nseg
    offs: List[int] = []
    off = _align(head_len)
    for a in arrays:
        offs.append(off)
        off = _align(off + a.nbytes)
    # Payload ends at the last segment's end (no trailing pad).
    payload_len = (offs[-1] + arrays[-1].nbytes) if nseg else head_len
    if payload_len > MAX_PAYLOAD:
        raise WireError(f"payload {payload_len} exceeds cap")
    head = [HEADER.pack(_MAGIC, WIRE_VERSION_MUX, FLAG_SG, payload_len),
            _REQID.pack(req_id), _U32.pack(len(meta)), meta,
            _U32.pack(nseg)]
    head += [_SEG.pack(o, a.nbytes) for o, a in zip(offs, arrays)]
    bufs: List[Any] = [b"".join(head)]
    cursor = head_len
    for o, a in zip(offs, arrays):
        if o > cursor:
            bufs.append(b"\x00" * (o - cursor))
        if a.size:  # empty arrays can't cast and carry no bytes
            bufs.append(memoryview(a).cast("B"))
        cursor = o + a.nbytes
    return bufs


def _sg_head(r: "_Reader", payload) -> Tuple[int, bytes, List[Tuple[int,
                                                                    int]]]:
    (req_id,) = r.unpack(_REQID)
    (meta_len,) = r.unpack(_U32)
    meta = r.take(meta_len)
    (nseg,) = r.unpack(_U32)
    if nseg > _MAX_CONTAINER:
        raise WireError("too many segments")
    table = [r.unpack(_SEG) for _ in range(nseg)]
    return req_id, meta, table


def loads_sg(payload) -> Tuple[int, Any]:
    """Decode an SG payload -> (req_id, value). ``payload`` should be a
    memoryview over the frame's receive buffer: decoded arrays are
    zero-copy views into it (the buffer must outlive them)."""
    mv = payload if isinstance(payload, memoryview) else memoryview(payload)
    r = _Reader(mv)
    req_id, meta, table = _sg_head(r, mv)
    segs: List[Any] = []
    for off, nbytes in table:
        if off < r.pos or off + nbytes > len(mv):
            raise WireError(f"segment [{off}, {off + nbytes}) outside "
                            f"payload of {len(mv)} bytes")
        segs.append(mv[off:off + nbytes])
    return req_id, loads_meta(bytes(meta), segs)


def loads_meta(meta: bytes, segs: List[Any]) -> Any:
    """Decode an SG meta tree against an externally supplied segment
    list (the shm path attaches its block and slices it here)."""
    r = _Reader(meta)
    v = _dec_value(r, segs)
    if r.pos != len(meta):
        raise WireError(f"{len(meta) - r.pos} trailing bytes after value")
    return v


def sg_plan(arrays: List[np.ndarray]) -> Tuple[List[int], int]:
    """64B-aligned placement of ``arrays`` in one block: (offsets,
    total). Shared by the shm shortcut's block sizing."""
    offs: List[int] = []
    off = 0
    for a in arrays:
        offs.append(off)
        off = _align(off + a.nbytes)
    return offs, max(off, 1)


def pack_frame_shm(obj: Any, req_id: int, name: str,
                   block: memoryview) -> Tuple[bytes, int]:
    """One FLAG_SHM frame: meta + segment table on the socket, array
    bytes copied into ``block`` (the caller's shared-memory mapping,
    sized by :func:`sg_plan`). Returns (frame bytes, bytes placed)."""
    meta, arrays = dumps_sg(obj)
    offs, total = sg_plan(arrays)
    if total > len(block) and arrays:
        raise WireError(f"shm block {len(block)} < plan {total}")
    for o, a in zip(offs, arrays):
        if a.size:  # empty arrays can't cast and place no bytes
            block[o:o + a.nbytes] = memoryview(a).cast("B")
    nb = name.encode("utf-8")
    head = [_REQID.pack(req_id), _U32.pack(len(meta)), meta,
            _U32.pack(len(nb)), nb, _U32.pack(len(arrays))]
    head += [_SEG.pack(o, a.nbytes) for o, a in zip(offs, arrays)]
    payload = b"".join(head)
    if len(payload) > MAX_PAYLOAD:
        raise WireError(f"payload {len(payload)} exceeds cap")
    frame = HEADER.pack(_MAGIC, WIRE_VERSION_MUX, FLAG_SG | FLAG_SHM,
                        len(payload)) + payload
    return frame, total


def loads_shm(payload, attach: Callable[[str], Any]) -> Tuple[int, Any]:
    """Decode a FLAG_SHM payload: ``attach(name)`` returns the block's
    memoryview; decoded arrays are COPIES (the caller unlinks the
    one-shot block immediately after)."""
    mv = payload if isinstance(payload, memoryview) else memoryview(payload)
    r = _Reader(mv)
    (req_id,) = r.unpack(_REQID)
    (meta_len,) = r.unpack(_U32)
    meta = r.take(meta_len)
    (name_len,) = r.unpack(_U32)
    try:
        name = bytes(r.take(name_len)).decode("utf-8")
    except UnicodeDecodeError as e:
        raise WireError(f"bad shm name: {e}") from None
    (nseg,) = r.unpack(_U32)
    if nseg > _MAX_CONTAINER:
        raise WireError("too many segments")
    table = [r.unpack(_SEG) for _ in range(nseg)]
    block = attach(name)
    segs: List[Any] = []
    for off, nbytes in table:
        if off + nbytes > len(block):
            raise WireError(f"shm segment [{off}, {off + nbytes}) outside "
                            f"block of {len(block)} bytes")
        segs.append(block[off:off + nbytes])
    obj = loads_meta(bytes(meta), segs)
    return req_id, _copy_arrays(obj)


def _copy_arrays(obj: Any) -> Any:
    if isinstance(obj, np.ndarray):
        return obj.copy()
    if isinstance(obj, dict):
        return {k: _copy_arrays(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_copy_arrays(v) for v in obj]
    return obj
