"""Typed wire protocol for the PS service (no pickle on the socket).

Role of the brpc PS message layer (``ps/service/brpc_ps_server.h:40``,
``sendrecv.proto``): a versioned, length-prefixed frame whose payload is a
TYPED tree of scalars / strings / numpy buffers — deserialization can
construct only these types, unlike pickle (which executes arbitrary
reduce callables from the peer and is unacceptable even one hop past
localhost).

Frame layout (little-endian):

    magic   2s   b"PB"
    version u8   WIRE_VERSION — mismatch is rejected, not guessed at
    flags   u8   reserved (0)
    length  u64  payload byte length (bounded by MAX_PAYLOAD)

Payload: one value, tag-prefixed; containers recurse.

    0x00 None
    0x01 bool      u8
    0x02 int       i64
    0x03 float     f64
    0x04 str       u32 len + utf-8
    0x05 bytes     u64 len + raw
    0x06 ndarray   u8 dtype-code, u8 ndim, ndim*u64 shape, raw buffer
    0x07 dict      u32 count + (str key, value)*
    0x08 list      u32 count + value*

SECURITY SCOPE: the protocol authenticates nothing — it is for a trusted
cluster network (same stance as the reference's brpc PS, which runs on
the job's private fabric). It is robust against malformed and truncated
frames (every length is bounds-checked; unknown tags/dtypes/versions
raise :class:`WireError`), not against an active adversary.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

WIRE_VERSION = 1
MAX_PAYLOAD = 1 << 34          # 16 GiB frame cap
_MAGIC = b"PB"
HEADER = struct.Struct("<2sBBQ")

# dtype allowlist (code <-> dtype); anything else is rejected.
_DTYPES = (np.dtype(np.float32), np.dtype(np.float64),
           np.dtype(np.int32), np.dtype(np.int64),
           np.dtype(np.uint8), np.dtype(np.uint32),
           np.dtype(np.uint64), np.dtype(np.bool_),
           np.dtype(np.int8), np.dtype(np.uint16), np.dtype(np.int16),
           np.dtype(np.float16))
_DTYPE_CODE = {dt: i for i, dt in enumerate(_DTYPES)}

_MAX_NDIM = 16
_MAX_CONTAINER = 1 << 24       # sanity cap on dict/list entries


class WireError(ValueError):
    """Malformed, truncated, oversized, or version-mismatched frame."""


def _enc_value(out: List[bytes], v: Any) -> None:
    if v is None:
        out.append(b"\x00")
    elif isinstance(v, bool):           # before int (bool is int subclass)
        out.append(b"\x01" + (b"\x01" if v else b"\x00"))
    elif isinstance(v, (int, np.integer)):
        out.append(b"\x02" + struct.pack("<q", int(v)))
    elif isinstance(v, (float, np.floating)):
        out.append(b"\x03" + struct.pack("<d", float(v)))
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(b"\x04" + struct.pack("<I", len(b)) + b)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(b"\x05" + struct.pack("<Q", len(b)) + b)
    elif isinstance(v, np.ndarray):
        a = np.ascontiguousarray(v)
        code = _DTYPE_CODE.get(a.dtype)
        if code is None:
            raise WireError(f"dtype {a.dtype} not on the wire allowlist")
        if a.ndim > _MAX_NDIM:
            raise WireError(f"ndim {a.ndim} > {_MAX_NDIM}")
        out.append(b"\x06" + struct.pack("<BB", code, a.ndim)
                   + struct.pack(f"<{a.ndim}Q", *a.shape))
        out.append(a.tobytes())
    elif isinstance(v, dict):
        out.append(b"\x07" + struct.pack("<I", len(v)))
        for k, item in v.items():
            if not isinstance(k, str):
                raise WireError(f"dict key must be str, got {type(k)}")
            kb = k.encode("utf-8")
            out.append(struct.pack("<I", len(kb)) + kb)
            _enc_value(out, item)
    elif isinstance(v, (list, tuple)):
        out.append(b"\x08" + struct.pack("<I", len(v)))
        for item in v:
            _enc_value(out, item)
    else:
        raise WireError(f"type {type(v).__name__} not wire-serializable")


def dumps(obj: Any) -> bytes:
    out: List[bytes] = []
    _enc_value(out, obj)
    return b"".join(out)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise WireError("truncated frame")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def unpack(self, st: struct.Struct) -> Tuple:
        return st.unpack(self.take(st.size))


_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_BB = struct.Struct("<BB")


def _dec_value(r: _Reader) -> Any:
    tag = r.take(1)
    if tag == b"\x00":
        return None
    if tag == b"\x01":
        return r.take(1) != b"\x00"
    if tag == b"\x02":
        return r.unpack(_I64)[0]
    if tag == b"\x03":
        return r.unpack(_F64)[0]
    if tag == b"\x04":
        (n,) = r.unpack(_U32)
        try:
            return r.take(n).decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"bad utf-8 string: {e}") from None
    if tag == b"\x05":
        (n,) = r.unpack(_U64)
        return r.take(n)
    if tag == b"\x06":
        code, ndim = r.unpack(_BB)
        if code >= len(_DTYPES):
            raise WireError(f"unknown dtype code {code}")
        if ndim > _MAX_NDIM:
            raise WireError(f"ndim {ndim} > {_MAX_NDIM}")
        shape = struct.unpack(f"<{ndim}Q", r.take(8 * ndim))
        dt = _DTYPES[code]
        count = 1
        for s in shape:
            count *= s
        nbytes = count * dt.itemsize
        if nbytes > MAX_PAYLOAD:
            raise WireError("array larger than frame cap")
        raw = r.take(nbytes)
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == b"\x07":
        (n,) = r.unpack(_U32)
        if n > _MAX_CONTAINER:
            raise WireError("dict too large")
        d: Dict[str, Any] = {}
        for _ in range(n):
            (kl,) = r.unpack(_U32)
            try:
                k = r.take(kl).decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad utf-8 key: {e}") from None
            d[k] = _dec_value(r)
        return d
    if tag == b"\x08":
        (n,) = r.unpack(_U32)
        if n > _MAX_CONTAINER:
            raise WireError("list too large")
        return [_dec_value(r) for _ in range(n)]
    raise WireError(f"unknown type tag {tag!r}")


def loads(buf: bytes) -> Any:
    r = _Reader(buf)
    v = _dec_value(r)
    if r.pos != len(buf):
        raise WireError(f"{len(buf) - r.pos} trailing bytes after value")
    return v


def pack_frame(obj: Any) -> bytes:
    payload = dumps(obj)
    if len(payload) > MAX_PAYLOAD:
        raise WireError(f"payload {len(payload)} exceeds cap")
    return HEADER.pack(_MAGIC, WIRE_VERSION, 0, len(payload)) + payload


def read_frame_header(hdr: bytes) -> int:
    """Validate a header; returns the payload length to read next."""
    try:
        magic, version, _flags, length = HEADER.unpack(hdr)
    except struct.error as e:
        raise WireError(f"bad header: {e}") from None
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise WireError(f"peer wire version {version} != {WIRE_VERSION} — "
                        f"mixed-version cluster; upgrade in lockstep")
    if length > MAX_PAYLOAD:
        raise WireError(f"frame length {length} exceeds cap")
    return length
