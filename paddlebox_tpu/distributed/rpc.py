"""Shared framed-RPC plane for the wire-protocol services.

The PS (``distributed/ps.py``), graph (``graph/service.py``), shard
(``multihost/shard_service.py``), and serving (``serving/service.py``)
services all speak the same length-prefixed typed-frame protocol
(``distributed/wire.py``). This module collects the transport ONCE so
protocol hardening (malformed-payload handling, frame errors, shutdown
semantics) cannot drift between services — the role of brpc's common
service plumbing under the reference's PS/graph stubs
(``sendrecv.proto`` services share one server loop there too).

Server: an EVENT LOOP, not thread-per-connection. ONE poller thread
(``selectors``) owns accept/read/write for every connection; decoded
requests go to a bounded worker pool (``FLAGS_rpc_worker_threads``)
only for device-touching/blocking handlers, while cheap handlers
(``POLLER_INLINE``: stats, clock_probe, metrics_snapshot, contains,
wire_caps) run inline on the poller. Payload bytes are received
straight into one preallocated buffer per frame (``recv_into`` — no
chunk-join copies) and replies are scatter/gather ``sendmsg`` buffer
lists, so a large ndarray reply is never materialized into a second
flat payload. Selector registrations are mutated ONLY on the poller
thread; workers hand completions back through a command queue and a
socketpair wakeup.

Client: a ``FramedRPCConn`` negotiates the MULTIPLEXED v2 wire on
connect (a ``wire_caps`` probe sent as a plain v1 frame — an old server
answers with an in-band error and the client falls back to the blocking
v1 discipline, counted by ``rpc/mux_fallbacks``, so mixed-version
clusters interoperate). On the mux plane every frame carries an
in-flight request id: N calls can be outstanding per socket
(``call_async``/futures), a dedicated reader thread matches replies out
of order, and array-heavy payloads ride zero-copy scatter/gather
(FLAG_SG) or shared-memory (FLAG_SHM, co-located processes,
``FLAGS_rpc_shm``) frames.

Robustness contract (unchanged from the blocking plane):
- a payload that is not a ``{"method": str, ...}`` dict gets an error
  REPLY (not a dropped connection — a malformed request must not strand
  the client until its socket timeout);
- handler exceptions are reported in-band and the connection keeps
  serving;
- wire-protocol violations drop the connection (a corrupt
  length-prefixed stream cannot be resynchronized);
- ``_after_reply()`` hooks post-response actions (the PS ``stop`` RPC
  closes its listener only AFTER the acknowledgement is on the wire);
- v1 requests are answered strictly IN ORDER per connection (a v1
  client matches replies by order, so the event loop serializes that
  connection's v1 dispatches even when handlers run on the pool).

Distributed tracing (OBSERVABILITY.md "Distributed tracing"): when the
CLIENT process has tracing on, every request dict carries a compact
``_trace`` context (``{tid, sid, origin}``) that the server loop pops,
installs thread-locally for the handler's duration, and records as a
``rpc/<method>`` server span whose ``parent`` is the client's span id.
Every reply also carries ``_server_ms`` (handler wall), letting any
client decompose its observed latency into server vs wire share without
a second RPC; on a SHARED mux connection the decomposition
(``last_server_ms``/``last_wire_ms``) is thread-local, so concurrent
callers each read their own call's split.

Retry/reconnect (unchanged): a dropped/half-read/desynced stream closes
the socket; the NEXT call re-resolves (``resolve=`` hook) and
reconnects. Methods named in ``idempotent`` retry with capped
exponential backoff bounded by ``FLAGS_rpc_max_retries`` AND
``FLAGS_rpc_retry_deadline_s``; non-idempotent methods never auto-retry
a call whose request may have executed.

Always-on observability (RPCs are not the jitted hot loop): the
module-level IN-FLIGHT CALL TABLE (``inflight_table()`` — peer
endpoint, method, age, per-endpoint outstanding depth) and the POLLER
TABLE (``poller_table()`` — per-server poller thread name, loop lag,
worker-queue depth), both registered as ``trace.stall_forensics``
providers so a watchdog stall names the remote or the wedged poller
first; per-method reconnect/retry counters
(``rpc/reconnects/<method>``, ``rpc/retries/<method>``) beside the
long-standing totals.
"""

from __future__ import annotations

import itertools
import os
import selectors
import socket
import threading
import time
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, FrozenSet, Iterable, List, Optional

from paddlebox_tpu.core import faults, flags, log, monitor, trace
from paddlebox_tpu.distributed import wire
from paddlebox_tpu.distributed.transport import (_recv_exact,
                                                 _recv_into_exact)

# -- in-flight RPC table ------------------------------------------------------

_INFLIGHT: Dict[int, Dict[str, Any]] = {}
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT_IDS = itertools.count(1)


def _inflight_enter(endpoint: str, method: str, service: str) -> int:
    token = next(_INFLIGHT_IDS)
    with _INFLIGHT_LOCK:
        _INFLIGHT[token] = {"endpoint": endpoint, "method": method,
                            "service": service, "t0": time.monotonic()}
    return token


def _inflight_exit(token: int) -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT.pop(token, None)


def inflight_table() -> List[Dict[str, Any]]:
    """Every RPC currently awaiting a peer's reply: endpoint, method,
    service, age, and ``outstanding`` — how many calls this process has
    in flight to that same endpoint (the mux depth). The watchdog's
    stall forensics include this (oldest first), so a hang past
    FLAGS_stall_timeout_s names the remote and the deepest pipe, not
    just the local thread stacks."""
    now = time.monotonic()
    with _INFLIGHT_LOCK:
        entries = list(_INFLIGHT.values())
    depth: Dict[str, int] = {}
    for e in entries:
        depth[e["endpoint"]] = depth.get(e["endpoint"], 0) + 1
    out = [{"endpoint": e["endpoint"], "method": e["method"],
            "service": e["service"], "age_s": round(now - e["t0"], 3),
            "outstanding": depth[e["endpoint"]]}
           for e in entries]
    out.sort(key=lambda e: (-e["outstanding"], -e["age_s"]))
    return out


trace.register_forensics_provider("inflight_rpcs", inflight_table)

# -- poller table -------------------------------------------------------------

_SERVERS: "weakref.WeakSet[FramedRPCServer]" = weakref.WeakSet()


def poller_table() -> List[Dict[str, Any]]:
    """One row per live FramedRPCServer in this process: poller thread
    name, current loop lag (how long the poller has been processing
    without re-entering ``select`` — a wedged inline handler shows up
    here), worker-queue depth, and connection count. Deepest queue
    first; a stalled server names its poller thread in the watchdog's
    forensics before any thread stack."""
    now = time.monotonic()
    out = []
    for srv in list(_SERVERS):
        try:
            out.append(srv._poller_stats(now))
        except Exception:  # a half-torn-down server must not break forensics
            continue
    out.sort(key=lambda r: (-r["worker_queue_depth"], -r["loop_lag_ms"]))
    return out


trace.register_forensics_provider("rpc_pollers", poller_table)


def _host_id() -> str:
    """Machine identity for the co-located-process shm shortcut: two
    peers exchange this in ``wire_caps`` and enable FLAG_SHM only on an
    exact match (boot id beats hostname — containers can share names)."""
    global _HOST_ID
    if _HOST_ID is None:
        tag = ""
        try:
            with open("/proc/sys/kernel/random/boot_id") as f:
                tag = f.read().strip()
        except OSError:
            pass
        _HOST_ID = f"{socket.gethostname()}|{tag}"
    return _HOST_ID


_HOST_ID: Optional[str] = None
_SHM_IDS = itertools.count(1)


def _pack_shm_frame(obj: Any, rid: int) -> bytes:
    """Encode one FLAG_SHM frame: arrays land in a fresh one-shot
    SharedMemory block whose unlink OWNERSHIP transfers to the receiver
    (this side untracks it, shm_channel discipline)."""
    from multiprocessing import shared_memory
    from paddlebox_tpu.data import shm_channel
    _, arrays = wire.dumps_sg(obj)
    _, total = wire.sg_plan(arrays)
    shm = shared_memory.SharedMemory(
        create=True, size=total,
        name=f"pbx-rpc-{os.getpid()}-{next(_SHM_IDS)}")
    try:
        frame, _ = wire.pack_frame_shm(obj, rid, shm.name, shm.buf)
    except BaseException:
        shm.close()
        try:
            shm.unlink()
        except OSError:
            pass
        raise
    shm_channel.untrack(shm)
    shm.close()
    return frame


def _consume_shm(payload: memoryview) -> Any:
    """Decode one FLAG_SHM payload, then close AND unlink its one-shot
    block (the arrays were copied out by ``wire.loads_shm``)."""
    from multiprocessing import shared_memory
    holder: Dict[str, Any] = {}

    def attach(name: str):
        shm = shared_memory.SharedMemory(name=name)
        holder["shm"] = shm
        return shm.buf

    try:
        return wire.loads_shm(payload, attach)
    finally:
        shm = holder.get("shm")
        if shm is not None:
            shm.close()
            try:
                shm.unlink()
            except OSError:
                pass


def _decode_v2_payload(fl: int, payload: bytearray):
    """(req_id, value) from a v2 payload buffer, honoring SG/SHM flags.
    ``rpc/sg_recv`` is the segmented-receive faultpoint — the window a
    crash drill kills in the middle of a scatter/gather frame."""
    if fl & wire.FLAG_SHM:
        faults.faultpoint("rpc/sg_recv")
        return _consume_shm(memoryview(payload))
    if fl & wire.FLAG_SG:
        faults.faultpoint("rpc/sg_recv")
        # Arrays decode as VIEWS over `payload`; the bytearray stays
        # alive as long as any of them does.
        return wire.loads_sg(memoryview(payload))
    return wire.loads_v2(payload)


def _sendmsg_all(sock: socket.socket, bufs: List[Any]) -> None:
    """Gather-send every buffer (sendmsg may stop short; resume from
    the trim point). The blocking-socket sibling of the poller's
    incremental flush."""
    pending = deque(bufs)
    while pending:
        batch = list(itertools.islice(pending, 0, 64))
        sent = sock.sendmsg(batch)
        _trim_sent(pending, sent)


def _trim_sent(pending: deque, sent: int) -> None:
    while sent > 0 and pending:
        head = pending[0]
        n = len(head) if not isinstance(head, memoryview) else head.nbytes
        if sent >= n:
            pending.popleft()
            sent -= n
        else:
            mv = head if isinstance(head, memoryview) else memoryview(head)
            pending[0] = mv[sent:]
            sent = 0


class _Conn:
    """Per-connection state owned by the poller thread."""

    __slots__ = ("sock", "peer", "hbuf", "pver", "pflags", "plen", "pbuf",
                 "pview", "pfill", "out", "wreg", "close_after_flush",
                 "dead", "v1_busy", "v1_backlog", "peer_sg", "peer_shm")

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.hbuf = bytearray()        # partial frame header
        self.pver = 0
        self.pflags = 0
        self.plen = 0
        self.pbuf: Optional[bytearray] = None   # preallocated payload
        self.pview: Optional[memoryview] = None
        self.pfill = 0
        self.out: deque = deque()      # reply buffers awaiting flush
        self.wreg = False              # EVENT_WRITE registered
        self.close_after_flush = False
        self.dead = False
        self.v1_busy = False           # a v1 request is being served
        self.v1_backlog: deque = deque()
        self.peer_sg = False           # negotiated via wire_caps
        self.peer_shm = False


class FramedRPCServer:
    """Event-loop socket server dispatching typed frames to
    ``handle_<method>``: one poller thread owns every socket, a bounded
    worker pool runs the blocking handlers."""

    # Subclasses set this for log attribution ("ps[3]", "graph[0]", ...).
    service_name: str = "rpc"

    #: Methods cheap and non-blocking enough to run ON the poller thread
    #: (no device work, at most a brief lock): a stats scrape or clock
    #: probe answers even while every worker is wedged on device work.
    POLLER_INLINE: FrozenSet[str] = frozenset(
        {"stats", "clock_probe", "metrics_snapshot", "metrics_history",
         "alerts_active", "contains", "wire_caps"})

    def __init__(self, endpoint: str, *, backlog: int = 32):
        host, port = endpoint.rsplit(":", 1)
        self._server = socket.create_server((host, int(port)),
                                            backlog=backlog)
        self._server.setblocking(False)
        self.endpoint = f"{host}:{self._server.getsockname()[1]}"
        self._running = True
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._server, selectors.EVENT_READ, None)
        # Wakeup pipe: workers (and cross-thread stop/close calls) post
        # a command and write one byte; ONLY the poller thread ever
        # mutates selector registrations or _Conn state.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._cmds: deque = deque()
        self._conns: Dict[socket.socket, _Conn] = {}
        self._conns_lock = threading.Lock()
        self._workers: Optional[ThreadPoolExecutor] = None
        self._queue_depth = 0          # requests handed to the pool
        self._busy_since: Optional[float] = None
        _SERVERS.add(self)
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True,
            name=f"rpc-poller-{self.endpoint}")
        self._poller.start()

    # -- poller loop -------------------------------------------------------

    def _poll_loop(self) -> None:
        while True:
            try:
                events = self._sel.select()
                # graftlint: allow-lock(poller-owned stamp: single writer, float slot — forensics reader tolerates a torn instant)
                self._busy_since = time.monotonic()
                for key, mask in events:
                    data = key.data
                    if data is None:
                        self._do_accept()
                    elif data == "wake":
                        self._drain_wake()
                    else:
                        cs: _Conn = data
                        if mask & selectors.EVENT_WRITE and not cs.dead:
                            self._flush(cs)
                        if mask & selectors.EVENT_READ and not cs.dead:
                            self._do_read(cs)
                monitor.set_gauge(
                    "rpc/poller_lag_ms",
                    round((time.monotonic() - self._busy_since) * 1e3, 3))
            except Exception as e:  # the poller must survive anything
                log.warning("%s: poller error: %r", self.service_name, e)
            finally:
                self._busy_since = None
            if (not self._running and self._server is None
                    and not self._conns):
                break
        self._teardown()

    def _teardown(self) -> None:
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
        if self._workers is not None:
            self._workers.shutdown(wait=False)

    def _post(self, fn: Callable[[], None]) -> None:
        self._cmds.append(fn)
        self._wake()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def _drain_wake(self) -> None:
        faults.faultpoint("rpc/poller_wakeup")
        try:
            while self._wake_r.recv(4096):
                pass
        except BlockingIOError:
            pass
        while True:
            try:
                fn = self._cmds.popleft()
            except IndexError:
                break
            fn()

    def _do_accept(self) -> None:
        srv = self._server
        if srv is None:
            return
        while True:
            try:
                sock, addr = srv.accept()
            except BlockingIOError:
                return
            except OSError:
                self._close_listener()
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            cs = _Conn(sock, f"{addr[0]}:{addr[1]}")
            with self._conns_lock:
                self._conns[sock] = cs
            self._sel.register(sock, selectors.EVENT_READ, cs)

    def _close_listener(self) -> None:
        srv = self._server
        if srv is None:
            return
        # graftlint: allow-lock(poller-owned: only the poller clears it; stop() reads a stale fd at worst and shutdown is idempotent)
        self._server = None
        try:
            self._sel.unregister(srv)
        except (KeyError, ValueError, OSError):
            pass
        try:
            srv.close()
        except OSError:
            pass

    # -- read side ---------------------------------------------------------

    def _do_read(self, cs: _Conn) -> None:
        try:
            while not cs.dead:
                if cs.pbuf is None:
                    chunk = cs.sock.recv(wire.HEADER.size - len(cs.hbuf))
                    if not chunk:
                        raise ConnectionError("peer closed")
                    cs.hbuf += chunk
                    if len(cs.hbuf) < wire.HEADER.size:
                        return
                    ver, fl, ln = wire.read_any_header(bytes(cs.hbuf))
                    cs.hbuf.clear()
                    cs.pver, cs.pflags, cs.plen = ver, fl, ln
                    cs.pbuf = bytearray(ln)
                    cs.pview = memoryview(cs.pbuf)
                    cs.pfill = 0
                    if ln == 0:
                        self._on_frame(cs)
                else:
                    n = cs.sock.recv_into(cs.pview[cs.pfill:])
                    if n == 0:
                        raise ConnectionError("peer closed")
                    cs.pfill += n
                    if cs.pfill == cs.plen:
                        self._on_frame(cs)
        except BlockingIOError:
            return
        except wire.WireError as e:
            # Protocol violation: drop the connection — resynchronizing
            # a corrupt length-prefixed stream is not possible.
            log.warning("%s: dropping connection on wire error: %s",
                        self.service_name, e)
            self._drop_conn(cs)
        except (ConnectionError, OSError, EOFError):
            self._drop_conn(cs)

    def _on_frame(self, cs: _Conn) -> None:
        ver, fl, payload = cs.pver, cs.pflags, cs.pbuf
        cs.pbuf = cs.pview = None
        # The decoded-request handoff point (worker pool or inline): the
        # drills' hook for a server wedged between transport and handler.
        faults.faultpoint("rpc/mux_dispatch")
        if ver == wire.WIRE_VERSION:
            req = wire.loads(bytes(payload))
            if cs.v1_busy:
                # v1 clients match replies by ORDER: serialize this
                # connection's v1 dispatches.
                cs.v1_backlog.append(req)
            else:
                cs.v1_busy = True
                self._start_request(cs, req, rid=0, v1=True)
        else:
            rid, req = _decode_v2_payload(fl, payload)
            self._start_request(cs, req, rid=rid, v1=False)

    def _start_request(self, cs: _Conn, req: Any, *, rid: int,
                       v1: bool) -> None:
        method = req.get("method") if isinstance(req, dict) else None
        if not isinstance(method, str):
            self._queue_reply(cs, self._encode_reply(
                cs, rid, v1, {"ok": False,
                              "error": "request must be a dict with a "
                                       "str 'method'"}), v1)
            return
        if method == "wire_caps":
            self._queue_reply(cs, self._encode_reply(
                cs, rid, v1, {"ok": True,
                              "result": self._wire_caps(cs, req)}), v1)
            return
        tctx = req.pop("_trace", None)
        if method in self.POLLER_INLINE:
            self._run_handler(cs, rid, v1, method, req, tctx, pooled=False)
        else:
            # graftlint: allow-lock(poller-owned counter: +1 here and -1 in _complete both run on the poller thread; forensics read is advisory)
            self._queue_depth += 1
            monitor.set_gauge("rpc/worker_queue_depth", self._queue_depth)
            self._pool().submit(self._run_handler, cs, rid, v1, method,
                                req, tctx, pooled=True)

    def _wire_caps(self, cs: _Conn, req: dict) -> dict:
        """The mux negotiation probe (always a v1 frame): record what
        the PEER can receive, answer what WE can. An old client never
        sends this; an old server answers it with an in-band
        AttributeError, which the client treats as 'v1 only'."""
        sg_ok = int(flags.flag("rpc_sg_min_bytes")) >= 0
        shm_ok = bool(flags.flag("rpc_shm"))
        same_host = req.get("host") == _host_id()
        cs.peer_sg = bool(req.get("sg")) and sg_ok
        cs.peer_shm = bool(req.get("shm")) and shm_ok and same_host
        return {"max_version": wire.WIRE_VERSION_MUX, "sg": sg_ok,
                "shm": shm_ok and same_host, "host": _host_id()}

    def _pool(self) -> ThreadPoolExecutor:
        p = self._workers
        if p is None:  # lazily, on the poller thread only
            n = max(1, int(flags.flag("rpc_worker_threads")))
            p = self._workers = ThreadPoolExecutor(
                max_workers=n,
                thread_name_prefix=f"rpc-worker-{self.endpoint}")
        return p

    # -- handler execution (worker pool or inline) -------------------------

    def _run_handler(self, cs: _Conn, rid: int, v1: bool, method: str,
                     req: dict, tctx: Optional[dict], *,
                     pooled: bool) -> None:
        t0 = time.perf_counter()
        try:
            out = self._dispatch(method, req, tctx)
            bufs = self._encode_reply(
                cs, rid, v1,
                {"ok": True, "result": out,
                 # Server share of the caller's observed latency:
                 # total - _server_ms = wire+queue, the per-hop
                 # decomposition every client gets for free.
                 "_server_ms": round(
                     (time.perf_counter() - t0) * 1e3, 3)})
        except Exception as e:  # report in-band, keep serving
            log.vlog(0, "%s %s failed: %s", self.service_name, method, e)
            try:
                bufs = self._encode_reply(
                    cs, rid, v1, {"ok": False, "error": repr(e)})
            except wire.WireError:
                bufs = None  # cannot even frame the error: drop the conn
        if pooled:
            self._post(lambda: self._complete(cs, bufs, v1, pooled=True))
        else:
            self._complete(cs, bufs, v1, pooled=False)

    def _complete(self, cs: _Conn, bufs: Optional[List[Any]], v1: bool,
                  *, pooled: bool) -> None:
        # Poller thread only.
        if pooled:
            self._queue_depth -= 1
            monitor.set_gauge("rpc/worker_queue_depth", self._queue_depth)
        if cs.dead:
            return
        if bufs is None:
            self._drop_conn(cs)
            return
        self._queue_reply(cs, bufs, v1)

    def _queue_reply(self, cs: _Conn, bufs: List[Any], v1: bool) -> None:
        cs.out.extend(bufs)
        self._flush(cs)
        if cs.dead:
            return
        if self._after_reply():
            cs.close_after_flush = True
            if not cs.out:
                self._drop_conn(cs)
                return
        if v1:
            if cs.v1_backlog:
                self._start_request(cs, cs.v1_backlog.popleft(), rid=0,
                                    v1=True)
            else:
                cs.v1_busy = False

    def _encode_reply(self, cs: _Conn, rid: int, v1: bool,
                      resp: dict) -> List[Any]:
        if v1:
            return [wire.pack_frame(resp)]
        nbytes = wire.array_nbytes(resp)
        if (cs.peer_shm
                and nbytes >= int(flags.flag("rpc_shm_min_bytes"))):
            try:
                frame = _pack_shm_frame(resp, rid)
                monitor.add("rpc/shm_frames", 1)
                return [frame]
            except (OSError, wire.WireError):
                pass  # shm pressure: degrade to the socket forms
        sg_min = int(flags.flag("rpc_sg_min_bytes"))
        if cs.peer_sg and sg_min >= 0 and nbytes >= sg_min:
            monitor.add("rpc/sg_frames", 1)
            return wire.sg_frame_buffers(resp, rid)
        return [wire.pack_frame_v2(resp, rid)]

    # -- write side --------------------------------------------------------

    def _flush(self, cs: _Conn) -> None:
        try:
            while cs.out:
                batch = list(itertools.islice(cs.out, 0, 64))
                sent = cs.sock.sendmsg(batch)
                _trim_sent(cs.out, sent)
        except BlockingIOError:
            pass
        except (ConnectionError, OSError):
            self._drop_conn(cs)
            return
        if cs.out and not cs.wreg:
            cs.wreg = True
            self._sel.modify(cs.sock, selectors.EVENT_READ
                             | selectors.EVENT_WRITE, cs)
        elif not cs.out:
            if cs.wreg:
                cs.wreg = False
                self._sel.modify(cs.sock, selectors.EVENT_READ, cs)
            if cs.close_after_flush:
                self._drop_conn(cs)

    def _drop_conn(self, cs: _Conn) -> None:
        if cs.dead:
            return
        cs.dead = True
        cs.out.clear()
        cs.v1_backlog.clear()
        with self._conns_lock:
            self._conns.pop(cs.sock, None)
        try:
            self._sel.unregister(cs.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            cs.sock.close()
        except OSError:
            pass

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, method: str, req: dict, tctx: Optional[dict]):
        """Invoke ``handle_<method>``, under the caller's trace context
        when the request carried one: the handler thread's spans then
        record the caller's trace id, and the ``rpc/<method>`` server
        span's ``parent`` links back to the client span for the merged
        trace's flow arrows. Requests without a context (tracing off at
        the client) dispatch exactly as before."""
        handler = getattr(self, "handle_" + method)
        if not isinstance(tctx, dict):
            return handler(req)
        sctx = trace.server_context(tctx)
        with trace.use_context(sctx), \
                trace.span(f"rpc/{method}", span=sctx["sid"],
                           parent=sctx["parent"],
                           origin=sctx["origin"]):
            return handler(req)

    # -- base handlers every framed service answers ------------------------

    def handle_clock_probe(self, req) -> Dict[str, int]:
        """Wall-clock sample for the client's clock-offset handshake
        (one probe per connect while tracing is on): the client halves
        the RTT to estimate this server's wall offset, which the merge
        tool uses to align per-process trace timelines."""
        # graftlint: allow-replay(clock handshake metadata, never training state)
        return {"wall_ns": time.time_ns()}

    def handle_metrics_snapshot(self, req) -> dict:
        """This process's labeled registry snapshot — the one-scrape
        cluster-telemetry surface (core/telemetry_scrape.py /
        tools/fleet_top.py). Servers with per-instance registries
        (PredictServer, ShardServer, FleetRouter) override this; the
        base answers from the process-global registry so EVERY framed
        service is scrapeable."""
        return monitor.snapshot_all(
            labels={"service": self.service_name,
                    "endpoint": self.endpoint})

    def handle_metrics_history(self, req) -> dict:
        """This process's metric-history ring (core/timeseries.py) —
        the trend surface beside the instantaneous snapshot. Servers
        with per-instance registries override this with their own
        ring; the base answers the process-global one. Empty ring
        (sampler off) is a valid answer — the scrape layer treats it
        as 'no trend yet'."""
        from paddlebox_tpu.core import timeseries
        h = timeseries.history_for(create=False)
        if h is None:
            return {"label": "global", "capacity": 0, "points": []}
        return h.to_dict(window_s=req.get("window_s"),
                         last_n=req.get("last_n"))

    def handle_alerts_active(self, req) -> dict:
        """Active SLO alerts (core/alerts.py) — the machine-readable
        surface ROADMAP item 1's controller consumes. The engine is
        process-global (instance registries mirror their signals into
        it), so one base handler serves every framed service."""
        from paddlebox_tpu.core import alerts
        return {"enabled": alerts.enabled(),
                "firing": alerts.firing_count(),
                "alerts": alerts.active_alerts(
                    include_ok=bool(req.get("include_ok")))}

    def handle_trace_export(self, req) -> dict:
        """Export this process's span ring to ``req['path']`` (or the
        configured FLAGS_trace_path) and return the path — how a drill
        or operator collects per-process trace files for
        ``trace_report --merge`` without waiting for process exit."""
        path = req.get("path") or None
        out = trace.GLOBAL.export(path)
        return {"path": out,
                "events": len(trace.snapshot())}

    def _after_reply(self) -> bool:
        """Post-response hook; return True to end this connection (the
        PS stop RPC uses it to close only after the ack is sent)."""
        return False

    # -- lifecycle ---------------------------------------------------------

    def _poller_stats(self, now: float) -> Dict[str, Any]:
        busy = self._busy_since
        with self._conns_lock:
            nconns = len(self._conns)
        return {"service": self.service_name, "endpoint": self.endpoint,
                "thread": self._poller.name,
                "loop_lag_ms": round((now - busy) * 1e3, 3)
                if busy is not None else 0.0,
                "worker_queue_depth": self._queue_depth,
                "conns": nconns, "running": self._running}

    def stop(self) -> None:
        """Stop accepting. Established connections keep draining until
        their clients close (graceful-stop semantics the PS stop drill
        pins); ``close_connections()`` is the abrupt variant."""
        self._running = False
        srv = self._server
        if srv is not None:
            try:
                # Refuses new connects immediately (synchronously);
                # the poller closes the listener fd on its next tick.
                srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._post(self._close_listener)

    def close_connections(self) -> None:
        """Abruptly sever every established connection (kill-like
        teardown for drills; graceful stops keep draining replies)."""
        with self._conns_lock:
            conns = list(self._conns.values())
        for cs in conns:
            try:
                # Synchronous: peers see EOF/RST now, like a host death.
                cs.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

        def _reap() -> None:
            for cs in conns:
                self._drop_conn(cs)
        self._post(_reap)


# -- client -------------------------------------------------------------------


class _MuxPending:
    __slots__ = ("event", "resp", "err", "token")

    def __init__(self):
        self.event = threading.Event()
        self.resp: Optional[dict] = None
        self.err: Optional[BaseException] = None
        self.token: Optional[int] = None


class _MuxState:
    """Everything tied to ONE negotiated mux socket generation: pending
    table, request-id counter, send lock, reader thread. A socket death
    fails the whole generation at once; the conn then reconnects and
    negotiates a fresh generation."""

    def __init__(self, sock: socket.socket, *, sg: bool, shm: bool):
        self.sock = sock
        self.sg = sg
        self.shm = shm
        self.send_lock = threading.Lock()
        self.pending: Dict[int, _MuxPending] = {}
        self.plock = threading.Lock()
        self.ids = itertools.count(1)
        self.dead = False

    def add(self, rid: int, p: _MuxPending) -> None:
        with self.plock:
            if self.dead:
                raise ConnectionError("mux connection is closed")
            self.pending[rid] = p

    def forget(self, rid: int) -> None:
        with self.plock:
            self.pending.pop(rid, None)

    def resolve(self, rid: int, resp: dict) -> None:
        with self.plock:
            p = self.pending.pop(rid, None)
        if p is None:
            return  # caller gave up (timeout) — late reply, drop
        p.resp = resp
        if p.token is not None:
            _inflight_exit(p.token)
        p.event.set()

    def fail_all(self, exc: BaseException) -> None:
        with self.plock:
            if self.dead:
                ps: List[_MuxPending] = []
            else:
                self.dead = True
                ps = list(self.pending.values())
                self.pending.clear()
        for p in ps:
            p.err = exc
            if p.token is not None:
                _inflight_exit(p.token)
            p.event.set()
        try:
            self.sock.close()
        except OSError:
            pass


_TLS_MISS = object()


class FramedRPCConn:
    """One client connection: multiplexed (v2, N outstanding calls per
    socket, out-of-order replies matched by request id) when the server
    negotiates it, blocking v1 otherwise — with in-band error raising,
    transparent reconnect, and retry-with-backoff for idempotent
    methods.

    A dropped/half-read/desynced stream closes the socket; the NEXT call
    reconnects (a PS restart no longer strands every client forever).
    Methods named in ``idempotent`` (pure reads: pull/stats/predict)
    additionally retry the call itself — reconnect, capped exponential
    backoff, bounded by ``FLAGS_rpc_max_retries`` AND the wall-clock
    ``FLAGS_rpc_retry_deadline_s`` — so a server blip costs latency, not
    the pass. Non-idempotent methods (pushes, applies) never auto-retry:
    the request may have executed before the connection died, and
    re-running it would double-apply."""

    def __init__(self, endpoint: str, *, timeout: float = 60.0,
                 service_name: str = "rpc",
                 idempotent: Iterable[str] = (),
                 resolve: Optional[Callable[[str], str]] = None):
        self.endpoint = endpoint
        self._timeout = timeout
        self._idempotent: FrozenSet[str] = frozenset(idempotent)
        self._lock = threading.Lock()        # serializes v1 call pairs
        self._conn_lock = threading.RLock()  # guards _sock/_mux identity
        self._service = service_name
        # Optional endpoint re-resolver, consulted BEFORE a reconnect:
        # (current endpoint) -> endpoint to connect to. Lets a client
        # whose server moved/died follow a control plane's topology
        # (e.g. the serving fleet router's epoch) instead of retrying a
        # fixed dead address until the deadline burns out. Exceptions
        # from the resolver are the resolver's bug — it should return
        # the current endpoint when it cannot do better.
        self._resolve = resolve
        # Per-hop latency decomposition from the newest completed call,
        # THREAD-LOCAL on top of an instance fallback: a mux connection
        # is shared by concurrent callers (the fleet router's fan-out),
        # and each caller must read its own call's split.
        self._tls = threading.local()
        self._g_server_ms: Optional[float] = None
        self._g_wire_ms: Optional[float] = None
        # Clock-offset handshake result (peer wall - our wall, ms);
        # None until tracing is on during a connect.
        self.clock_offset_ms: Optional[float] = None
        self._mux: Optional[_MuxState] = None
        self._sock: Optional[socket.socket] = None
        self._sock, self._mux = self._connect()

    # -- latency decomposition (thread-local view) -------------------------

    @property
    def last_server_ms(self) -> Optional[float]:
        v = getattr(self._tls, "server_ms", _TLS_MISS)
        return self._g_server_ms if v is _TLS_MISS else v

    @property
    def last_wire_ms(self) -> Optional[float]:
        v = getattr(self._tls, "wire_ms", _TLS_MISS)
        return self._g_wire_ms if v is _TLS_MISS else v

    def _note_latency(self, resp: Any, total_ms: float) -> None:
        server_ms = resp.get("_server_ms") if isinstance(resp, dict) \
            else None
        if isinstance(server_ms, (int, float)):
            s = float(server_ms)
            w = round(max(0.0, total_ms - s), 3)
        else:
            s = w = None
        self._tls.server_ms = s
        self._tls.wire_ms = w
        self._g_server_ms = s
        self._g_wire_ms = w

    # -- connect / negotiate ----------------------------------------------

    def _connect(self):
        host, port = self.endpoint.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self._timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        if trace.enabled():
            self._clock_handshake(sock)
        ms = self._negotiate(sock)
        return sock, ms

    def _negotiate(self, sock: socket.socket) -> Optional[_MuxState]:
        """One v1 ``wire_caps`` probe per connect. A peer that answers
        with an error (an old server has no such handler) pins this
        socket generation to the blocking v1 plane — counted, so a
        mixed-version rollout is visible on one scrape."""
        if not flags.flag("rpc_mux"):
            return None
        want_sg = int(flags.flag("rpc_sg_min_bytes")) >= 0
        want_shm = bool(flags.flag("rpc_shm"))
        sock.sendall(wire.pack_frame(
            {"method": "wire_caps", "max_version": wire.WIRE_VERSION_MUX,
             "sg": want_sg, "shm": want_shm, "host": _host_id()}))
        ln = wire.read_frame_header(_recv_exact(sock, wire.HEADER.size))
        resp = wire.loads(_recv_exact(sock, ln))
        caps = resp.get("result") if isinstance(resp, dict) \
            and resp.get("ok") else None
        if not (isinstance(caps, dict)
                and int(caps.get("max_version", 1))
                >= wire.WIRE_VERSION_MUX):
            monitor.add("rpc/mux_fallbacks", 1)
            log.vlog(1, "%s: peer %s speaks v1 only; mux off for this "
                     "connection", self._service, self.endpoint)
            return None
        ms = _MuxState(
            sock,
            sg=want_sg and bool(caps.get("sg")),
            shm=(want_shm and bool(caps.get("shm"))
                 and caps.get("host") == _host_id()))
        t = threading.Thread(target=self._reader_loop, args=(ms,),
                             daemon=True,
                             name=f"rpc-mux-reader-{self.endpoint}")
        t.start()
        return ms

    def _clock_handshake(self, sock: socket.socket) -> None:
        """One wall-clock probe per connect (tracing on only): the
        peer's wall at the RTT midpoint vs ours estimates the clock
        offset the trace merge aligns per-process timelines with.
        Best-effort — a peer that cannot answer costs nothing."""
        try:
            # graftlint: allow-replay(telemetry clock metadata, gated on tracing)
            t0_wall = time.time_ns()
            t0 = time.perf_counter_ns()
            sock.sendall(wire.pack_frame({"method": "clock_probe"}))
            ln = wire.read_frame_header(
                _recv_exact(sock, wire.HEADER.size))
            resp = wire.loads(_recv_exact(sock, ln))
            rtt_ns = time.perf_counter_ns() - t0
            if not (isinstance(resp, dict) and resp.get("ok")):
                return
            peer_wall = int(resp["result"]["wall_ns"])
            offset_ms = (peer_wall - (t0_wall + rtt_ns // 2)) / 1e6
            self.clock_offset_ms = round(offset_ms, 3)
            trace.note_peer_offset(self.endpoint, offset_ms,
                                   rtt_ms=rtt_ns / 1e6)
            monitor.set_gauge("rpc/clock_offset_ms", round(offset_ms, 3))
        except (OSError, ConnectionError, wire.WireError, KeyError,
                TypeError, ValueError):
            return

    def _ensure_connected(self, method: str):
        """(sock, mux-or-None), reconnecting — resolve= first — when the
        previous generation died."""
        with self._conn_lock:
            if self._sock is None:
                if self._resolve is not None:
                    ep = self._resolve(self.endpoint)
                    if ep and ep != self.endpoint:
                        monitor.add("rpc/reresolves", 1)
                        log.vlog(0, "%s: endpoint re-resolved %s -> %s",
                                 self._service, self.endpoint, ep)
                        self.endpoint = ep
                self._sock, self._mux = self._connect()
                monitor.add("rpc/reconnects", 1)
                monitor.add(f"rpc/reconnects/{method}", 1)
            return self._sock, self._mux

    def _forget(self, sock: Optional[socket.socket],
                ms: Optional[_MuxState]) -> None:
        """Retire one socket generation (if still current)."""
        with self._conn_lock:
            if self._sock is sock or (ms is not None and self._mux is ms):
                self._sock = None
                self._mux = None
        if ms is not None:
            ms.fail_all(ConnectionError("mux connection closed"))
        elif sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- mux reader --------------------------------------------------------

    def _reader_loop(self, ms: _MuxState) -> None:
        sock = ms.sock
        try:
            while True:
                hdr = self._recv_frame_hdr(sock)
                ver, fl, ln = wire.read_any_header(hdr)
                if ver != wire.WIRE_VERSION_MUX:
                    raise wire.WireError(
                        "v1 frame on a negotiated mux connection")
                if fl & (wire.FLAG_SG | wire.FLAG_SHM):
                    faults.faultpoint("rpc/sg_recv")
                buf = bytearray(ln)
                _recv_into_exact(sock, memoryview(buf))
                rid, resp = _decode_v2_payload(fl, buf)
                ms.resolve(rid, resp)
        except BaseException as e:
            # Fail every waiter now, but leave the dead generation in
            # place: the NEXT call trips on it, counts a retry, and
            # reconnects — the blocking plane's call-time failure
            # detection, which the drill suites pin.
            ms.fail_all(e if isinstance(e, Exception)
                        else ConnectionError(repr(e)))

    @staticmethod
    def _recv_frame_hdr(sock: socket.socket) -> bytes:
        """Header read tolerating IDLE socket timeouts: between frames a
        quiet connection is healthy (a slow server is the CALLER's
        timeout to enforce); a timeout mid-header means a wedged peer
        and propagates."""
        buf = bytearray()
        while len(buf) < wire.HEADER.size:
            try:
                part = sock.recv(wire.HEADER.size - len(buf))
            except socket.timeout:
                if buf:
                    raise
                continue
            if not part:
                raise ConnectionError("peer closed")
            buf += part
        return bytes(buf)

    # -- send/encode -------------------------------------------------------

    def _mux_send(self, ms: _MuxState, obj: dict, rid: int) -> None:
        if ms.dead:
            raise ConnectionError("mux connection is closed")
        nbytes = wire.array_nbytes(obj)
        if ms.shm and nbytes >= int(flags.flag("rpc_shm_min_bytes")):
            try:
                frame = _pack_shm_frame(obj, rid)
                monitor.add("rpc/shm_frames", 1)
                with ms.send_lock:
                    ms.sock.sendall(frame)
                return
            except (wire.WireError, FileExistsError, MemoryError):
                pass  # shm pressure: degrade to the socket forms
        sg_min = int(flags.flag("rpc_sg_min_bytes"))
        if ms.sg and sg_min >= 0 and nbytes >= sg_min:
            bufs = wire.sg_frame_buffers(obj, rid)
            monitor.add("rpc/sg_frames", 1)
            with ms.send_lock:
                _sendmsg_all(ms.sock, bufs)
            return
        data = wire.pack_frame_v2(obj, rid)
        with ms.send_lock:
            ms.sock.sendall(data)

    # -- the call paths ----------------------------------------------------

    def _call_once(self, method: str, kw) -> dict:
        faults.faultpoint("rpc/call")
        sock, ms = self._ensure_connected(method)
        if ms is not None:
            return self._mux_call_once(ms, method, kw)
        with self._lock:
            s = self._sock
            if s is None or s is not sock:
                raise ConnectionError("connection closed concurrently")
            tctx = kw.get("_trace")
            sp = (trace.span(f"rpc/client/{method}", trace=tctx["tid"],
                             span=tctx["sid"], peer=self.endpoint)
                  if tctx is not None else trace.NULL_SPAN)
            token = _inflight_enter(self.endpoint, method, self._service)
            try:
                with sp:
                    s.sendall(wire.pack_frame({"method": method, **kw}))
                    ln = wire.read_frame_header(
                        _recv_exact(s, wire.HEADER.size))
                    return wire.loads(_recv_exact(s, ln))
            except (OSError, ConnectionError, wire.WireError):
                # A timed-out / half-read / desynced stream cannot be
                # reused — drop it so the next attempt reconnects.
                self._forget(sock, None)
                raise
            finally:
                _inflight_exit(token)

    def _mux_call_once(self, ms: _MuxState, method: str, kw) -> dict:
        rid = next(ms.ids)
        p = _MuxPending()
        p.token = _inflight_enter(self.endpoint, method, self._service)
        tctx = kw.get("_trace")
        sp = (trace.span(f"rpc/client/{method}", trace=tctx["tid"],
                         span=tctx["sid"], peer=self.endpoint)
              if tctx is not None else trace.NULL_SPAN)
        try:
            with sp:
                ms.add(rid, p)
                self._mux_send(ms, {"method": method, **kw}, rid)
                if not p.event.wait(self._timeout):
                    raise socket.timeout(
                        f"rpc {method} to {self.endpoint}: no reply in "
                        f"{self._timeout}s")
                if p.err is not None:
                    raise self._translate(p.err)
                return p.resp
        except (OSError, ConnectionError, wire.WireError):
            # Conservative, like the blocking plane: a timeout or stream
            # error poisons the whole generation (replies can no longer
            # be trusted to match), so every sibling call fails fast and
            # the next call reconnects.
            self._forget(ms.sock, ms)
            raise
        finally:
            ms.forget(rid)
            if not p.event.is_set():
                _inflight_exit(p.token)

    @staticmethod
    def _translate(err: BaseException) -> Exception:
        if isinstance(err, (OSError, wire.WireError)):
            return err
        return ConnectionError(repr(err))

    def call(self, method: str, **kw):
        retries = (max(0, int(flags.flag("rpc_max_retries")))
                   if method in self._idempotent else 0)
        deadline = time.monotonic() + float(
            flags.flag("rpc_retry_deadline_s"))
        tctx = trace.wire_context()
        if tctx is not None:
            kw["_trace"] = tctx
        t_call = time.perf_counter()
        attempt = 0
        while True:
            try:
                resp = self._call_once(method, kw)
                break
            except (OSError, ConnectionError, wire.WireError) as e:
                if attempt >= retries or time.monotonic() >= deadline:
                    raise
                attempt += 1
                monitor.add("rpc/retries", 1)
                monitor.add(f"rpc/retries/{method}", 1)
                log.warning(
                    "%s.%s: connection error %r — reconnect+retry "
                    "%d/%d", self._service, method, e, attempt,
                    retries)
                time.sleep(min(
                    float(flags.flag("rpc_retry_backoff_s"))
                    * (2.0 ** (attempt - 1)), 2.0))
        self._note_latency(resp, (time.perf_counter() - t_call) * 1e3)
        if not resp["ok"]:
            raise RuntimeError(
                f"{self._service}.{method}: {resp['error']}")
        return resp["result"]

    def call_async(self, method: str, **kw) -> "RPCFuture":
        """Start a call WITHOUT waiting: returns an :class:`RPCFuture`
        whose ``.result()`` yields what ``call`` would have returned.
        On a mux connection this is true pipelining — the request is on
        the wire now and the caller's thread is free to issue more; the
        fan-out tiers (router, replication forwarding, boundary
        exchange) stop paying one RTT per sequential call. On a v1
        connection it degrades to a helper thread running ``call`` (same
        contract, same retry semantics)."""
        tctx = trace.wire_context()
        if tctx is not None:
            kw["_trace"] = tctx
        ms = None
        try:
            _, ms = self._ensure_connected(method)
        except (OSError, ConnectionError, wire.WireError):
            pass  # the fallback path below owns reconnect+retry
        if ms is not None:
            rid = next(ms.ids)
            p = _MuxPending()
            p.token = _inflight_enter(self.endpoint, method,
                                      self._service)
            try:
                ms.add(rid, p)
                self._mux_send(ms, {"method": method, **kw}, rid)
                return _MuxFuture(self, ms, rid, p, method, kw,
                                  time.perf_counter())
            except (OSError, ConnectionError, wire.WireError):
                # Send failed -> the frame never fully left, so the
                # request did not execute: safe to fall back to the
                # sync path even for non-idempotent methods.
                ms.forget(rid)
                if not p.event.is_set():
                    _inflight_exit(p.token)
                self._forget(ms.sock, ms)
        return _ThreadFuture(self, method, kw)

    def close(self) -> None:
        with self._conn_lock:
            sock, ms = self._sock, self._mux
            self._sock = None
            self._mux = None
        if ms is not None:
            ms.fail_all(ConnectionError("connection closed"))
        elif sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class RPCFuture:
    """Handle for one in-flight ``call_async``; ``result()`` blocks."""

    def result(self, timeout: Optional[float] = None):
        raise NotImplementedError


class _MuxFuture(RPCFuture):
    __slots__ = ("_conn", "_ms", "_rid", "_p", "_method", "_kw", "_t0")

    def __init__(self, conn: FramedRPCConn, ms: _MuxState, rid: int,
                 p: _MuxPending, method: str, kw: dict, t0: float):
        self._conn = conn
        self._ms = ms
        self._rid = rid
        self._p = p
        self._method = method
        self._kw = kw
        self._t0 = t0

    def result(self, timeout: Optional[float] = None):
        c = self._conn
        p = self._p
        # The pipelined call still contributes its ``rpc/client/<m>``
        # span to the merged trace (the sync paths emit it around the
        # send; here the visible client-side wait is the result() call).
        tctx = self._kw.get("_trace")
        sp = (trace.span(f"rpc/client/{self._method}",
                         trace=tctx["tid"], span=tctx["sid"],
                         peer=c.endpoint)
              if isinstance(tctx, dict) else trace.NULL_SPAN)
        with sp:
            return self._result(timeout)

    def _result(self, timeout: Optional[float]):
        c = self._conn
        p = self._p
        tmo = c._timeout if timeout is None else timeout
        if not p.event.wait(tmo):
            # Same conservative poisoning as the sync mux path.
            self._ms.forget(self._rid)
            _inflight_exit(p.token)
            c._forget(self._ms.sock, self._ms)
            p.err = p.err or socket.timeout(
                f"rpc {self._method} to {c.endpoint}: no reply in {tmo}s")
        if p.err is not None:
            if self._method in c._idempotent:
                # The reply was lost but the method is a pure read:
                # re-issue through the sync path's full retry/resolve
                # machinery.
                kw = dict(self._kw)
                kw.pop("_trace", None)
                return c.call(self._method, **kw)
            raise c._translate(p.err)
        resp = p.resp
        c._note_latency(resp, (time.perf_counter() - self._t0) * 1e3)
        if not resp["ok"]:
            raise RuntimeError(
                f"{c._service}.{self._method}: {resp['error']}")
        return resp["result"]


class _ThreadFuture(RPCFuture):
    """v1 fallback: one helper thread runs the blocking ``call`` (the
    fan-out tiers used to spawn exactly this thread themselves)."""

    def __init__(self, conn: FramedRPCConn, method: str, kw: dict):
        self._out: Any = None
        self._exc: Optional[BaseException] = None
        self._method = method
        self._conn = conn

        def _run() -> None:
            try:
                # graftlint: allow-lock(Thread.join in result() orders these writes before the read)
                self._out = conn.call(method, **kw)
            except BaseException as e:
                # graftlint: allow-lock(Thread.join in result() orders these writes before the read)
                self._exc = e

        self._t = threading.Thread(
            target=_run, daemon=True,
            name=f"rpc-async-{method}-{conn.endpoint}")
        self._t.start()

    def result(self, timeout: Optional[float] = None):
        self._t.join(self._conn._timeout if timeout is None else timeout)
        if self._t.is_alive():
            raise socket.timeout(
                f"rpc {self._method} to {self._conn.endpoint}: "
                f"no reply")
        if self._exc is not None:
            raise self._exc
        return self._out
