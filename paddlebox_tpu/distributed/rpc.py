"""Shared framed-RPC skeleton for the wire-protocol services.

The PS (``distributed/ps.py``), graph (``graph/service.py``), and
serving (``serving/service.py``) services all speak the same
length-prefixed typed-frame protocol (``distributed/wire.py``) with the
same loop shape: accept → per-connection thread → dispatch
``handle_<method>`` → ``{ok, result|error}`` reply. This base collects
that loop ONCE so protocol hardening (malformed-payload handling, frame
errors, shutdown semantics) cannot drift between services — the role of
brpc's common service plumbing under the reference's PS/graph stubs
(``sendrecv.proto`` services share one server loop there too).

Robustness contract of the loop:
- a payload that is not a ``{"method": str, ...}`` dict gets an error
  REPLY (not a dropped connection — a malformed request must not strand
  the client until its socket timeout);
- handler exceptions are reported in-band and the connection keeps
  serving;
- wire-protocol violations drop the connection (a corrupt
  length-prefixed stream cannot be resynchronized);
- ``_after_reply()`` hooks post-response actions (the PS ``stop`` RPC
  closes its listener only AFTER the acknowledgement is on the wire).

Distributed tracing (OBSERVABILITY.md "Distributed tracing"): when the
CLIENT process has tracing on, every request dict carries a compact
``_trace`` context (``{tid, sid, origin}``) that the server loop pops,
installs thread-locally for the handler's duration, and records as a
``rpc/<method>`` server span whose ``parent`` is the client's span id —
so one predict's trace id follows it through router → replica → shard
hops, and ``tools/trace_report.py --merge`` can draw the cross-process
flow arrows. Every reply also carries ``_server_ms`` (handler wall),
letting any client decompose its observed latency into server vs wire
share without a second RPC. With tracing off the client attaches
nothing and the per-call cost is one cached-bool check.

Two always-on observability surfaces (RPCs are not the jitted hot
loop): the module-level IN-FLIGHT CALL TABLE (``inflight_table()`` —
peer endpoint, method, age; registered as a ``trace.stall_forensics``
provider so a watchdog stall names the remote it is stuck on), and
per-method reconnect/retry counters (``rpc/reconnects/<method>``,
``rpc/retries/<method>`` beside the long-standing totals) so a
failover drill can assert exactly which method consumed the retry
budget.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Any, Callable, Dict, FrozenSet, Iterable, List, Optional

from paddlebox_tpu.core import faults, flags, log, monitor, trace
from paddlebox_tpu.distributed import wire
from paddlebox_tpu.distributed.transport import _recv_exact

# -- in-flight RPC table ------------------------------------------------------

_INFLIGHT: Dict[int, Dict[str, Any]] = {}
_INFLIGHT_LOCK = threading.Lock()
_INFLIGHT_IDS = itertools.count(1)


def _inflight_enter(endpoint: str, method: str, service: str) -> int:
    token = next(_INFLIGHT_IDS)
    with _INFLIGHT_LOCK:
        _INFLIGHT[token] = {"endpoint": endpoint, "method": method,
                            "service": service, "t0": time.monotonic()}
    return token


def _inflight_exit(token: int) -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT.pop(token, None)


def inflight_table() -> List[Dict[str, Any]]:
    """Every RPC currently blocked on a peer: endpoint, method, service,
    age. The watchdog's stall forensics include this (oldest first), so
    a hang past FLAGS_stall_timeout_s names the remote, not just the
    local thread stacks."""
    now = time.monotonic()
    with _INFLIGHT_LOCK:
        entries = list(_INFLIGHT.values())
    out = [{"endpoint": e["endpoint"], "method": e["method"],
            "service": e["service"], "age_s": round(now - e["t0"], 3)}
           for e in entries]
    out.sort(key=lambda e: -e["age_s"])
    return out


trace.register_forensics_provider("inflight_rpcs", inflight_table)


class FramedRPCServer:
    """Socket server dispatching typed frames to ``handle_<method>``."""

    # Subclasses set this for log attribution ("ps[3]", "graph[0]", ...).
    service_name: str = "rpc"

    def __init__(self, endpoint: str, *, backlog: int = 32):
        host, port = endpoint.rsplit(":", 1)
        self._server = socket.create_server((host, int(port)),
                                            backlog=backlog)
        self.endpoint = f"{host}:{self._server.getsockname()[1]}"
        self._running = True
        # Live accepted sockets: close_connections() lets an in-process
        # "host death" (tests/drills) sever established conns the way a
        # SIGKILL would — stop() alone only closes the LISTENER, and a
        # persistent client conn would otherwise get one more reply
        # from the "dead" host.
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def close_connections(self) -> None:
        """Abruptly sever every established connection (kill-like
        teardown for drills; graceful stops keep draining replies)."""
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _serve(self, conn: socket.socket) -> None:
        try:
            self._serve_inner(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _serve_inner(self, conn: socket.socket) -> None:
        try:
            with conn:
                while True:
                    ln = wire.read_frame_header(
                        _recv_exact(conn, wire.HEADER.size))
                    req = wire.loads(_recv_exact(conn, ln))
                    method = (req.get("method")
                              if isinstance(req, dict) else None)
                    if not isinstance(method, str):
                        conn.sendall(wire.pack_frame(
                            {"ok": False,
                             "error": "request must be a dict with a "
                                      "str 'method'"}))
                        continue
                    tctx = req.pop("_trace", None)
                    t0 = time.perf_counter()
                    try:
                        out = self._dispatch(method, req, tctx)
                        conn.sendall(wire.pack_frame(
                            {"ok": True, "result": out,
                             # Server share of the caller's observed
                             # latency: total - _server_ms = wire+queue,
                             # the per-hop decomposition every client
                             # gets for free.
                             "_server_ms": round(
                                 (time.perf_counter() - t0) * 1e3, 3)}))
                    except Exception as e:  # report in-band, keep serving
                        log.vlog(0, "%s %s failed: %s", self.service_name,
                                 method, e)
                        conn.sendall(wire.pack_frame(
                            {"ok": False, "error": repr(e)}))
                    if self._after_reply():
                        return
        except wire.WireError as e:
            # Protocol violation (malformed/mismatched frame): drop the
            # connection — resynchronizing a corrupt byte stream is not
            # possible with length-prefixed framing.
            log.warning("%s: dropping connection on wire error: %s",
                        self.service_name, e)
            return
        except (ConnectionError, OSError, EOFError):
            return

    def _dispatch(self, method: str, req: dict, tctx: Optional[dict]):
        """Invoke ``handle_<method>``, under the caller's trace context
        when the request carried one: the handler thread's spans then
        record the caller's trace id, and the ``rpc/<method>`` server
        span's ``parent`` links back to the client span for the merged
        trace's flow arrows. Requests without a context (tracing off at
        the client) dispatch exactly as before."""
        handler = getattr(self, "handle_" + method)
        if not isinstance(tctx, dict):
            return handler(req)
        sctx = trace.server_context(tctx)
        with trace.use_context(sctx), \
                trace.span(f"rpc/{method}", span=sctx["sid"],
                           parent=sctx["parent"],
                           origin=sctx["origin"]):
            return handler(req)

    # -- base handlers every framed service answers ------------------------

    def handle_clock_probe(self, req) -> Dict[str, int]:
        """Wall-clock sample for the client's clock-offset handshake
        (one probe per connect while tracing is on): the client halves
        the RTT to estimate this server's wall offset, which the merge
        tool uses to align per-process trace timelines."""
        # graftlint: allow-replay(clock handshake metadata, never training state)
        return {"wall_ns": time.time_ns()}

    def handle_metrics_snapshot(self, req) -> dict:
        """This process's labeled registry snapshot — the one-scrape
        cluster-telemetry surface (core/telemetry_scrape.py /
        tools/fleet_top.py). Servers with per-instance registries
        (PredictServer, ShardServer, FleetRouter) override this; the
        base answers from the process-global registry so EVERY framed
        service is scrapeable."""
        return monitor.snapshot_all(
            labels={"service": self.service_name,
                    "endpoint": self.endpoint})

    def handle_trace_export(self, req) -> dict:
        """Export this process's span ring to ``req['path']`` (or the
        configured FLAGS_trace_path) and return the path — how a drill
        or operator collects per-process trace files for
        ``trace_report --merge`` without waiting for process exit."""
        path = req.get("path") or None
        out = trace.GLOBAL.export(path)
        return {"path": out,
                "events": len(trace.snapshot())}

    def _after_reply(self) -> bool:
        """Post-response hook; return True to end this connection (the
        PS stop RPC uses it to close only after the ack is sent)."""
        return False

    def stop(self) -> None:
        self._running = False
        try:
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._server.close()
        except OSError:
            pass


class FramedRPCConn:
    """One blocking client connection with in-band error raising,
    transparent reconnect, and retry-with-backoff for idempotent methods.

    A dropped/half-read/desynced stream closes the socket; the NEXT call
    reconnects (a PS restart no longer strands every client forever).
    Methods named in ``idempotent`` (pure reads: pull/stats/predict)
    additionally retry the call itself — reconnect, capped exponential
    backoff, bounded by ``FLAGS_rpc_max_retries`` AND the wall-clock
    ``FLAGS_rpc_retry_deadline_s`` — so a server blip costs latency, not
    the pass. Non-idempotent methods (pushes, applies) never auto-retry:
    the request may have executed before the connection died, and
    re-running it would double-apply."""

    def __init__(self, endpoint: str, *, timeout: float = 60.0,
                 service_name: str = "rpc",
                 idempotent: Iterable[str] = (),
                 resolve: Optional[Callable[[str], str]] = None):
        self.endpoint = endpoint
        self._timeout = timeout
        self._idempotent: FrozenSet[str] = frozenset(idempotent)
        self._lock = threading.Lock()
        self._service = service_name
        # Optional endpoint re-resolver, consulted BEFORE a reconnect:
        # (current endpoint) -> endpoint to connect to. Lets a client
        # whose server moved/died follow a control plane's topology
        # (e.g. the serving fleet router's epoch) instead of retrying a
        # fixed dead address until the deadline burns out. Exceptions
        # from the resolver are the resolver's bug — it should return
        # the current endpoint when it cannot do better.
        self._resolve = resolve
        # Per-hop latency decomposition from the newest completed call:
        # the reply's _server_ms (handler wall on the peer) and the
        # client-observed remainder (wire + peer queue). Read under the
        # conn lock by callers that just completed a call (the fleet
        # router's hop metrics).
        self.last_server_ms: Optional[float] = None
        self.last_wire_ms: Optional[float] = None
        # Clock-offset handshake result (peer wall - our wall, ms);
        # None until tracing is on during a connect.
        self.clock_offset_ms: Optional[float] = None
        self._sock: Optional[socket.socket] = self._connect()

    def _connect(self) -> socket.socket:
        host, port = self.endpoint.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self._timeout)
        if trace.enabled():
            self._clock_handshake(sock)
        return sock

    def _clock_handshake(self, sock: socket.socket) -> None:
        """One wall-clock probe per connect (tracing on only): the
        peer's wall at the RTT midpoint vs ours estimates the clock
        offset the trace merge aligns per-process timelines with.
        Best-effort — a peer that cannot answer costs nothing."""
        try:
            # graftlint: allow-replay(telemetry clock metadata, gated on tracing)
            t0_wall = time.time_ns()
            t0 = time.perf_counter_ns()
            sock.sendall(wire.pack_frame({"method": "clock_probe"}))
            ln = wire.read_frame_header(
                _recv_exact(sock, wire.HEADER.size))
            resp = wire.loads(_recv_exact(sock, ln))
            rtt_ns = time.perf_counter_ns() - t0
            if not (isinstance(resp, dict) and resp.get("ok")):
                return
            peer_wall = int(resp["result"]["wall_ns"])
            offset_ms = (peer_wall - (t0_wall + rtt_ns // 2)) / 1e6
            self.clock_offset_ms = round(offset_ms, 3)
            trace.note_peer_offset(self.endpoint, offset_ms,
                                   rtt_ms=rtt_ns / 1e6)
            monitor.set_gauge("rpc/clock_offset_ms", round(offset_ms, 3))
        except (OSError, ConnectionError, wire.WireError, KeyError,
                TypeError, ValueError):
            return

    def _call_once(self, method: str, kw) -> dict:
        faults.faultpoint("rpc/call")
        if self._sock is None:  # reconnect after a previous failure
            if self._resolve is not None:
                ep = self._resolve(self.endpoint)
                if ep and ep != self.endpoint:
                    monitor.add("rpc/reresolves", 1)
                    log.vlog(0, "%s: endpoint re-resolved %s -> %s",
                             self._service, self.endpoint, ep)
                    self.endpoint = ep
            self._sock = self._connect()
            monitor.add("rpc/reconnects", 1)
            monitor.add(f"rpc/reconnects/{method}", 1)
        s = self._sock
        tctx = kw.get("_trace")
        sp = (trace.span(f"rpc/client/{method}", trace=tctx["tid"],
                         span=tctx["sid"], peer=self.endpoint)
              if tctx is not None else trace.NULL_SPAN)
        token = _inflight_enter(self.endpoint, method, self._service)
        try:
            with sp:
                s.sendall(wire.pack_frame({"method": method, **kw}))
                ln = wire.read_frame_header(
                    _recv_exact(s, wire.HEADER.size))
                return wire.loads(_recv_exact(s, ln))
        except (OSError, ConnectionError, wire.WireError):
            # A timed-out / half-read / desynced stream cannot be
            # reused — drop it so the next attempt reconnects cleanly.
            self.close()
            raise
        finally:
            _inflight_exit(token)

    def call(self, method: str, **kw):
        retries = (max(0, int(flags.flag("rpc_max_retries")))
                   if method in self._idempotent else 0)
        deadline = time.monotonic() + float(
            flags.flag("rpc_retry_deadline_s"))
        tctx = trace.wire_context()
        if tctx is not None:
            kw["_trace"] = tctx
        with self._lock:
            t_call = time.perf_counter()
            attempt = 0
            while True:
                try:
                    resp = self._call_once(method, kw)
                    break
                except (OSError, ConnectionError, wire.WireError) as e:
                    if attempt >= retries or time.monotonic() >= deadline:
                        raise
                    attempt += 1
                    monitor.add("rpc/retries", 1)
                    monitor.add(f"rpc/retries/{method}", 1)
                    log.warning(
                        "%s.%s: connection error %r — reconnect+retry "
                        "%d/%d", self._service, method, e, attempt,
                        retries)
                    time.sleep(min(
                        float(flags.flag("rpc_retry_backoff_s"))
                        * (2.0 ** (attempt - 1)), 2.0))
            total_ms = (time.perf_counter() - t_call) * 1e3
            server_ms = resp.get("_server_ms") if isinstance(resp, dict) \
                else None
            if isinstance(server_ms, (int, float)):
                self.last_server_ms = float(server_ms)
                self.last_wire_ms = round(
                    max(0.0, total_ms - float(server_ms)), 3)
            else:
                self.last_server_ms = None
                self.last_wire_ms = None
        if not resp["ok"]:
            raise RuntimeError(
                f"{self._service}.{method}: {resp['error']}")
        return resp["result"]

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
