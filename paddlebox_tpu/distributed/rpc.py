"""Shared framed-RPC skeleton for the wire-protocol services.

The PS (``distributed/ps.py``), graph (``graph/service.py``), and
serving (``serving/service.py``) services all speak the same
length-prefixed typed-frame protocol (``distributed/wire.py``) with the
same loop shape: accept → per-connection thread → dispatch
``handle_<method>`` → ``{ok, result|error}`` reply. This base collects
that loop ONCE so protocol hardening (malformed-payload handling, frame
errors, shutdown semantics) cannot drift between services — the role of
brpc's common service plumbing under the reference's PS/graph stubs
(``sendrecv.proto`` services share one server loop there too).

Robustness contract of the loop:
- a payload that is not a ``{"method": str, ...}`` dict gets an error
  REPLY (not a dropped connection — a malformed request must not strand
  the client until its socket timeout);
- handler exceptions are reported in-band and the connection keeps
  serving;
- wire-protocol violations drop the connection (a corrupt
  length-prefixed stream cannot be resynchronized);
- ``_after_reply()`` hooks post-response actions (the PS ``stop`` RPC
  closes its listener only AFTER the acknowledgement is on the wire).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Callable, FrozenSet, Iterable, Optional

from paddlebox_tpu.core import faults, flags, log, monitor
from paddlebox_tpu.distributed import wire
from paddlebox_tpu.distributed.transport import _recv_exact


class FramedRPCServer:
    """Socket server dispatching typed frames to ``handle_<method>``."""

    # Subclasses set this for log attribution ("ps[3]", "graph[0]", ...).
    service_name: str = "rpc"

    def __init__(self, endpoint: str, *, backlog: int = 32):
        host, port = endpoint.rsplit(":", 1)
        self._server = socket.create_server((host, int(port)),
                                            backlog=backlog)
        self.endpoint = f"{host}:{self._server.getsockname()[1]}"
        self._running = True
        # Live accepted sockets: close_connections() lets an in-process
        # "host death" (tests/drills) sever established conns the way a
        # SIGKILL would — stop() alone only closes the LISTENER, and a
        # persistent client conn would otherwise get one more reply
        # from the "dead" host.
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def close_connections(self) -> None:
        """Abruptly sever every established connection (kill-like
        teardown for drills; graceful stops keep draining replies)."""
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _serve(self, conn: socket.socket) -> None:
        try:
            self._serve_inner(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _serve_inner(self, conn: socket.socket) -> None:
        try:
            with conn:
                while True:
                    ln = wire.read_frame_header(
                        _recv_exact(conn, wire.HEADER.size))
                    req = wire.loads(_recv_exact(conn, ln))
                    method = (req.get("method")
                              if isinstance(req, dict) else None)
                    if not isinstance(method, str):
                        conn.sendall(wire.pack_frame(
                            {"ok": False,
                             "error": "request must be a dict with a "
                                      "str 'method'"}))
                        continue
                    try:
                        out = getattr(self, "handle_" + method)(req)
                        conn.sendall(wire.pack_frame(
                            {"ok": True, "result": out}))
                    except Exception as e:  # report in-band, keep serving
                        log.vlog(0, "%s %s failed: %s", self.service_name,
                                 method, e)
                        conn.sendall(wire.pack_frame(
                            {"ok": False, "error": repr(e)}))
                    if self._after_reply():
                        return
        except wire.WireError as e:
            # Protocol violation (malformed/mismatched frame): drop the
            # connection — resynchronizing a corrupt byte stream is not
            # possible with length-prefixed framing.
            log.warning("%s: dropping connection on wire error: %s",
                        self.service_name, e)
            return
        except (ConnectionError, OSError, EOFError):
            return

    def _after_reply(self) -> bool:
        """Post-response hook; return True to end this connection (the
        PS stop RPC uses it to close only after the ack is sent)."""
        return False

    def stop(self) -> None:
        self._running = False
        try:
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._server.close()
        except OSError:
            pass


class FramedRPCConn:
    """One blocking client connection with in-band error raising,
    transparent reconnect, and retry-with-backoff for idempotent methods.

    A dropped/half-read/desynced stream closes the socket; the NEXT call
    reconnects (a PS restart no longer strands every client forever).
    Methods named in ``idempotent`` (pure reads: pull/stats/predict)
    additionally retry the call itself — reconnect, capped exponential
    backoff, bounded by ``FLAGS_rpc_max_retries`` AND the wall-clock
    ``FLAGS_rpc_retry_deadline_s`` — so a server blip costs latency, not
    the pass. Non-idempotent methods (pushes, applies) never auto-retry:
    the request may have executed before the connection died, and
    re-running it would double-apply."""

    def __init__(self, endpoint: str, *, timeout: float = 60.0,
                 service_name: str = "rpc",
                 idempotent: Iterable[str] = (),
                 resolve: Optional[Callable[[str], str]] = None):
        self.endpoint = endpoint
        self._timeout = timeout
        self._idempotent: FrozenSet[str] = frozenset(idempotent)
        self._lock = threading.Lock()
        self._service = service_name
        # Optional endpoint re-resolver, consulted BEFORE a reconnect:
        # (current endpoint) -> endpoint to connect to. Lets a client
        # whose server moved/died follow a control plane's topology
        # (e.g. the serving fleet router's epoch) instead of retrying a
        # fixed dead address until the deadline burns out. Exceptions
        # from the resolver are the resolver's bug — it should return
        # the current endpoint when it cannot do better.
        self._resolve = resolve
        self._sock: Optional[socket.socket] = self._connect()

    def _connect(self) -> socket.socket:
        host, port = self.endpoint.rsplit(":", 1)
        return socket.create_connection((host, int(port)),
                                        timeout=self._timeout)

    def _call_once(self, method: str, kw) -> dict:
        faults.faultpoint("rpc/call")
        if self._sock is None:  # reconnect after a previous failure
            if self._resolve is not None:
                ep = self._resolve(self.endpoint)
                if ep and ep != self.endpoint:
                    monitor.add("rpc/reresolves", 1)
                    log.vlog(0, "%s: endpoint re-resolved %s -> %s",
                             self._service, self.endpoint, ep)
                    self.endpoint = ep
            self._sock = self._connect()
            monitor.add("rpc/reconnects", 1)
        s = self._sock
        try:
            s.sendall(wire.pack_frame({"method": method, **kw}))
            ln = wire.read_frame_header(
                _recv_exact(s, wire.HEADER.size))
            return wire.loads(_recv_exact(s, ln))
        except (OSError, ConnectionError, wire.WireError):
            # A timed-out / half-read / desynced stream cannot be
            # reused — drop it so the next attempt reconnects cleanly.
            self.close()
            raise

    def call(self, method: str, **kw):
        retries = (max(0, int(flags.flag("rpc_max_retries")))
                   if method in self._idempotent else 0)
        deadline = time.monotonic() + float(
            flags.flag("rpc_retry_deadline_s"))
        with self._lock:
            attempt = 0
            while True:
                try:
                    resp = self._call_once(method, kw)
                    break
                except (OSError, ConnectionError, wire.WireError) as e:
                    if attempt >= retries or time.monotonic() >= deadline:
                        raise
                    attempt += 1
                    monitor.add("rpc/retries", 1)
                    log.warning(
                        "%s.%s: connection error %r — reconnect+retry "
                        "%d/%d", self._service, method, e, attempt,
                        retries)
                    time.sleep(min(
                        float(flags.flag("rpc_retry_backoff_s"))
                        * (2.0 ** (attempt - 1)), 2.0))
        if not resp["ok"]:
            raise RuntimeError(
                f"{self._service}.{method}: {resp['error']}")
        return resp["result"]

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
