"""Distributed runtime: multi-host bootstrap, transport, launch, elastic.

Roles (SURVEY.md §2.6/§5):
- ``launch``: per-host process spawner + env wiring — role of
  ``python -m paddle.distributed.launch`` (``launch/main.py:18``,
  ``controllers/collective.py``)
- ``bootstrap``: cluster init — role of NCCL id exchange /
  ``c_gen_nccl_id`` + Gloo HdfsStore rendezvous; on TPU this is
  ``jax.distributed.initialize`` (coordinator + ICI/DCN discovery)
- ``transport``: host-side control-plane RPC — role of brpc/MPI for
  dataset shuffle and PS build traffic (the device data plane is XLA
  collectives and never touches this)
- ``elastic``: failure watch + restart — role of ElasticManager
  (``fleet/elastic/manager.py:131``)
"""

from paddlebox_tpu.distributed.bootstrap import (initialize, is_initialized,
                                                 process_count, process_index)
from paddlebox_tpu.distributed.transport import TcpTransport, FileStore

__all__ = [
    "FileStore",
    "TcpTransport",
    "initialize",
    "is_initialized",
    "process_count",
    "process_index",
]
