"""Day/pass checkpoint protocol with atomic done-file publication.

Role of ``FleetUtil`` (reference ``python/paddle/fluid/incubate/fleet/
utils/fleet_util.py``): day/pass-addressed model output directories
(``save_batch_model`` :681 — day-level base under <out>/<day>/0;
``save_delta_model`` :704 — pass-level delta under <out>/<day>/<pass>),
append-only ``donefile.txt`` with one tab-separated line per published
model (``write_model_donefile`` :368: day, key, path, pass_id, flag), and
the online pass schedule (``get_online_pass_interval`` :1196 mapping a
day's time splits into passes).

TPU-first: the filesystem abstraction is pluggable (local posix here;
an HDFS/GCS client can swap in), publication is atomic
(write-temp + rename), and the donefile is the recovery index for
elastic restart (find last published day/pass, reload base+deltas).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional, Tuple

from paddlebox_tpu.core import faults, log


def get_online_pass_interval(hours: List[int], split_interval: int,
                             split_per_pass: int,
                             is_data_hourly_placed: bool = False
                             ) -> List[List[str]]:
    """Map a training day's time splits into pass groups (role of
    get_online_pass_interval, fleet_util.py:1196).

    hours: training-hour range, e.g. range(24); split_interval: minutes
    per data split; split_per_pass: splits consumed per pass. Returns one
    list of split names (HHMM or HH) per pass.
    """
    splits_per_day = 24 * 60 // split_interval
    pass_per_day = splits_per_day // split_per_pass
    lo, hi = hours[0], hours[-1]
    split_path = []
    start = 0
    for _ in range(splits_per_day):
        h, m = divmod(start, 60)
        if lo <= h <= hi:
            split_path.append(f"{h:02d}" if is_data_hourly_placed
                              else f"{h:02d}{m:02d}")
        start += split_interval
    return [split_path[i * split_per_pass:(i + 1) * split_per_pass]
            for i in range(pass_per_day)
            if split_path[i * split_per_pass:(i + 1) * split_per_pass]]


@dataclasses.dataclass
class DoneRecord:
    day: str
    key: int
    path: str
    pass_id: int

    def line(self) -> str:
        return f"{self.day}\t{self.key}\t{self.path}\t{self.pass_id}\t0"

    @staticmethod
    def parse(line: str) -> "DoneRecord":
        parts = line.strip().split("\t")
        return DoneRecord(day=parts[0], key=int(parts[1]), path=parts[2],
                          pass_id=int(parts[3]))


class CheckpointProtocol:
    """Day/pass addressed checkpoint tree with donefile index.

    Layout (mirrors the reference's output convention):
        <root>/<day>/0/        day-level base model
        <root>/<day>/<pass>/   pass-level delta model
        <root>/donefile.txt    append-only publication index
    """

    def __init__(self, root: str, *, donefile_name: str = "donefile.txt",
                 xbox_donefile_name: str = "xbox_donefile.txt",
                 is_rank0: bool = True):
        self.root = root.rstrip("/")
        self.donefile = os.path.join(self.root, donefile_name)
        # Separate index for serving-format (xbox) exports — consumers
        # are the online serving fleet, not training recovery (role of
        # write_xbox_donefile, fleet_util.py:520).
        self.xbox_donefile = os.path.join(self.root, xbox_donefile_name)
        self.is_rank0 = is_rank0
        os.makedirs(self.root, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def model_dir(self, day: str, pass_id: int = -1) -> str:
        sub = "0" if pass_id < 0 else str(pass_id)
        d = os.path.join(self.root, str(day), sub)
        os.makedirs(d, exist_ok=True)
        return d

    # -- donefile ----------------------------------------------------------

    def _read_records(self, donefile: str) -> List[DoneRecord]:
        if not os.path.exists(donefile):
            return []
        with open(donefile) as f:
            return [DoneRecord.parse(l) for l in f if l.strip()]

    def records(self) -> List[DoneRecord]:
        return self._read_records(self.donefile)

    def xbox_records(self) -> List[DoneRecord]:
        return self._read_records(self.xbox_donefile)

    def _publish_to(self, donefile: str, day: str, pass_id: int,
                    key: Optional[int], model_path: str) -> bool:
        if not self.is_rank0:
            return False
        day = str(day)
        pid = 0 if pass_id < 0 else pass_id
        recs = self._read_records(donefile)
        if any(r.day == day and r.pass_id == pid for r in recs):
            log.warning("donefile %s: %s/%s already published",
                        os.path.basename(donefile), day, pid)
            return False
        # The record key is publication METADATA (a human-readable id in
        # the donefile), never replayed training state: recovery orders
        # records by file position, not key.
        # graftlint: allow-replay(donefile key is metadata, not replayed state)
        rec = DoneRecord(day=day, key=key or int(time.time()),
                         path=model_path, pass_id=pid)
        tmp = donefile + ".tmp"
        with open(tmp, "w") as f:
            for r in recs:
                f.write(r.line() + "\n")
            f.write(rec.line() + "\n")
            # The donefile is the recovery INDEX: it must be durable
            # before it becomes visible, or a crash could recover
            # through a record pointing at data the page cache lost.
            f.flush()
            os.fsync(f.fileno())
        # The classic crash window: model files written, index not yet
        # swapped — recovery must resume from the PREVIOUS record.
        faults.faultpoint("checkpoint/publish")
        os.replace(tmp, donefile)  # atomic publication
        log.vlog(0, "%s: published %s/%s -> %s",
                 os.path.basename(donefile), day, pid, rec.path)
        return True

    def publish(self, day: str, pass_id: int = -1,
                key: Optional[int] = None) -> bool:
        """Atomically append a done record (rank 0 only; duplicate
        day/pass entries are skipped like write_model_donefile)."""
        return self._publish_to(self.donefile, str(day), pass_id, key,
                                self.model_dir(str(day), pass_id))

    def publish_xbox(self, day: str, pass_id: int = -1,
                     key: Optional[int] = None) -> bool:
        """Publish a serving-format export to the xbox done-file (role of
        write_xbox_donefile)."""
        return self._publish_to(self.xbox_donefile, str(day), pass_id, key,
                                self.model_dir(str(day), pass_id))

    def last_published(self) -> Optional[DoneRecord]:
        """Recovery entry point: newest published model (role of the
        donefile consumers in elastic restart)."""
        recs = self.records()
        return recs[-1] if recs else None

    def recovery_chain(self) -> Tuple[Optional[DoneRecord], List[DoneRecord]]:
        """(last day-level base, deltas after it, in order) — the load
        sequence for failover resume. With no base yet (a crash during
        the FIRST day), the chain is every published delta applied to
        the fresh store — deltas are self-contained row snapshots, so a
        day-1 mid-day failure still resumes at the last published pass
        instead of retraining the day from scratch."""
        recs = self.records()
        base = None
        base_i = -1
        for i, r in enumerate(recs):
            if r.pass_id == 0:
                base, base_i = r, i
        deltas = [r for r in recs[base_i + 1:] if r.pass_id != 0]
        return base, deltas
