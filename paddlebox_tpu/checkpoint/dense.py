"""Dense pytree checkpointing (role of paddle.save / save_persistables).

Flat-key npz format: pytree paths joined with ``/``; arrays fetched to
host. Restores into the template's structure, re-placing onto the
template leaves' shardings (so a restored model resumes with identical
layouts — including ZeRO-sharded optimizer state).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import jax
import numpy as np


def _flatten(tree: Any) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def save_pytree(tree: Any, path: str, *, step: int | None = None) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    flat = _flatten(tree)
    if step is not None:
        flat["__step__"] = np.asarray(step)
    tmp = path + ".tmp"
    np.savez(tmp, **flat)
    # np.savez appends .npz to the name it opens.
    os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp, path)


def load_pytree(template: Any, path: str) -> Any:
    """Restore into template's structure + shardings. Returns (tree, step)."""
    data = np.load(path)
    flat_t = _flatten(template)
    missing = [k for k in flat_t if k not in data.files]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}")
    leaves_paths = jax.tree_util.tree_flatten_with_path(template)
    restored = []
    for path_keys, leaf in leaves_paths[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path_keys)
        arr = data[key]
        if hasattr(leaf, "sharding") and leaf.sharding is not None:
            arr = jax.device_put(arr, leaf.sharding)
        restored.append(arr)
    tree = jax.tree_util.tree_unflatten(leaves_paths[1], restored)
    step = int(data["__step__"]) if "__step__" in data.files else None
    return tree, step
