"""Dense pytree checkpointing (role of paddle.save / save_persistables).

Flat-key npz format: pytree paths joined with ``/``; arrays fetched to
host. Restores into the template's structure, re-placing onto the
template leaves' shardings (so a restored model resumes with identical
layouts — including ZeRO-sharded optimizer state).

Crash consistency: the write is fsync'd before the atomic rename (a
power cut after ``os.replace`` must not leave a hole where the data
should be), and the payload carries a CRC32 over every array's bytes so
``load_pytree`` can tell a torn/corrupt file from a good one —
:class:`CheckpointCorruptError` lets recovery skip to an older record
instead of dying inside the restart it exists to serve.
"""

from __future__ import annotations

import os
import zipfile
import zlib
from typing import Any, Dict, Tuple

import jax
import numpy as np


class CheckpointCorruptError(Exception):
    """The checkpoint file is truncated or its payload fails the CRC —
    recovery should warn and fall back to an older record."""


def _flatten(tree: Any) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _payload_crc32(flat: Dict[str, np.ndarray]) -> int:
    """CRC32 over every array's raw bytes in sorted key order — the
    same walk at save and load, so any flipped/zeroed payload byte (not
    just zip-structure truncation) fails verification."""
    crc = 0
    for key in sorted(flat):
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(flat[key]).tobytes(), crc)
    return crc & 0xFFFFFFFF


def save_pytree(tree: Any, path: str, *, step: int | None = None) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    flat = _flatten(tree)
    if step is not None:
        flat["__step__"] = np.asarray(step)
    flat["__crc32__"] = np.asarray(_payload_crc32(
        {k: v for k, v in flat.items() if k != "__crc32__"}),
        dtype=np.uint64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
        # Durability before visibility: flush + fsync the payload, THEN
        # rename. os.replace alone only orders the directory entry — a
        # crash could publish a name pointing at unflushed bytes.
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _verify_crc(data) -> None:
    if "__crc32__" not in data.files:
        return  # pre-CRC checkpoint: structure checks still apply
    want = int(data["__crc32__"])
    try:
        flat = {k: data[k] for k in data.files if k != "__crc32__"}
    except (zipfile.BadZipFile, OSError, EOFError, ValueError) as e:
        raise CheckpointCorruptError(
            f"checkpoint payload unreadable: {e}") from e
    got = _payload_crc32(flat)
    if got != want:
        raise CheckpointCorruptError(
            f"checkpoint CRC mismatch: payload {got:#010x} != "
            f"recorded {want:#010x} (torn or corrupted write)")


def load_pytree(template: Any, path: str) -> Tuple[Any, Any]:
    """Restore into template's structure + shardings. Returns (tree, step).

    Raises :class:`CheckpointCorruptError` for a truncated or
    bit-flipped file (including ``zipfile.BadZipFile`` from a torn npz),
    ``KeyError`` for a structure mismatch — both are skip-to-older-record
    cases for recovery, distinct from a genuine IO error."""
    try:
        data = np.load(path)
    except (zipfile.BadZipFile, ValueError, EOFError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path} is not a readable npz: {e}") from e
    _verify_crc(data)
    flat_t = _flatten(template)
    missing = [k for k in flat_t if k not in data.files]
    if missing:
        raise KeyError(f"checkpoint missing keys: {missing[:5]}")
    leaves_paths = jax.tree_util.tree_flatten_with_path(template)
    restored = []
    for path_keys, leaf in leaves_paths[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path_keys)
        arr = data[key]
        if hasattr(leaf, "sharding") and leaf.sharding is not None:
            arr = jax.device_put(arr, leaf.sharding)
        restored.append(arr)
    tree = jax.tree_util.tree_unflatten(leaves_paths[1], restored)
    step = int(data["__step__"]) if "__step__" in data.files else None
    return tree, step
