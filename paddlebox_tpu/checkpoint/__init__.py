"""Checkpointing: dense pytree snapshots + the day/pass production protocol.

Roles (SURVEY.md §5 "Checkpoint / resume"):
- dense: ``paddle.save/load`` / ``save_persistables`` → :mod:`dense` pytree
  snapshots (npz, jax-array aware, orbax-compatible layout on disk)
- sparse: base+delta lives with the FeatureStore
  (``embedding/store.py``, role of SaveBase/SaveDelta)
- production protocol: day/pass-addressed output dirs with atomic done-file
  publication and online pass scheduling — role of ``FleetUtil``
  (``fleet_util.py:368-1196`` save_batch_model / save_delta_model /
  write_model_donefile / get_online_pass_interval)
"""

from paddlebox_tpu.checkpoint.dense import load_pytree, save_pytree
from paddlebox_tpu.checkpoint.protocol import (
    CheckpointProtocol,
    get_online_pass_interval,
)

__all__ = [
    "CheckpointProtocol",
    "get_online_pass_interval",
    "load_pytree",
    "save_pytree",
]
