"""Sequence pooling + CVM ops over CSR slot batches.

Role of the fused seqpool+CVM CUDA family
(``operators/fused/fused_seqpool_cvm_op.cu`` and python wrapper
``python/paddle/fluid/contrib/layers/nn.py:1746`` ``fused_seqpool_cvm``)
and ``cvm_op`` (``operators/cvm_op.cu``): per-instance sum-pool of each
slot's embedding sequence, then the "continuous value model" normalization
that replaces the leading [show, click] columns with
[log(show+1), log(click+1) - log(show+1)].

TPU-first: pooling is ``jax.ops.segment_sum`` over the static CSR segment
ids (padding rows accumulate into a discard row) and the CVM transform is
elementwise — XLA fuses the two, reproducing the "fused" property of the
reference kernel without a hand-written kernel.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def seqpool(values: jax.Array, segments: jax.Array, num_rows: int,
            mode: str = "sum") -> jax.Array:
    """Pool variable-length per-instance sequences to one row each.

    values [n, ...]; segments [n] row ids in [0, num_rows] where num_rows
    marks padding (discard row). Returns [num_rows, ...].
    """
    if mode not in ("sum", "mean", "sqrtn"):
        raise ValueError(f"unknown seqpool mode {mode!r}")
    pooled = jax.ops.segment_sum(values, segments, num_segments=num_rows + 1)
    pooled = pooled[:num_rows]
    if mode == "sum":
        return pooled
    ones = jnp.ones(values.shape[:1], values.dtype)
    counts = jax.ops.segment_sum(ones, segments, num_segments=num_rows + 1)
    counts = jnp.maximum(counts[:num_rows], 1.0)
    counts = counts.reshape(counts.shape + (1,) * (pooled.ndim - 1))
    if mode == "mean":
        return pooled / counts
    return pooled / jnp.sqrt(counts)


def continuous_value_model(x: jax.Array, *, use_cvm: bool = True) -> jax.Array:
    """CVM normalization (role of cvm_op, operators/cvm_op.cu).

    x [B, 2 + D] with leading [show, click] columns. use_cvm=True keeps
    width (log-transformed counters); False strips the two columns —
    matching the reference op's two modes.
    """
    show = x[:, 0]
    click = x[:, 1]
    rest = x[:, 2:]
    if not use_cvm:
        return rest
    log_show = jnp.log(show + 1.0)
    ctr = jnp.log(click + 1.0) - log_show
    return jnp.concatenate([log_show[:, None], ctr[:, None], rest], axis=-1)


def fused_seqpool_cvm(emb: jax.Array, show: jax.Array, click: jax.Array,
                      segments: jax.Array, num_rows: int, *,
                      use_cvm: bool = True, mode: str = "sum",
                      clip_value: Optional[float] = None) -> jax.Array:
    """Fused sequence-pool + CVM for one slot.

    emb [n, D] pulled embeddings; show/click [n] per-feature counters from
    the sparse pull; segments [n] CSR row ids (num_rows = discard). Output
    [num_rows, 2 + D] when use_cvm else [num_rows, D].

    Mirrors fused_seqpool_cvm's contract where the embedding's first two
    channels carry show/click — here they arrive as separate pull outputs
    and are concatenated pre-pool, which XLA fuses into one pass.
    """
    if clip_value is not None:
        emb = jnp.clip(emb, -clip_value, clip_value)
    x = jnp.concatenate([show[:, None], click[:, None], emb], axis=-1)
    pooled = seqpool(x, segments, num_rows, mode=mode)
    return continuous_value_model(pooled, use_cvm=use_cvm)
