"""Fused CTR ops (TPU lowerings of the reference's custom CUDA op family).

Role of ``paddle/fluid/operators/fused/`` (SURVEY.md §2.2 "Fused CTR ops"):
``fused_seqpool_cvm`` + variants, ``cvm_op``, ``rank_attention``. On TPU
these are expressed as XLA-fusable segment ops / batched matmuls — XLA fuses
the elementwise CVM transform into the pooling reduction, so no hand kernel
is needed for the memory-bound path; the MXU-bound rank-attention is a
batched gather + dot_general.
"""

from paddlebox_tpu.ops.seqpool import (
    seqpool,
    fused_seqpool_cvm,
    continuous_value_model,
)
from paddlebox_tpu.ops.rank_attention import rank_attention

__all__ = [
    "continuous_value_model",
    "fused_seqpool_cvm",
    "rank_attention",
    "seqpool",
]
