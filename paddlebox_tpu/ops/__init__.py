"""Fused CTR ops (TPU lowerings of the reference's custom CUDA op family).

Role of ``paddle/fluid/operators/fused/`` (SURVEY.md §2.2 "Fused CTR ops"):
``fused_seqpool_cvm`` + its variant zoo (conv/pcoc/tradew/credit/
diff_thres), ``fused_concat``/``fusion_seqpool_cvm_concat``, ``cvm_op``,
``rank_attention``/``rank_attention2``. On TPU these are expressed as
XLA-fusable segment ops / batched matmuls — XLA fuses the elementwise CVM
transform into the pooling reduction, so no hand kernel is needed for the
memory-bound path; the MXU-bound rank-attention is a batched gather +
dot_general.
"""

from paddlebox_tpu.ops.seqpool import (
    seqpool,
    fused_seqpool_cvm,
    continuous_value_model,
)
from paddlebox_tpu.ops.seqpool_variants import (
    fused_seqpool_cvm_full,
    fused_seqpool_cvm_with_conv,
    fused_seqpool_cvm_with_credit,
    fused_seqpool_cvm_with_pcoc,
    fused_seqpool_cvm_tradew,
    fused_seqpool_cvm_with_diff_thres,
    fused_concat,
    fusion_seqpool_cvm_concat,
    quant_filter_mask,
    quantize,
)
from paddlebox_tpu.ops.rank_attention import rank_attention, rank_attention2
from paddlebox_tpu.ops.data_norm import data_norm_apply, data_norm_init

__all__ = [
    "continuous_value_model",
    "data_norm_apply",
    "data_norm_init",
    "fused_concat",
    "fused_seqpool_cvm",
    "fused_seqpool_cvm_full",
    "fused_seqpool_cvm_tradew",
    "fused_seqpool_cvm_with_conv",
    "fused_seqpool_cvm_with_credit",
    "fused_seqpool_cvm_with_diff_thres",
    "fused_seqpool_cvm_with_pcoc",
    "fusion_seqpool_cvm_concat",
    "quant_filter_mask",
    "quantize",
    "rank_attention",
    "rank_attention2",
    "seqpool",
]
