"""DataNorm — global-statistics feature normalization for CTR dense paths.

Role of the reference's ``data_norm`` op (``data_norm_op.cc:292`` CPU
kernel, ``data_norm_op.cu:90`` KernelUpdateParam, python surface
``fluid/layers/nn.py:3490``): normalize each feature channel by running
GLOBAL statistics — not per-batch moments like BatchNorm — maintained as
three per-channel accumulators (size, sum, square_sum) that decay by
``summary_decay_rate`` and absorb each batch's contribution. PaddleBox
CTR models run it over the concatenated dense/show-click features.

TPU-first shape: a pure function over an explicit stats pytree —
``(y, new_stats) = data_norm_apply(stats, x, ...)`` with the stats
update fused into the same jitted program (no mutable parameter hooks),
and ``sync_stats`` realized as a ``lax.psum`` over the dp mesh axis
(role of the NCCL allreduce in ``data_norm_op.cu:208``).

Semantics mirrored from the reference:

- ``means = sum / size``; ``scales = sqrt(size / square_sum)``;
  ``y = (x - means) * scales`` (optionally ``* scale_w + bias``).
- ``slot_dim > 0``: x is a concatenation of per-slot chunks whose first
  element is the show count; chunks with show ~ 0 (new/empty slot)
  output zeros and are EXCLUDED from the stats update
  (``data_norm_op.cc:341-357,686-718``).
- batch deltas: without slot_dim ``(N, sum(x), sum((x-mean)^2) + N*eps)``;
  with slot_dim the per-channel deltas are normalized to a size of 1
  (``d_sum /= d_size; d_sq = d_sq/d_size + d_size*eps; d_size = 1``).
- update: ``stats = stats * decay + delta`` (KernelUpdateParam).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

_MIN_PRECISION = 1e-7


def normalize_dense_and_strip(params, dense_feats, *, slot_dim: int = -1):
    """Shared train-forward/serving helper: if ``params`` carries a
    ``data_norm`` stats entry, normalize ``dense_feats`` by it (f32,
    no stats update) and return (params-without-the-entry, dense).
    One implementation for both sides — trainer and predictor MUST
    normalize identically or served probabilities drift from training."""
    if not (isinstance(params, dict) and "data_norm" in params):
        return params, dense_feats
    if dense_feats is not None:
        dense_feats, _ = data_norm_apply(params["data_norm"], dense_feats,
                                         slot_dim=slot_dim, train=False)
    return {k: v for k, v in params.items() if k != "data_norm"}, \
        dense_feats


def data_norm_init(c: int, *, batch_size_default: float = 1e4,
                   batch_sum_default: float = 0.0,
                   batch_square_sum_default: float = 1e4,
                   enable_scale_and_shift: bool = False
                   ) -> Dict[str, jax.Array]:
    """Per-channel stats (reference defaults make the initial transform
    the identity: mean 0, scale sqrt(1e4/1e4) = 1)."""
    out = {
        "batch_size": jnp.full((c,), batch_size_default, jnp.float32),
        "batch_sum": jnp.full((c,), batch_sum_default, jnp.float32),
        "batch_square_sum": jnp.full((c,), batch_square_sum_default,
                                     jnp.float32),
    }
    if enable_scale_and_shift:
        out["scale_w"] = jnp.ones((c,), jnp.float32)
        out["bias"] = jnp.zeros((c,), jnp.float32)
    return out


def data_norm_apply(stats: Dict[str, jax.Array], x: jax.Array, *,
                    slot_dim: int = -1, epsilon: float = 1e-4,
                    summary_decay_rate: float = 0.9999999,
                    train: bool = True,
                    axis_name: Optional[str] = None
                    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """x [N, C] -> (y [N, C], updated stats).

    ``axis_name`` syncs the batch deltas across that mesh axis before
    the decayed update (sync_stats role). Stats are state, not
    gradients — thread them like BN running stats; gradients flow
    through y w.r.t. x as a plain affine transform.
    """
    n, c = x.shape
    xf = x.astype(jnp.float32)
    # The three accumulators are updated ONLY by the decayed summary
    # below (KernelUpdateParam role) — never by SGD, so no cotangent may
    # flow into them through y. scale_w/bias (when enabled) stay
    # differentiable: the reference trains those as ordinary parameters.
    size = lax.stop_gradient(stats["batch_size"])
    means = lax.stop_gradient(stats["batch_sum"]) / size
    scales = jnp.sqrt(size / lax.stop_gradient(stats["batch_square_sum"]))
    y = (xf - means) * scales
    enable_ss = "scale_w" in stats
    if enable_ss:
        y = y * stats["scale_w"] + stats["bias"]

    valid = None
    if slot_dim > 0:
        if c % slot_dim:
            raise ValueError(f"C={c} not divisible by slot_dim={slot_dim}")
        # Chunk k covers channels [k*slot_dim, (k+1)*slot_dim); its show
        # count sits at the chunk's first channel. The mask drives the
        # stats update REGARDLESS of scale/shift (data_norm_op.cc:686
        # applies the show-skip to the stat deltas unconditionally);
        # only the output zeroing is the not-enable_ss behavior
        # (data_norm_op.cc:341-357).
        show = xf[:, ::slot_dim]                       # [N, C/slot_dim]
        alive = jnp.abs(show) >= _MIN_PRECISION       # [N, C/slot_dim]
        valid = jnp.repeat(alive, slot_dim, axis=1)   # [N, C]
        if not enable_ss:
            y = jnp.where(valid, y, 0.0)
    y = y.astype(x.dtype)

    if not train:
        return y, stats

    # Batch stat deltas (the reference computes these in the grad op —
    # they are accumulators, not true gradients; lax.stop_gradient keeps
    # autodiff from routing cotangents into the stats path).
    xs = lax.stop_gradient(xf)
    if valid is not None:
        v = valid.astype(jnp.float32)
        d_size = jnp.sum(v, axis=0)
        d_sum = jnp.sum(xs * v, axis=0)
        d_sq = jnp.sum((xs - means) ** 2 * v, axis=0)
        if axis_name is not None:
            d_size = lax.psum(d_size, axis_name)
            d_sum = lax.psum(d_sum, axis_name)
            d_sq = lax.psum(d_sq, axis_name)
        # Normalize to per-sample scale (data_norm_op.cc:708-716);
        # channels that saw no live chunk contribute nothing.
        seen = d_size >= 1.0
        d_sum = jnp.where(seen, d_sum / jnp.maximum(d_size, 1.0), 0.0)
        d_sq = jnp.where(
            seen,
            d_sq / jnp.maximum(d_size, 1.0) + d_size * epsilon, 0.0)
        d_size = jnp.where(seen, 1.0, 0.0)
    else:
        d_size = jnp.full((c,), float(n), jnp.float32)
        d_sum = jnp.sum(xs, axis=0)
        d_sq = jnp.sum((xs - means) ** 2, axis=0) + n * epsilon
        if axis_name is not None:
            d_size = lax.psum(d_size, axis_name)
            d_sum = lax.psum(d_sum, axis_name)
            d_sq = lax.psum(d_sq, axis_name)

    dr = summary_decay_rate
    new_stats = dict(stats)
    new_stats["batch_size"] = size * dr + d_size
    new_stats["batch_sum"] = stats["batch_sum"] * dr + d_sum
    new_stats["batch_square_sum"] = stats["batch_square_sum"] * dr + d_sq
    return y, new_stats
