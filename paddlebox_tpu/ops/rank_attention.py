"""Rank attention op for rank-aware CTR models.

Role of ``rank_attention_op`` (``operators/rank_attention_op.cc:28-76``,
CUDA kernels ``operators/rank_attention.cu.h:28-91``): every instance has a
rank (position bucket) and up to ``max_rank`` (faster_rank, peer_index)
pairs in ``rank_offset``; the op gathers each peer's feature row, selects a
parameter block indexed by the (instance_rank, faster_rank) pair, and
contracts — Out[b] = Σ_k X[index_k] @ P[(lower_b, faster_k)].

TPU-first: the reference expands input and params into helper buffers then
runs a blocked GEMM; here the whole thing is one gather + one einsum that
XLA maps onto the MXU, with validity masking instead of zero-fill buffers.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rank_attention(x: jax.Array, rank_offset: jax.Array,
                   rank_param: jax.Array, *, max_rank: int
                   ) -> Tuple[jax.Array, jax.Array]:
    """Apply rank attention.

    x           [B, F]            instance features
    rank_offset [B, 1 + 2*max_rank] int32 — col 0: 1-based instance rank
                (0 = invalid); then (faster_rank_k, peer_index_k) pairs,
                faster_rank 1-based, peer_index row into x
    rank_param  [max_rank * max_rank, F, C] — block (lower*max_rank +
                faster) is the [F, C] weight for that rank pair

    Returns (out [B, C], ins_rank [B] float32) matching the reference's
    Out / InsRank outputs.
    """
    b, f = x.shape
    k = max_rank
    if rank_offset.shape[1] != 1 + 2 * k:
        raise ValueError(
            f"rank_offset has {rank_offset.shape[1]} cols, expected {1 + 2*k}")
    if rank_param.shape[0] != k * k or rank_param.shape[1] != f:
        raise ValueError(
            f"rank_param shape {rank_param.shape} != ({k*k}, {f}, C)")

    lower = rank_offset[:, 0] - 1                       # [B]
    faster = rank_offset[:, 1::2] - 1                   # [B, K]
    index = rank_offset[:, 2::2]                        # [B, K]
    valid = (lower >= 0)[:, None] & (faster >= 0)       # [B, K]

    safe_index = jnp.where(valid, index, 0)
    xin = x[safe_index]                                 # [B, K, F]
    xin = jnp.where(valid[..., None], xin, 0.0)

    block = lower[:, None] * k + faster                 # [B, K]
    safe_block = jnp.clip(jnp.where(valid, block, 0), 0, k * k - 1)
    psel = rank_param[safe_block]                       # [B, K, F, C]
    psel = jnp.where(valid[..., None, None], psel, 0.0)

    out = jnp.einsum("bkf,bkfc->bc", xin, psel,
                     preferred_element_type=jnp.float32)
    return out, rank_offset[:, 0].astype(jnp.float32)


def rank_attention2(x: jax.Array, rank_offset: jax.Array,
                    rank_param: jax.Array, *, max_rank: int) -> jax.Array:
    """``rank_attention2`` (``rank_attention_op.cc:182``, CUDA
    ``rank_attention_op.cu:297``): same contraction as rank_attention but
    the parameter comes flat as [max_rank*max_rank*F, C] and only Out is
    produced (the reference's grad flows to RankParam only; here jax.grad
    gives exact grads for both inputs and callers drop what they don't
    use)."""
    b, f = x.shape
    k = max_rank
    if rank_param.shape[0] != k * k * f:
        raise ValueError(
            f"rank_param rows {rank_param.shape[0]} != max_rank^2*F {k*k*f}")
    out, _ = rank_attention(
        x, rank_offset, rank_param.reshape(k * k, f, rank_param.shape[1]),
        max_rank=max_rank)
    return out
