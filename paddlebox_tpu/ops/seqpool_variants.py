"""The full fused seqpool+CVM op family, TPU-style.

Role of the CUDA variant zoo under ``operators/fused/``:
``fused_seqpool_cvm_with_conv_op.cu``, ``_with_pcoc_op.cu``,
``_tradew_op.cu``, ``_with_credit_op.cu``, ``_with_diff_thres_op.cu``,
``fused_concat_op.cu``, ``fusion_seqpool_cvm_concat_op.cc`` (python
wrappers ``python/paddle/fluid/contrib/layers/nn.py:1746-2085``).

Each reference kernel pair is (seqpool with optional token filter/quant)
followed by a CVM-style transform of the leading counter columns. Here
both halves are jnp expressions — ``segment_sum`` + elementwise — which
XLA fuses into one pass over the batch, reproducing the "fused" property
without bespoke kernels; every function is jit/grad-safe.

Conventions (matching ops/seqpool.py): per-slot CSR inputs ``x [n, C]``
(leading counter columns then embedding dims), ``segments [n]`` row ids in
``[0, num_rows]`` with ``num_rows`` = padding discard row.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from paddlebox_tpu.ops.seqpool import seqpool


def _log1p(x):
    return jnp.log(x + 1.0)


def quant_filter_mask(show: jax.Array, click: jax.Array, *,
                      show_coeff: float = 0.2, clk_coeff: float = 1.0,
                      threshold: float = 0.96) -> jax.Array:
    """Per-token keep mask: drop tokens whose confidence score
    ``(show-click)*show_coeff + click*clk_coeff`` is under threshold
    (FusedSeqpoolKernelQuantFilter, fused_seqpool_cvm_op.cu:238-244)."""
    score = (show - click) * show_coeff + click * clk_coeff
    return score >= threshold


def quantize(emb: jax.Array, quant_ratio: int) -> jax.Array:
    """Pull-value quantization ``trunc(v*q + 0.5)/q`` — the C int-cast
    truncates toward zero (fused_seqpool_cvm_op.cu:247)."""
    if quant_ratio <= 0:
        return emb
    return jnp.trunc(emb * quant_ratio + 0.5) / float(quant_ratio)


def _pool_with_filter(x: jax.Array, segments: jax.Array, num_rows: int, *,
                      cvm_offset: int, need_filter: bool, show_coeff: float,
                      clk_coeff: float, threshold: float,
                      quant_ratio: int) -> jax.Array:
    """Shared first half: optional token filter + embed quantization, then
    sum-pool counters and embeddings together."""
    cols = x
    if quant_ratio > 0:
        cols = jnp.concatenate(
            [x[:, :cvm_offset], quantize(x[:, cvm_offset:], quant_ratio)],
            axis=-1)
    if need_filter:
        keep = quant_filter_mask(x[:, 0], x[:, 1], show_coeff=show_coeff,
                                 clk_coeff=clk_coeff, threshold=threshold)
        cols = cols * keep[:, None].astype(cols.dtype)
    return seqpool(cols, segments, num_rows, mode="sum")


def fused_seqpool_cvm_full(x: jax.Array, segments: jax.Array, num_rows: int, *,
                           use_cvm: bool = True, need_filter: bool = False,
                           show_coeff: float = 0.2, clk_coeff: float = 1.0,
                           threshold: float = 0.96, quant_ratio: int = 0,
                           cvm_offset: int = 2) -> jax.Array:
    """Base op with the full attr surface (fused_seqpool_cvm_op.cc:125-141):
    token confidence filter + quantization + seqpool + CVM.

    x [n, cvm_offset + D] with leading [show, click]. Output
    [num_rows, cvm_offset + D] when use_cvm else [num_rows, D].
    """
    pooled = _pool_with_filter(
        x, segments, num_rows, cvm_offset=cvm_offset,
        need_filter=need_filter, show_coeff=show_coeff, clk_coeff=clk_coeff,
        threshold=threshold, quant_ratio=quant_ratio)
    if not use_cvm:
        return pooled[:, cvm_offset:]
    show, click = pooled[:, 0], pooled[:, 1]
    lead = [_log1p(show), _log1p(click) - _log1p(show)]
    return jnp.concatenate(
        [jnp.stack(lead, axis=-1), pooled[:, 2:]], axis=-1)


def fused_seqpool_cvm_with_conv(x: jax.Array, segments: jax.Array,
                                num_rows: int, *, use_cvm: bool = True,
                                show_filter: bool = False) -> jax.Array:
    """Conv-signal variant (fused_seqpool_cvm_with_conv_op.cu:57-140):
    x [n, 3 + D] leading [show, click, conv]. Output leading columns are
    [log(show+1), log(click+1), log(conv+1)-log(click+1)]; ``show_filter``
    drops the show column (join phase feeds click-only);
    ``use_cvm=False`` strips all three."""
    cvm_offset = 3
    pooled = seqpool(x, segments, num_rows, mode="sum")
    if not use_cvm:
        return pooled[:, cvm_offset:]
    show, click, conv = pooled[:, 0], pooled[:, 1], pooled[:, 2]
    lead = [_log1p(click), _log1p(conv) - _log1p(click)]
    if not show_filter:
        lead = [_log1p(show)] + lead
    return jnp.concatenate(
        [jnp.stack(lead, axis=-1), pooled[:, cvm_offset:]], axis=-1)


def fused_seqpool_cvm_with_credit(x: jax.Array, segments: jax.Array,
                                  num_rows: int, *, cvm_offset: int = 4,
                                  use_cvm: bool = True,
                                  show_filter: bool = False) -> jax.Array:
    """Credit variant (fused_seqpool_cvm_with_credit_op.cu): all
    ``cvm_offset`` leading counters [show, click, conv, credit] map through
    log(x+1); show_filter drops the show column."""
    pooled = seqpool(x, segments, num_rows, mode="sum")
    if not use_cvm:
        return pooled[:, cvm_offset:]
    lo = 1 if show_filter else 0
    lead = _log1p(pooled[:, lo:cvm_offset])
    return jnp.concatenate([lead, pooled[:, cvm_offset:]], axis=-1)


def fused_seqpool_cvm_with_pcoc(x: jax.Array, segments: jax.Array,
                                num_rows: int, *, cvm_offset: int = 7,
                                pclk_num: int = 3, use_cvm: bool = True,
                                need_filter: bool = False,
                                show_coeff: float = 0.2,
                                clk_coeff: float = 1.0,
                                threshold: float = 0.96,
                                quant_ratio: int = 0) -> jax.Array:
    """PCOC (predicted-click-over-click calibration) variant
    (fused_seqpool_cvm_with_pcoc_op.cu:87-160).

    Input columns: [show, click, q, d, p_1..p_pclk_num, emb...] with
    ``cvm_offset = 4 + pclk_num`` leading counters. Output leading columns:
      [ log(show+1), log(click+1)-log(show+1),
        log(p_i+1)-log(q+1) ...,            (pclk_num cols)
        log(p_i+1)-log(d+1) ... ]           (pclk_num cols)
    followed by the embedding columns.
    """
    if cvm_offset != 4 + pclk_num:
        raise ValueError(
            f"pcoc layout needs cvm_offset == 4 + pclk_num, got "
            f"{cvm_offset} vs pclk_num={pclk_num}")
    pooled = _pool_with_filter(
        x, segments, num_rows, cvm_offset=cvm_offset,
        need_filter=need_filter, show_coeff=show_coeff, clk_coeff=clk_coeff,
        threshold=threshold, quant_ratio=quant_ratio)
    if not use_cvm:
        return pooled[:, cvm_offset:]
    show, click = pooled[:, 0], pooled[:, 1]
    q, d = pooled[:, 2], pooled[:, 3]
    p = pooled[:, 4:4 + pclk_num]
    lead = jnp.concatenate([
        _log1p(show)[:, None],
        (_log1p(click) - _log1p(show))[:, None],
        _log1p(p) - _log1p(q)[:, None],
        _log1p(p) - _log1p(d)[:, None],
    ], axis=-1)
    return jnp.concatenate([lead, pooled[:, cvm_offset:]], axis=-1)


def fused_seqpool_cvm_tradew(x: jax.Array, segments: jax.Array,
                             num_rows: int, *, trade_num: int,
                             trade_id: int = -1, cvm_offset: int = 2,
                             use_cvm: bool = True) -> jax.Array:
    """Trade-weighted variant (fused_seqpool_cvm_tradew_op.cu:34-130).

    Input columns: [show, click, w_0..w_{trade_num-1}, emb...]. With
    ``trade_id >= 0`` each token's embedding columns are scaled by its
    trade weight ``w[trade_id]`` before pooling; counters pool unweighted.
    Then the base CVM transform.
    """
    counters = x[:, :cvm_offset]
    emb = x[:, cvm_offset + trade_num:]
    if trade_id >= 0:
        w = x[:, cvm_offset + trade_id]
        emb = emb * w[:, None]
    pooled = seqpool(jnp.concatenate([counters, emb], axis=-1),
                     segments, num_rows, mode="sum")
    if not use_cvm:
        return pooled[:, cvm_offset:]
    show, click = pooled[:, 0], pooled[:, 1]
    lead = jnp.stack([_log1p(show), _log1p(click) - _log1p(show)], axis=-1)
    return jnp.concatenate([lead, pooled[:, cvm_offset:]], axis=-1)


def fused_seqpool_cvm_with_diff_thres(
        x: jax.Array, segments: jax.Array, num_rows: int, *,
        slot_threshold: float, use_cvm: bool = True,
        need_filter: bool = True, show_coeff: float = 0.2,
        clk_coeff: float = 1.0, quant_ratio: int = 0,
        clk_filter: bool = False) -> jax.Array:
    """Per-slot-threshold variant (fused_seqpool_cvm_with_diff_thres_op.cu:
    92-111 ``xbox_diff_thres_filter`` path): the confidence filter uses the
    calling slot's own threshold instead of one global value; ``clk_filter``
    drops the show column from the CVM output (click-only join input)."""
    pooled = _pool_with_filter(
        x, segments, num_rows, cvm_offset=2, need_filter=need_filter,
        show_coeff=show_coeff, clk_coeff=clk_coeff,
        threshold=slot_threshold, quant_ratio=quant_ratio)
    if not use_cvm:
        return pooled[:, 2:]
    show, click = pooled[:, 0], pooled[:, 1]
    ctr = _log1p(click) - _log1p(show)
    lead = ([ctr] if clk_filter else [_log1p(show), ctr])
    return jnp.concatenate(
        [jnp.stack(lead, axis=-1), pooled[:, 2:]], axis=-1)


def fused_concat(xs: Sequence[jax.Array], *, offset: int = 0,
                 length: int = -1) -> jax.Array:
    """Feature-dim concat of per-slot outputs with optional column slice
    (role of ``fused_concat_op.cu``: concatenates a [offset, offset+length)
    column window from every input). XLA lowers this to one fused copy."""
    if length >= 0:
        xs = [x[:, offset:offset + length] for x in xs]
    elif offset:
        xs = [x[:, offset:] for x in xs]
    return jnp.concatenate(list(xs), axis=-1)


def fusion_seqpool_cvm_concat(xs: Sequence[jax.Array],
                              segments: Sequence[jax.Array], num_rows: int, *,
                              use_cvm: bool = True) -> jax.Array:
    """Multi-slot seqpool+CVM then concat (role of
    ``fusion_seqpool_cvm_concat_op.cc``): equivalent to the per-slot base
    op followed by fused_concat, expressed so XLA schedules all slots'
    segment-sums in one fusion."""
    outs = [fused_seqpool_cvm_full(x, seg, num_rows, use_cvm=use_cvm)
            for x, seg in zip(xs, segments)]
    return jnp.concatenate(outs, axis=-1)
